"""Filer HTTP server (weed/server/filer_server.go + handlers).

Public API mirrors the reference's filer HTTP surface:
  POST/PUT /path/to/file     upload (auto-chunked)
  GET      /path/to/file     ranged read
  GET      /path/to/dir/     JSON listing (?limit=&lastFileName=&prefix=)
  DELETE   /path             (?recursive=true for directories)
  HEAD     /path             existence/size probe
plus JSON-over-HTTP mirrors of key filer.proto RPCs:
  GET  /__meta__/lookup?path=         <- filer.proto LookupDirectoryEntry
  POST /__meta__/rename               <- filer.proto AtomicRenameEntry
  GET  /__meta__/events?sinceNs=      <- SubscribeMetadata (poll form)
"""

from __future__ import annotations

from ..filer import Entry, Filer
from ..filer.filer_store import SqliteStore
from .httpd import HttpServer, Request, parse_range


def cluster_statistics(master: str, collection: str = "") -> dict:
    """Aggregate used/total/file counts from the master topology —
    the filer Statistics feed (filer.proto Statistics) shared by the
    HTTP route, the gRPC servicer, and the mount's quota poll.
    Raises OSError when the master is unreachable."""
    from .httpd import http_json
    vl = http_json("GET", f"{master}/dir/status", timeout=30)
    cs = http_json("GET", f"{master}/cluster/status", timeout=30)
    used = files = max_count = 0
    for dc in vl.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            for node in rack.get("nodes", []):
                max_count += node.get("maxVolumeCount", 0)
                for v in node.get("volumes", []):
                    if collection and \
                            v.get("collection") != collection:
                        continue
                    used += v.get("size", 0)
                    files += v.get("fileCount", 0)
    total = cs.get("volumeSizeLimit", 0) * max(max_count, 1)
    return {"totalSize": total, "usedSize": used,
            "fileCount": files}


class FilerServer:
    def __init__(self, master: str, host: str = "127.0.0.1",
                 port: int = 0, store_path: str = ":memory:",
                 collection: str = "", replication: str = "",
                 meta_log_dir: str | None = None,
                 store_type: str = "sqlite",
                 notification: str = "",
                 lock_peers: "list[str] | None" = None,
                 reuse_port: bool = False):
        self._notification_spec = notification
        self._notifier = None
        self._lock_peers = lock_peers or []
        # bind the listener FIRST: the default metalog dir below needs
        # the RESOLVED port so two co-located filers derive distinct
        # dirs (binding also fails fast on a taken port, before any
        # store file is touched).  reuse_port: the pre-fork worker
        # mode — N filer processes share this listener, one sqlite
        # WAL store, and one metalog dir (exactly the supported
        # two-filers-one-store topology, multiplied)
        self.http = HttpServer(host, port, reuse_port=reuse_port)
        try:
            if meta_log_dir is None and store_path != ":memory:" and \
                    store_type in ("sqlite", "lsm"):
                # persist the metadata log beside the store by default —
                # subscribers must survive a filer restart
                # (filer_notify_append.go).  Only for LOCAL-path stores:
                # a redis/elastic store_path is a network ADDRESS, and
                # "host:port.metalog" would litter the working directory
                meta_log_dir = store_path + ".metalog"
            elif meta_log_dir is None and store_type in ("redis",
                                                         "elastic"):
                # per-address uniqueness (two filers on different redis
                # servers must not interleave one log) is NOT enough: two
                # CO-LOCATED filers sharing one redis/ES server would
                # still derive the same dir and interleave their
                # monotonic stamp clocks — so the dir carries this
                # filer's port too.  Path-safe chars only.  Port-0
                # (ephemeral, test) filers get a fresh dir per boot; a
                # production filer pins its port, so its log survives
                # restart like the sqlite/lsm case.
                safe = store_path.replace(":", "_").replace("/", "_")
                meta_log_dir = (f"filer-{store_type}-{safe}"
                                f"-p{self.http.port}.metalog")
            if store_type == "lsm":
                if store_path == ":memory:":
                    raise ValueError(
                        "the lsm store needs a directory path, not "
                        ":memory: (use -storeType sqlite for in-memory)")
                from ..filer.lsm_store import LsmStore
                store = LsmStore(store_path)
            elif store_type == "sqlite":
                store = SqliteStore(store_path)
            elif store_type == "redis":
                # store_path = host:port of a RESP server
                # (filer/redis_store.py; reference weed/filer/redis2)
                from ..filer.redis_store import RedisFilerStore, RespClient
                r_host, _, r_port = store_path.rpartition(":")
                if not r_host or not r_port.isdigit():
                    raise ValueError(
                        "-storeType redis needs -store host:port of a "
                        "RESP server")
                store = RedisFilerStore(RespClient(r_host, int(r_port)))
            elif store_type == "elastic":
                # store_path = host:port of an ES-wire server
                # (filer/elastic_store.py; reference weed/filer/elastic)
                from ..filer.elastic_store import (ElasticClient,
                                                   ElasticFilerStore)
                store = ElasticFilerStore(ElasticClient(store_path))
            else:
                raise ValueError(f"unknown filer store type "
                                 f"{store_type!r} "
                                 f"(sqlite|lsm|redis|elastic)")
            # the metadata cache's cross-filer coherence rides shared
            # metalog watermark files: sqlite/lsm siblings share the
            # store-derived dir by construction, redis/elastic
            # siblings deliberately keep distinct dirs (PR 6) — so the
            # cache defaults OFF for them (env =force overrides)
            import os as _os

            from ..filer.meta_plane import meta_plane_enabled
            from ..util.chunk_cache import read_cache_disk
            coherent = store_type not in ("redis", "elastic") or \
                _os.environ.get("SEAWEEDFS_TPU_FILER_META_CACHE") == \
                "force"
            # will the meta plane run for this store shape?  (Filer
            # makes the final call; this mirrors its gate so the
            # worker-mode cache decision below can see it.)
            plane_on = store_type in ("sqlite", "lsm") and \
                store_path != ":memory:" and \
                meta_plane_enabled() is not False
            if reuse_port and not plane_on and _os.environ.get(
                    "SEAWEEDFS_TPU_FILER_META_CACHE") != "force":
                # pre-fork worker mode WITHOUT the meta plane: N
                # co-located siblings over one store advance the
                # shared durable-ts watermark at the combined commit
                # rate, so a fill's expected servable lifetime is one
                # sibling commit window (~ms) — the cache degenerates
                # into pure invalidation bookkeeping (measured: 8.3 ->
                # 3.4 ms filer CPU/request at 4 workers under write
                # load).  With the plane ON the cache stays: sibling
                # commits arrive as per-path invalidations through
                # the plane's log follower, so fills survive (ISSUE
                # 13's worker-scalable coherence).
                coherent = False
            cache_dir, _ = read_cache_disk()
            self.filer = Filer(master, store,
                               collection=collection,
                               replication=replication,
                               meta_log_dir=meta_log_dir,
                               meta_cache=coherent,
                               chunk_cache_dir=(
                                   _os.path.join(
                                       cache_dir,
                                       f"filer{self.http.port}")
                                   if cache_dir else None))
        except BaseException:
            # the listener above is already bound; a store-setup
            # failure must not leak a socket that accepts (and
            # then hangs) connections with no server behind it
            self.http.abort()
            raise
        # native META plane (native/meta_plane.cc — the filer-side
        # sibling of the volume write plane): plain single-chunk PUTs
        # into provably-fresh directories are parsed, uploaded to the
        # volume write plane, WAL-appended and acked by a C++ epoll
        # loop; everything else 404s and the client falls back to this
        # port.  Kill switch SEAWEEDFS_TPU_FILER_META_PLANE_NATIVE=0;
        # requires the Python meta plane (the WAL protocol owner).
        self.native_meta = None
        if self.filer.meta_plane is not None:
            from .meta_plane_native import (NativeMetaPlane,
                                            native_meta_plane_enabled)
            if native_meta_plane_enabled() is not False:
                try:
                    mp_host = self.http.host if all(
                        c in "0123456789." for c in self.http.host) \
                        else "127.0.0.1"
                    self.native_meta = NativeMetaPlane(
                        self.filer.meta_log.dir, master, host=mp_host,
                        collection=collection,
                        replication=replication)
                except (RuntimeError, OSError):
                    self.native_meta = None  # pure-Python fallback
        # native READ plane (native/filer_read_plane.cc — the read
        # sibling): eligible warm GETs are parsed, looked up against a
        # C-side entry map, fetched from the volume read plane over
        # the shared persistent plane-socket pool and answered by a
        # C++ epoll loop; everything else 404s and the client falls
        # back to this port.  Kill switch
        # SEAWEEDFS_TPU_FILER_READ_PLANE_NATIVE=0.  Requires an event
        # channel covering every writer that can mutate the namespace:
        # this process's own listener always, plus the meta plane's
        # follower tap when pre-fork siblings share the store — so in
        # worker mode without the meta plane the read plane stays off
        # (a sibling's overwrite would never invalidate our map).
        self.native_read = None
        if self.filer.meta_plane is not None or not reuse_port:
            from .filer_read_plane_native import (
                NativeReadPlane, native_read_plane_enabled)
            if native_read_plane_enabled() is not False:
                try:
                    rp_host = self.http.host if all(
                        c in "0123456789." for c in self.http.host) \
                        else "127.0.0.1"
                    self.native_read = NativeReadPlane(master,
                                                       host=rp_host)
                except (RuntimeError, OSError):
                    self.native_read = None  # pure-Python fallback
        # directory/entry truth flows in from both sides: this
        # process's own Python-path mutations (listener) and every
        # sibling writer's WAL lines (the meta plane's follower tap,
        # fanned out when both native planes are up)
        taps = []
        if self.native_meta is not None:
            self.filer.subscribe(self.native_meta.on_event)
            taps.append(self.native_meta.on_follower_events)
        if self.native_read is not None:
            self.filer.subscribe(self.native_read.on_event)
            taps.append(self.native_read.on_follower_events)
        if taps and self.filer.meta_plane is not None:
            if len(taps) == 1:
                self.filer.meta_plane.sink = taps[0]
            else:
                def _fan_sink(evs, _taps=tuple(taps)):
                    evs = list(evs)  # both taps see the full batch
                    for t in _taps:
                        t(evs)
                self.filer.meta_plane.sink = _fan_sink
        if self.native_meta is not None:
            self.native_meta.arm(True)
            # flight-deck drainer (ISSUE 18): pull the plane's
            # per-request records into traces / FlightRecorder /
            # stage histograms on a tick + at /debug/slow scrape
            self.native_meta.start_record_drain()
        if self.native_read is not None:
            self.native_read.arm(True)
            self.native_read.start_record_drain()
        self.http.route("GET", "/status", self._status)
        self.http.route("POST", "/debug/meta_plane",
                        self._debug_meta_plane)
        self.http.route("POST", "/debug/read_plane",
                        self._debug_read_plane)
        self.http.route("GET", "/__meta__/lookup", self._meta_lookup)
        self.http.route("POST", "/__meta__/rename", self._meta_rename)
        self.http.route("POST", "/__meta__/set_attrs",
                        self._meta_set_attrs)
        self.http.route("POST", "/__meta__/create",
                        self._meta_create)
        self.http.route("POST", "/__meta__/put_entry",
                        self._meta_put_entry)
        self.http.route("POST", "/__meta__/patch_extended",
                        self._meta_patch_extended)
        self.http.route("GET", "/__meta__/events", self._meta_events)
        self.http.route("GET", "/__meta__/statistics",
                        self._meta_statistics)
        # distributed lock manager (weed/cluster/lock_manager) — the
        # filer hosts the lock ring, as in the reference.  Ring
        # membership comes from -lockPeers (every filer of a deployment
        # configured with the same list); each key hashes to exactly
        # one member, so clients dialing DIFFERENT filers still agree
        # on the lock host via movedTo redirects.  Without peers the
        # ring is this filer alone — correct for single-filer clusters,
        # and multi-filer deployments that skip -lockPeers get per-
        # filer (not cluster-wide) locks.
        from ..cluster import LockManager
        from ..cluster.lock_manager import normalize_address
        # ring identity is the NORMALIZED address (ADVICE r4): if the
        # operator's -lockPeers spelling differs from our advertised
        # url (localhost vs 127.0.0.1), exact-string membership would
        # make the owning filer redirect its own keys forever.
        # HttpServer.url is unbracketed host:port; bracket a v6 host
        # first or an address like ::1:8888 parses ambiguously
        self._ring_self = normalize_address(
            f"[{self.http.host}]:{self.http.port}"
            if ":" in self.http.host else self.http.url)
        self.lock_manager = LockManager(self._ring_self)
        if self._lock_peers:
            members = {normalize_address(p) for p in self._lock_peers}
            if self._ring_self not in members:
                # fail HARD (review r5): silently adding ourselves
                # would run a ring whose member list diverges from the
                # peers' (they don't list us under this spelling) —
                # two filers could then both compute target == self
                # for one key and grant the same cluster lock twice.
                # A diverged ring is worse than not starting.
                raise ValueError(
                    f"filer {self.http.url} (normalized "
                    f"{self._ring_self}) is not in -lockPeers "
                    f"{sorted(members)}; every filer must appear in "
                    f"the shared peer list under a spelling that "
                    f"normalizes to its advertised address")
            self.lock_manager.members = sorted(members)
        self.http.route("POST", "/admin/locks/acquire",
                        self._lock_acquire)
        self.http.route("POST", "/admin/locks/release",
                        self._lock_release)
        self.http.route("GET", "/admin/locks/list", self._lock_list)
        # metrics registry + /metrics endpoint (stats/metrics.go
        # FilerGather): the filer serves the same Prometheus text
        # plane as master/volume/s3, fed request_seconds by the httpd
        # middleware plus filer-specific gauges below
        from ..stats import Metrics
        self.metrics = Metrics("filer")
        self.http.route("GET", "/metrics", self._metrics)
        self.http.role = "filer"
        self.http.metrics = self.metrics
        from .debug import install_debug_routes
        install_debug_routes(self.http)  # util/grace/pprof.go analog
        self.http.guard = self._guard
        # pre-parsed prefix routes (httpd.route_prefix): the TUS and
        # interval-chunk planes resolve from the compiled table
        # instead of per-request startswith chains in the fallback
        for m in ("OPTIONS", "POST", "HEAD", "PATCH", "DELETE", "GET",
                  "PUT"):
            self.http.route_prefix(m, "/__tus__/", self._tus_route)
        self.http.route_prefix("POST", "/__chunk__/",
                               self._chunk_route)
        self.http.fallback = self._dispatch
        # QoS plane (qos.py): per-tenant admission at the filer edge
        # (tenant = auth principal / X-Tenant / anonymous), and this
        # filer's request_seconds feeds the background EC throttle
        from .. import qos
        qos.install(self.http, "filer")
        qos.throttle().add_metrics(f"filer:{self.http.port}",
                                   self.metrics)
        qos.throttle().maybe_start()
        # SLO autopilot (autopilot.py, ISSUE 20): closes the loop
        # over hedge/brownout/cache knobs and supervises both native
        # planes; the tick thread only spins when the env kill switch
        # allows (the registry still serves /debug/autopilot when
        # held, so the lever can re-enable without a restart)
        from .. import autopilot as _autopilot
        from .debug import install_autopilot_routes
        self.autopilot = _autopilot.build_for_filer(self)
        install_autopilot_routes(self.http, self.autopilot)
        self.autopilot.start()

    def _guard(self, req: Request):
        """Admin-plane gate (guard.go): the filer's /debug plane must
        honor the same admin JWT as every other role."""
        from .. import security
        from .httpd import is_admin_path
        if is_admin_path(req.path):
            err = security.current().check_admin(
                req.query, req.headers, req.remote_ip)
            if err:
                return 401, {"error": err}
        return None

    # -- distributed locks (distributed_lock_manager.go) ---------------

    def _lock_acquire(self, req: Request):
        b = req.json()
        key = str(b.get("key", ""))
        if not key:
            return 400, {"error": "missing lock key"}
        target = self.lock_manager.target_server(key)
        if target and target != self._ring_self:
            return 200, {"movedTo": target}
        r = self.lock_manager.acquire(
            key, str(b.get("owner", "")),
            float(b.get("ttlSec", 10.0)),
            str(b.get("renewToken", "")))
        if isinstance(r, str):
            return 423, {"error": "locked", "owner": r}
        token, expires_at = r
        return 200, {"renewToken": token, "expiresAt": expires_at}

    def _lock_release(self, req: Request):
        b = req.json()
        key = str(b.get("key", ""))
        target = self.lock_manager.target_server(key)
        if target and target != self._ring_self:
            return 200, {"movedTo": target}
        ok = self.lock_manager.release(key,
                                       str(b.get("renewToken", "")))
        if not ok:
            return 409, {"error": "token mismatch"}
        return 200, {}

    def _lock_list(self, req: Request):
        return 200, {"locks": self.lock_manager.all_locks()}

    def _metrics(self, req: Request):
        """Prometheus text endpoint (stats/metrics.go FilerGather
        analog): request_seconds arrives via the httpd middleware;
        namespace-shape gauges are refreshed per scrape."""
        self.metrics.gauge_set(
            "meta_log_last_ts_ns", float(self.filer.meta_log.last_ts()),
            help_text="timestamp of the newest metadata log event")
        self.metrics.gauge_set(
            "locks_held", float(len(self.lock_manager.all_locks())),
            help_text="distributed locks currently held here")
        if self.filer.meta_plane is not None:
            mp = self.filer.meta_plane.snapshot()
            self.metrics.gauge_set(
                "meta_plane_overlay_entries", float(mp["overlay"]),
                help_text="WAL-acked entries awaiting the async store "
                          "checkpoint (the overlay index)")
            self.metrics.gauge_set(
                "meta_plane_applier", float(bool(mp["holder"])),
                help_text="1 when this process holds the designated-"
                          "applier lock for the shared metalog")
            self.metrics.gauge_set(
                "meta_plane_checkpoint_ts_ns",
                float(mp["checkpointTsNs"]),
                help_text="newest event stamp the store checkpoint "
                          "covers")
        from ..stats import render_process
        return 200, ((self.metrics.render() +
                      self._native_meta_metrics_text() +
                      self._native_read_metrics_text() +
                      render_process()).encode(),
                     "text/plain; version=0.0.4")

    def _native_meta_metrics_text(self) -> str:
        """Native meta-plane counters rendered straight from the C++
        atomics at scrape time (the plane has no Python on its hot
        path): requests/fallbacks/fid pool, the ack latency histogram,
        and the per-stage wall split (parse / upload / wal) that keeps
        cluster.slow able to attribute a tail request that crossed the
        native plane."""
        nm = self.native_meta
        if nm is None:
            return ""
        st = nm.stats()
        out = []
        for key, help_text in (
                ("requests", "filer writes acked by the native meta "
                             "plane"),
                ("fallbacks", "native meta-plane requests answered "
                              "404 (python filer owns them)"),
                ("fid_misses", "native requests that fell back on an "
                               "empty pre-assigned fid pool"),
                ("wal_errors", "group-commit batches that failed the "
                               "WAL append (every member fell back)"),
                ("upstream_errors", "chunk uploads the volume write "
                                    "plane refused or dropped"),
                ("wal_batches", "group-commit barrier batches landed"),
                ("wal_lines", "WAL lines landed by the native plane")):
            name = f"filer_meta_plane_native_{key}_total"
            out.append(f"# HELP {name} {help_text}\n"
                       f"# TYPE {name} counter\n"
                       f"{name} {st[key]}\n")
        out.append("# HELP filer_meta_plane_native_stage_seconds_total"
                   " cumulative native-plane wall per stage\n"
                   "# TYPE filer_meta_plane_native_stage_seconds_total"
                   " counter\n")
        for stage in ("parse", "upload", "wal"):
            out.append(f"filer_meta_plane_native_stage_seconds_total"
                       f'{{stage="{stage}"}} '
                       f"{st[stage + '_ns'] / 1e9}\n")
        out.append("# HELP filer_meta_plane_native_fid_level "
                   "pre-assigned fids ready in the native pool\n"
                   "# TYPE filer_meta_plane_native_fid_level gauge\n"
                   f"filer_meta_plane_native_fid_level "
                   f"{max(nm.fid_level(), 0)}\n")
        from .meta_plane_native import ACK_BUCKETS_S
        buckets, count, total_s = nm.ack_histogram()
        out.append("# HELP filer_meta_plane_native_ack_seconds "
                   "native meta-plane ack latency\n"
                   "# TYPE filer_meta_plane_native_ack_seconds "
                   "histogram\n")
        for le, cum in zip(ACK_BUCKETS_S, buckets):
            out.append(f"filer_meta_plane_native_ack_seconds_bucket"
                       f'{{le="{le}"}} {cum}\n')
        out.append(f"filer_meta_plane_native_ack_seconds_bucket"
                   f'{{le="+Inf"}} {count}\n'
                   f"filer_meta_plane_native_ack_seconds_sum "
                   f"{total_s}\n"
                   f"filer_meta_plane_native_ack_seconds_count "
                   f"{count}\n")
        return "".join(out)

    def _native_read_metrics_text(self) -> str:
        """Native read-plane counters rendered straight from the C++
        atomics at scrape time: requests/fallbacks/stale/upstream, the
        response latency histogram, the entry-map gauge, and the
        per-stage wall split (parse / lookup / fetch / send) that
        keeps cluster.slow able to attribute a tail read that crossed
        the native plane."""
        nr = self.native_read
        if nr is None:
            return ""
        st = nr.stats()
        out = []
        for key, help_text in (
                ("requests", "filer reads served by the native read "
                             "plane"),
                ("fallbacks", "native read-plane requests answered "
                              "404 (python filer owns them)"),
                ("stale_misses", "native fetches the volume plane "
                                 "404'd (registration invalidated)"),
                ("upstream_errors", "chunk fetches the volume read "
                                    "plane refused or dropped")):
            name = f"filer_read_plane_native_{key}_total"
            out.append(f"# HELP {name} {help_text}\n"
                       f"# TYPE {name} counter\n"
                       f"{name} {st[key]}\n")
        out.append("# HELP filer_read_plane_native_stage_seconds_total"
                   " cumulative native-plane wall per stage\n"
                   "# TYPE filer_read_plane_native_stage_seconds_total"
                   " counter\n")
        for stage in ("parse", "lookup", "fetch", "send"):
            out.append(f"filer_read_plane_native_stage_seconds_total"
                       f'{{stage="{stage}"}} '
                       f"{st[stage + '_ns'] / 1e9}\n")
        out.append("# HELP filer_read_plane_native_entries "
                   "paths registered in the C-side entry map\n"
                   "# TYPE filer_read_plane_native_entries gauge\n"
                   f"filer_read_plane_native_entries {nr.entries()}\n")
        from .filer_read_plane_native import RESPONSE_BUCKETS_S
        buckets, count, total_s = nr.response_histogram()
        out.append("# HELP filer_read_plane_native_response_seconds "
                   "native read-plane response latency\n"
                   "# TYPE filer_read_plane_native_response_seconds "
                   "histogram\n")
        for le, cum in zip(RESPONSE_BUCKETS_S, buckets):
            out.append(
                f"filer_read_plane_native_response_seconds_bucket"
                f'{{le="{le}"}} {cum}\n')
        out.append(f"filer_read_plane_native_response_seconds_bucket"
                   f'{{le="+Inf"}} {count}\n'
                   f"filer_read_plane_native_response_seconds_sum "
                   f"{total_s}\n"
                   f"filer_read_plane_native_response_seconds_count "
                   f"{count}\n")
        return "".join(out)

    def _status(self, req: Request):
        """Plane discovery (the volume server's /status precedent):
        lean clients probe this once per process and pin their hot
        PUTs/GETs to the native plane ports."""
        nm = self.native_meta
        nr = self.native_read
        return 200, {"version": "seaweedfs-tpu/0.1",
                     "role": "filer",
                     "metaPlanePort":
                         nm.port if nm is not None and nm.armed else 0,
                     "readPlanePort":
                         nr.port if nr is not None and nr.armed else 0}

    def _debug_meta_plane(self, req: Request):
        """The PR 11 native_on/native_off lever, filer edition:
        POST /debug/meta_plane {"native": "on"|"off"} arms/disarms the
        native meta plane without tearing down its listener (clients
        keep their sockets; every request 404s to Python while off)."""
        nm = self.native_meta
        if nm is None:
            return 404, {"error": "native meta plane not running"}
        b = req.json() if req.body else {}
        want = str(b.get("native", "")).lower()
        if want in ("on", "1", "true"):
            nm.arm(True)
        elif want in ("off", "0", "false"):
            nm.arm(False)
        if "uploadDelayMs" in b:
            # ISSUE 18 failpoint: stall the native volume-upload hop
            # so a plane-served write lands in cluster.slow on demand
            try:
                nm.set_upload_delay_ms(int(b.get("uploadDelayMs")
                                           or 0))
            except (TypeError, ValueError):
                pass
        return 200, {"armed": nm.armed, "port": nm.port,
                     "fidLevel": max(nm.fid_level(), 0),
                     "recordsDropped": nm.records_dropped(),
                     **nm.stats()}

    def _debug_read_plane(self, req: Request):
        """The arm/disarm lever, read edition: POST /debug/read_plane
        {"native": "on"|"off"} arms/disarms the native read plane
        without tearing down its listener (clients keep their sockets;
        every request 404s to Python while off)."""
        nr = self.native_read
        if nr is None:
            return 404, {"error": "native read plane not running"}
        b = req.json() if req.body else {}
        want = str(b.get("native", "")).lower()
        if want in ("on", "1", "true"):
            nr.arm(True)
        elif want in ("off", "0", "false"):
            nr.arm(False)
        if "fetchDelayMs" in b:
            # chaos failpoint: stall the native volume-fetch hop so a
            # SIGKILL lands mid-flight / a plane-served read lands in
            # cluster.slow on demand
            try:
                nr.set_fetch_delay_ms(int(b.get("fetchDelayMs") or 0))
            except (TypeError, ValueError):
                pass
        return 200, {"armed": nr.armed, "port": nr.port,
                     "entries": nr.entries(),
                     "recordsDropped": nr.records_dropped(),
                     **nr.stats()}

    def start(self):
        self.http.start()
        # gRPC plane (filer.proto SeaweedFiler): entries CRUD, atomic
        # rename, streaming list, SubscribeMetadata fed by the meta
        # log, KV, distributed locks — the reference's most-trafficked
        # proto (filer.proto:13-87)
        try:
            from ..pb.filer_service import start_filer_grpc
            self.grpc_server, self.grpc_port = start_filer_grpc(
                self, host=self.http.host)
        except ImportError:     # grpcio absent: HTTP-only mode
            self.grpc_server, self.grpc_port = None, 0
        # follow stream: push-fed vid map + instant leader tracking
        # (the reference filer keeps KeepConnected open for the same
        # reason, masterclient.go:471)
        from .. import operation
        operation.enable_follow(self.filer.master)
        if self._notification_spec:
            # metadata notification fan-out (weed/notification):
            # every namespace mutation is published to the configured
            # sink with at-least-once delivery
            from .. import notification
            state = None
            if self.filer.meta_log.dir:
                import os
                state = os.path.join(self.filer.meta_log.dir,
                                     "notify.offset")
            self._notifier = notification.NotificationTailer(
                self.filer.meta_log,
                notification.from_spec(self._notification_spec),
                state_path=state).start()
        return self

    def stop(self):
        from .. import operation, qos
        if getattr(self, "autopilot", None) is not None:
            self.autopilot.stop()
        qos.throttle().remove_source(f"filer:{self.http.port}")
        operation.disable_follow(self.filer.master)
        if self._notifier is not None:
            self._notifier.stop()
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5)
        if getattr(self, "native_meta", None) is not None:
            # before the Python listener: once the native port stops
            # acking, clients retry here and must still find a server
            self.native_meta.stop()
        if getattr(self, "native_read", None) is not None:
            self.native_read.stop()
        self.http.stop()
        # meta plane first (final async apply), then store + metalog
        self.filer.close()

    @property
    def url(self) -> str:
        return self.http.url

    # -- dispatch ---------------------------------------------------------

    def _tus_route(self, req: Request):
        """Compiled-prefix entry for the TUS plane (see route_prefix
        registration): unquote once, delegate."""
        import urllib.parse
        return self._tus(req, urllib.parse.unquote(req.path))

    def _chunk_route(self, req: Request):
        import urllib.parse
        return self._chunk_write(
            req, urllib.parse.unquote(req.path)[len("/__chunk__"):])

    def _dispatch(self, req: Request):
        import urllib.parse
        # the wire path is percent-encoded (every client quotes);
        # storing it un-decoded would persist names like "a%21" for
        # "a!" — visible in listings and to in-process consumers.
        # (The /__tus__/ and /__chunk__/ planes normally resolve from
        # the compiled prefix table before this fallback runs; the
        # checks below keep percent-encoded spellings routing the way
        # they always did.)
        path = urllib.parse.unquote(req.path)
        if path.startswith("/__tus__/"):
            return self._tus(req, path)
        if path.startswith("/__chunk__/"):
            return self._chunk_write(req, path[len("/__chunk__"):])
        if req.method in ("POST", "PUT"):
            return self._put(req, path)
        if req.method in ("GET", "HEAD"):
            return self._get(req, path)
        if req.method == "DELETE":
            return self._delete(req, path)
        return 405, {"error": "method not allowed"}

    def _chunk_write(self, req: Request, path: str):
        """Interval chunk write (mount dirty-page flush target):
        POST /__chunk__/<path>?offset=N[&truncateTo=M] with raw bytes
        — appends overlapping chunks / clips length without rewriting
        the whole file (filer.proto UpdateEntry + AssignVolume)."""
        if req.method != "POST":
            return 405, {"error": "POST only"}
        offset = int(req.query.get("offset", 0))
        trunc = req.query.get("truncateTo")
        trunc = int(trunc) if trunc is not None else None
        try:
            if req.body or trunc is None:
                entry = self.filer.append_chunks(path, offset, req.body,
                                                 truncate_to=trunc)
            else:
                entry = self.filer.truncate_file(path, trunc)
        except IsADirectoryError:
            return 409, {"error": "is a directory"}
        except FileNotFoundError:
            return 404, {"error": "not found"}
        return 200, {"name": entry.name, "size": entry.total_size()}

    def _put(self, req: Request, path: str):
        if path.endswith("/"):
            # mkdir (filer_server_handlers_write.go mkdir on trailing /)
            e = Entry(path.rstrip("/") or "/", is_directory=True)
            self.filer.create_entry(e)
            return 201, {"name": e.name}
        mime = req.headers.get("Content-Type", "")
        if mime == "application/x-www-form-urlencoded":
            mime = ""
        from .. import faults, profiling
        # armed `filer.entry.put` faults fail the write BEFORE any
        # chunk is assigned — the caller's retry policy (not a
        # half-written entry) owns recovery
        faults.fire("filer.entry.put", key=path)
        # filer-funnel decomposition: assign/upload stages recorded by
        # operation.py (on the limiter pool threads, via use_track),
        # the metadata commit by filer.write_file — together they say
        # whether a slow filer write sat in master assigns, volume
        # round-trips, or the store (bench.py write_path reads these)
        with profiling.track("write", role="filer",
                             metrics=self.metrics):
            with profiling.stage("recv"):
                body = req.body
            entry = self.filer.write_file(path, body, mime=mime)
        return 201, {"name": entry.name, "size": entry.total_size()}

    def _get(self, req: Request, path: str):
        if path.endswith("/") or path == "":
            return self._list(req, path or "/")
        # read-plane fill fence (SWFS020 guard shape): capture the
        # plane's generation token BEFORE the store lookup, so the
        # warm fill below loses to any invalidation that raced it
        nr = self.native_read
        token = nr.begin_fill() if nr is not None else 0
        entry = self.filer.find_entry(path, count_negative=True)
        if entry is None:
            return 404, {"error": f"{path} not found"}
        if entry.is_directory:
            return self._list(req, path)
        if not entry.chunks and entry.extended.get("remote"):
            return self._get_remote(req, path, entry)
        rng = req.headers.get("Range", "")
        file_size = entry.total_size()
        parsed = parse_range(rng, file_size)
        if parsed == "unsatisfiable":
            return 416, (b"", {"Content-Range": f"bytes */{file_size}"})
        if parsed is None:
            rng = ""  # absent/malformed: full body (RFC 9110)
            offset, size = 0, file_size
        else:
            # parse_range already clamps size within [1, total-offset]
            offset, size = parsed
        mime = entry.attributes.mime or "application/octet-stream"
        # response-side QoS byte metering (qos.charge_response): held
        # for the whole response write, so a stampede of concurrent
        # big reads — hot-cache hits included — is bounded by the
        # tenant's in-flight-bytes budget like uploads are
        from .. import qos
        release, deny = qos.charge_response(req, size, "filer")
        if deny is not None:
            return deny
        # stream, never buffer: views fetch lazily as the response
        # drains (through the hot chunk cache), so a multi-GB GET
        # holds one chunk in memory, not the file
        body = self.filer.open_read_stream(entry, offset, size,
                                           on_close=release)
        if nr is not None and not rng:
            # warm fill: the NEXT read of this path can be served
            # natively (fenced by the pre-lookup token above)
            nr.warm_fill(path, entry, token)
        headers = {"Content-Type": mime,
                   "Content-Length": str(size)}
        if rng:
            headers["Content-Range"] = \
                f"bytes {offset}-{offset + size - 1}/{file_size}"
            return 206, (body, headers)
        return 200, (body, headers)

    def _get_remote(self, req: Request, path: str, entry):
        """Read-through for uncached remote-mounted entries
        (filer_remote_read: fetch from the foreign store on demand;
        remote.cache materializes local chunks so this path stops
        being hit)."""
        import json as _json
        from ..remote import RemoteError, remote_for_path
        try:
            located = remote_for_path(self.url, path)
            if located is None:
                return 404, {"error": f"{path}: remote mount gone"}
            client, key = located
            marker = _json.loads(entry.extended["remote"])
            total = int(marker.get("size", 0))
            parsed = parse_range(req.headers.get("Range", ""), total)
            if parsed == "unsatisfiable":
                return 416, (b"", {"Content-Range": f"bytes */{total}"})
            if parsed is not None:
                offset, size = parsed
                data = client.read(key, offset, size)
                end = offset + len(data) - 1
                return 206, (data, {
                    "Content-Type": "application/octet-stream",
                    "Content-Range": f"bytes {offset}-{end}/{total}"})
            return 200, (client.read(key),
                         "application/octet-stream")
        except FileNotFoundError:
            return 404, {"error": f"{path}: gone on remote"}
        except (RemoteError, OSError, ValueError) as e:
            return 502, {"error": f"remote read {path}: {e}"}

    def _list(self, req: Request, path: str):
        limit = int(req.query.get("limit", 1000))
        last = req.query.get("lastFileName", "")
        prefix = req.query.get("prefix", "")
        entries = self.filer.list_directory(
            path.rstrip("/") or "/", start_file=last, limit=limit,
            prefix=prefix)
        return 200, {
            "path": path,
            "entries": [e.to_json() for e in entries],
            "lastFileName": entries[-1].name if entries else "",
            "shouldDisplayLoadMore": len(entries) >= limit,
        }

    def _delete(self, req: Request, path: str):
        recursive = req.query.get("recursive", "") == "true"
        # ignoreChunks: remove metadata only (filer.proto
        # DeleteEntryRequest.is_delete_data=false) — multipart
        # completion strips its scratch dir while the final entry now
        # references the parts' chunks
        keep_chunks = req.query.get("ignoreChunks", "") == "true"
        try:
            self.filer.delete_entry(path.rstrip("/") or "/",
                                    recursive=recursive,
                                    delete_chunks=not keep_chunks)
        except IsADirectoryError as e:
            return 409, {"error": str(e)}
        return 204, b""

    # -- TUS resumable uploads (filer_server_tus_handlers.go) -------------

    TUS_VERSION = "1.0.0"
    _TUS_DIR = "/.tus"

    def _tus(self, req: Request, path: str):
        """tus.io core protocol: creation (POST), offset probe (HEAD),
        append (PATCH), abort (DELETE).  Upload parts are staged as
        filer files under /.tus/<id>/ — resumable across filer
        restarts — and the completed upload materializes by STITCHING
        the parts' chunk lists (no data copy, the multipart-complete
        trick)."""
        tus_headers = {"Tus-Resumable": self.TUS_VERSION,
                       "Tus-Version": self.TUS_VERSION,
                       "Tus-Extension": "creation,termination"}
        if req.method == "OPTIONS":
            return 204, (b"", tus_headers)
        if req.method == "POST":
            try:
                length = int(req.headers.get("Upload-Length", -1))
            except ValueError:
                length = -1
            target = req.query.get("path", "")
            if length < 0 or not target:
                return 400, {"error": "Upload-Length header and "
                                      "?path= are required"}
            import uuid as _uuid
            uid = _uuid.uuid4().hex
            marker = Entry(f"{self._TUS_DIR}/{uid}",
                           is_directory=True)
            marker.extended["tusTarget"] = target
            marker.extended["tusLength"] = str(length)
            self.filer.create_entry(marker)
            h = dict(tus_headers)
            h["Location"] = f"/__tus__/{uid}"
            return 201, (b"", h)

        uid = path[len("/__tus__/"):].strip("/")
        if not uid or "/" in uid:
            # an empty id would resolve to the /.tus staging ROOT —
            # DELETE would then wipe every in-flight upload
            return 404, {"error": "unknown upload"}
        updir = f"{self._TUS_DIR}/{uid}"
        marker = self.filer.find_entry(updir)
        if marker is None or not marker.extended.get("tusTarget"):
            return 404, {"error": "unknown upload"}
        length = int(marker.extended.get("tusLength", 0))
        parts = sorted(
            (e for e in self.filer.list_directory(updir, limit=100000)
             if e.name.endswith(".part")),
            key=lambda e: int(e.name.split(".")[0]))
        offset = sum(e.total_size() for e in parts)

        if req.method == "HEAD":
            h = dict(tus_headers)
            h.update({"Upload-Offset": str(offset),
                      "Upload-Length": str(length),
                      "Cache-Control": "no-store"})
            return 200, (b"", h)
        if req.method == "DELETE":
            self.filer.delete_entry(updir, recursive=True)
            return 204, (b"", tus_headers)
        if req.method == "PATCH":
            try:
                claimed = int(req.headers.get("Upload-Offset", -1))
            except ValueError:
                claimed = -1
            if claimed != offset:
                # 409: the client's view of the offset is stale
                h = dict(tus_headers)
                h["Upload-Offset"] = str(offset)
                return 409, (b"", h)
            data = req.body
            if offset + len(data) > length:
                return 413, {"error": "upload exceeds Upload-Length"}
            self.filer.write_file(f"{updir}/{offset:020d}.part", data)
            offset += len(data)
            if offset == length:
                # materialize: stitch part chunk lists, zero data copy
                target = marker.extended["tusTarget"]
                chunks = []
                base = 0
                parts = sorted(
                    (e for e in self.filer.list_directory(
                        updir, limit=100000)
                     if e.name.endswith(".part")),
                    key=lambda e: int(e.name.split(".")[0]))
                for p in parts:
                    for c in p.chunks:
                        chunks.append(type(c)(
                            c.file_id, base + c.offset, c.size,
                            c.e_tag, c.mtime_ns))
                    base += p.total_size()
                old = self.filer.find_entry(target)
                final = Entry(target, chunks=chunks)
                self.filer.create_entry(final)
                if old is not None and not old.is_directory:
                    # reclaim the replaced file's chunks, matching
                    # write_file's overwrite semantics — create_entry
                    # alone would orphan them on the volume servers
                    self.filer._delete_chunks(old)
                self.filer.delete_entry(updir, recursive=True,
                                        delete_chunks=False)
            h = dict(tus_headers)
            h["Upload-Offset"] = str(offset)
            return 204, (b"", h)
        return 405, {"error": f"method {req.method} not allowed"}

    # -- meta RPC mirrors -------------------------------------------------

    def _meta_lookup(self, req: Request):
        entry = self.filer.find_entry(req.query["path"])
        if entry is None:
            return 404, {"error": "not found"}
        return 200, entry.to_json()

    def _meta_rename(self, req: Request):
        b = req.json()
        try:
            self.filer.rename(b["oldPath"], b["newPath"])
        except FileNotFoundError as e:
            return 404, {"error": str(e)}
        return 200, {}

    def _meta_set_attrs(self, req: Request):
        """Attribute-only update (filer.proto UpdateEntry with unchanged
        chunks) — filer.sync uses this to propagate mode/uid/gid/mtime
        that the content PUT cannot carry."""
        b = req.json()
        entry = self.filer.find_entry(b["path"])
        if entry is None:
            return 404, {"error": "not found"}
        from ..filer.entry import Attributes
        entry.attributes = Attributes.from_json(b.get("attributes", {}))
        self.filer.create_entry(entry, create_parents=False)
        return 200, {}

    def _meta_create(self, req: Request):
        """Create/replace a chunkless entry with extended metadata —
        the remote-mount pointer entries (filer_pb.RemoteEntry shape)
        and remote.uncache both need an entry with metadata but no
        content."""
        from ..filer.entry import Entry
        b = req.json()
        entry = Entry(b["path"],
                      is_directory=bool(b.get("isDirectory")))
        entry.extended = dict(b.get("extended", {}))
        old_entry = self.filer.find_entry(b["path"])
        self.filer.create_entry(entry)
        if old_entry is not None and old_entry.chunks:
            # replacing a file with a chunkless entry (uncache /
            # remote-pointer refresh) must reclaim the old content —
            # write_file does the same for content overwrites
            self.filer._delete_chunks(old_entry)
        return 200, {}

    def _meta_put_entry(self, req: Request):
        """Full-entry create/replace (filer.proto CreateEntry):
        attributes, extended metadata AND chunk list — what remote
        gateways (weed s3 -filer) need to write entries they
        assembled themselves (multipart completion, delete markers,
        config mutations)."""
        from ..filer.entry import Entry
        self.filer.create_entry(Entry.from_json(req.json()))
        return 200, {}

    def _meta_patch_extended(self, req: Request):
        """Merge extended keys into an entry, keeping chunks/attrs."""
        b = req.json()
        entry = self.filer.find_entry(b["path"])
        if entry is None:
            return 404, {"error": "not found"}
        entry.extended.update(b.get("extended", {}))
        self.filer.create_entry(entry, create_parents=False)
        return 200, {}

    def _meta_statistics(self, req: Request):
        """Cluster usage aggregated from the master topology
        (filer.proto Statistics; also the mount's quota feed —
        weedfs_quota.go polls the same numbers)."""
        try:
            return 200, cluster_statistics(
                self.filer.master, req.query.get("collection", ""))
        except OSError as e:
            return 503, {"error": str(e)}

    def _meta_events(self, req: Request):
        since = int(req.query.get("sinceNs", 0))
        limit = int(req.query.get("limit", 0))
        return 200, {"events": self.filer.events_since(since, limit)}
