"""Unix-domain-socket zero-copy needle read plane — the idiomatic
analog of the reference's RDMA sidecar fast data path
(seaweedfs-rdma-sidecar/rdma-engine/src/ipc.rs;
weed/mount/rdma_client.go:20): same-host readers bypass the HTTP
stack and receive the raw needle record straight from the volume
file via sendfile(2) — the bytes never enter this process's
userspace.

Protocol (one request per connection round, connection reusable):
    client -> {"volumeId": v, "key": k}\n
    server -> {"size": n, "version": ver}\n  + n raw record bytes
    or     -> {"error": "..."}\n

The client parses the record with the shared needle codec (crc, ttl,
cookie checks happen client-side — it holds the same code).  The
socket path is advertised in the volume server's /status response
(udsPath), so discovery needs no extra configuration; consumers fall
back to HTTP when the path is absent or unconnectable (different
host, container boundary)."""

from __future__ import annotations

import json
import os
import socket
import threading

from ..storage import types


class UdsNeedleServer:
    def __init__(self, store, sock_path: str, on_read=None):
        self.store = store
        self.sock_path = sock_path
        # on_read(vid, key): post-serve hook — the volume server uses
        # it to lazily warm the native TCP read plane, which would
        # otherwise never learn about needles whose every read takes
        # this zero-copy path (the filer-plane fetch would 404 forever)
        self.on_read = on_read
        self._stop = threading.Event()
        try:
            os.remove(sock_path)
        except OSError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(sock_path)
        self._sock.listen(64)
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)

    def start(self) -> "UdsNeedleServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        try:
            os.remove(self.sock_path)
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            f = conn.makefile("rb")
            while not self._stop.is_set():
                line = f.readline(4096)
                if not line:
                    return
                try:
                    req = json.loads(line)
                    self._serve_one(conn, int(req["volumeId"]),
                                    int(req["key"]))
                except (ValueError, KeyError):
                    conn.sendall(json.dumps(
                        {"error": "malformed request"}).encode()
                        + b"\n")
                    return
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def _serve_one(self, conn: socket.socket, vid: int,
                   key: int) -> None:
        v = self.store.find_volume(vid)
        if v is None:
            conn.sendall(json.dumps(
                {"error": f"volume {vid} not found"}).encode() + b"\n")
            return
        # Snapshot location + dup the fd UNDER the volume lock, then
        # stream OUTSIDE it: a slow/stalled client on the socket must
        # never wedge the volume (the lock gates every read/write/
        # heartbeat).  The dup'd fd stays valid even if the volume is
        # compacted/closed mid-send — the client's crc check rejects
        # torn bytes in that rare race.
        dup_fd = None
        payload = None
        with v.lock:
            # read-your-native-writes: a write-plane ack whose journal
            # entry hasn't drained yet must still be UDS-readable
            v._drain_if_pending()
            got = v.nm.get(key)
            if got is None:
                conn.sendall(json.dumps(
                    {"error": "not found"}).encode() + b"\n")
                return
            stored_offset, size = got
            offset = types.to_actual_offset(stored_offset)
            from ..storage.needle import get_actual_size
            total = get_actual_size(size, v.version)
            version = v.version
            v.sync()
            if v.is_remote:
                # remote-tier volumes have no local fd: plain read
                v._dat.seek(offset)
                payload = v._dat.read(total)
            else:
                dup_fd = os.dup(v._dat.fileno())
        try:
            conn.settimeout(30.0)
            conn.sendall(json.dumps(
                {"size": total, "version": version}).encode() + b"\n")
            if payload is not None:
                conn.sendall(payload)
                return
            # THE zero-copy hop: kernel moves .dat bytes directly to
            # the socket
            sent = 0
            while sent < total:
                n = os.sendfile(conn.fileno(), dup_fd, offset + sent,
                                total - sent)
                if n == 0:
                    break
                sent += n
        finally:
            if dup_fd is not None:
                os.close(dup_fd)
        if self.on_read is not None:
            try:
                self.on_read(vid, key)
            except Exception:  # noqa: SWFS004 — plane warm is
                pass           # best-effort cache upkeep


def uds_read_needle(sock_path: str, vid: int, key: int,
                    version_hint: int = 3,
                    timeout: float = 10.0):
    """Client side: fetch + parse one needle record over the UDS
    plane.  Returns a parsed Needle (crc-checked); raises OSError on
    transport problems and LookupError when the server reports a
    miss."""
    from ..storage.needle import Needle

    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
        s.settimeout(timeout)
        s.connect(sock_path)
        s.sendall(json.dumps({"volumeId": vid, "key": key}).encode()
                  + b"\n")
        f = s.makefile("rb")
        header = json.loads(f.readline(4096))
        if "error" in header:
            raise LookupError(header["error"])
        total = int(header["size"])
        buf = f.read(total)
        if len(buf) != total:
            raise OSError(f"short uds read: {len(buf)}/{total}")
        return Needle.from_bytes(buf, int(header["version"]))
