"""SSH wire primitives: RFC 4251 data types and the RFC 4253 §6 binary
packet protocol (length/padding framing, AES-128-CTR encryption,
HMAC-SHA2-256 integrity, per-direction sequence numbers).

The reference gets this from golang.org/x/crypto/ssh; none of the
image's libraries provide it, so it lives here.  Only the negotiated
suite is implemented: curve25519-sha256 / ssh-ed25519 / aes128-ctr /
hmac-sha2-256 / none — the same defaults x/crypto/ssh picks for the
reference's server (sftpd/sftp_service.go buildSSHConfig).
"""

from __future__ import annotations

import hmac
import os
import struct

from cryptography.hazmat.primitives.ciphers import Cipher, algorithms, modes


# -- RFC 4251 §5 data types -----------------------------------------------

def u32(v: int) -> bytes:
    return struct.pack(">I", v)


def u8(v: int) -> bytes:
    return struct.pack(">B", v)


def ssh_string(b: bytes | str) -> bytes:
    if isinstance(b, str):
        b = b.encode()
    return u32(len(b)) + b


def ssh_bool(v: bool) -> bytes:
    return b"\x01" if v else b"\x00"


def mpint(n: int) -> bytes:
    """Minimal two's-complement big-endian with sign-bit padding."""
    if n == 0:
        return u32(0)
    b = n.to_bytes((n.bit_length() + 8) // 8, "big")
    return u32(len(b)) + b


def name_list(names: list[str]) -> bytes:
    return ssh_string(",".join(names))


class Reader:
    """Sequential decoder over one packet payload."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise ValueError("ssh packet truncated")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u8(self) -> int:
        return self._take(1)[0]

    def u32(self) -> int:
        return struct.unpack(">I", self._take(4))[0]

    def u64(self) -> int:
        return struct.unpack(">Q", self._take(8))[0]

    def string(self) -> bytes:
        return self._take(self.u32())

    def text(self) -> str:
        return self.string().decode()

    def boolean(self) -> bool:
        return self.u8() != 0

    def name_list(self) -> list[str]:
        s = self.text()
        return s.split(",") if s else []

    def rest(self) -> bytes:
        b = self.data[self.pos:]
        self.pos = len(self.data)
        return b


# -- RFC 4253 §7.2 key derivation -----------------------------------------

def derive_key(hash_fn, k_mpint: bytes, h: bytes, letter: bytes,
               session_id: bytes, length: int) -> bytes:
    out = hash_fn(k_mpint + h + letter + session_id).digest()
    while len(out) < length:
        out += hash_fn(k_mpint + h + out).digest()
    return out[:length]


# -- RFC 4253 §6 binary packets -------------------------------------------

class PacketStream:
    """Framed packet IO over a socket, with an armed/unarmed cipher
    state per direction.  Sequence numbers run from connection start
    (they cover the cleartext kex packets too — the MAC input is
    uint32(seq) || unencrypted_packet)."""

    MAX_PACKET = 1 << 18

    def __init__(self, sock):
        self.sock = sock
        self._rbuf = b""
        self._seq_in = 0
        self._seq_out = 0
        self._enc = None            # outgoing cipher context
        self._dec = None            # incoming cipher context
        self._mac_out = None        # outgoing hmac key
        self._mac_in = None
        self._block_out = 8
        self._block_in = 8

    def arm(self, enc_key: bytes, enc_iv: bytes, dec_key: bytes,
            dec_iv: bytes, mac_out: bytes, mac_in: bytes) -> None:
        """Switch both directions to aes128-ctr + hmac-sha2-256 after
        NEWKEYS.  CTR state is continuous across packets."""
        self._enc = Cipher(algorithms.AES(enc_key),
                           modes.CTR(enc_iv)).encryptor()
        self._dec = Cipher(algorithms.AES(dec_key),
                           modes.CTR(dec_iv)).decryptor()
        self._mac_out, self._mac_in = mac_out, mac_in
        self._block_out = self._block_in = 16

    # -- raw socket helpers ------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        while len(self._rbuf) < n:
            chunk = self.sock.recv(65536)
            if not chunk:
                raise ConnectionError("ssh peer closed")
            self._rbuf += chunk
        b, self._rbuf = self._rbuf[:n], self._rbuf[n:]
        return b

    def read_version_line(self) -> str:
        """RFC 4253 §4.2: lines before the SSH- identification are
        permitted (server banner); the id line ends with CRLF."""
        for _ in range(32):
            line = b""
            while not line.endswith(b"\n"):
                line += self._recv_exact(1)
                if len(line) > 255:
                    raise ValueError("oversized ssh version line")
            text = line.rstrip(b"\r\n").decode(errors="replace")
            if text.startswith("SSH-"):
                return text
        raise ValueError("no SSH identification line")

    def write_version_line(self, version: str) -> None:
        self.sock.sendall(version.encode() + b"\r\n")

    # -- packets -----------------------------------------------------------

    def send(self, payload: bytes) -> None:
        block = self._block_out
        # 4-byte length + 1-byte padlen + payload + padding ≡ 0 mod block
        pad = block - ((5 + len(payload)) % block)
        if pad < 4:
            pad += block
        packet = (u32(1 + len(payload) + pad) + u8(pad) + payload +
                  os.urandom(pad))
        mac = b""
        if self._mac_out:
            mac = hmac.new(self._mac_out, u32(self._seq_out) + packet,
                           "sha256").digest()
            packet = self._enc.update(packet)
        self._seq_out = (self._seq_out + 1) & 0xFFFFFFFF
        self.sock.sendall(packet + mac)

    def recv(self) -> bytes:
        first = self._recv_exact(self._block_in)
        if self._dec:
            first = self._dec.update(first)
        length = struct.unpack(">I", first[:4])[0]
        if (not 5 <= length <= self.MAX_PACKET or
                (4 + length) % self._block_in != 0):
            raise ValueError(f"bad ssh packet length {length}")
        rest = self._recv_exact(4 + length - self._block_in)
        if self._dec:
            rest = self._dec.update(rest)
        packet = first + rest
        if self._mac_in:
            want = hmac.new(self._mac_in, u32(self._seq_in) + packet,
                            "sha256").digest()
            got = self._recv_exact(len(want))
            if not hmac.compare_digest(want, got):
                raise ValueError("ssh mac mismatch")
        self._seq_in = (self._seq_in + 1) & 0xFFFFFFFF
        pad = packet[4]
        payload = packet[5:4 + length - pad]
        if not payload:
            raise ValueError("empty ssh payload")
        return payload
