"""SSH transport layer (RFC 4253) + curve25519-sha256 kex (RFC 8731),
usable in both server and client roles.

The reference's server gets this from golang.org/x/crypto/ssh
(sftpd/sftp_service.go handleSSHConnection); the from-scratch analog
here negotiates exactly one suite:

    kex        curve25519-sha256          (RFC 8731)
    host key   ssh-ed25519                (RFC 8709)
    cipher     aes128-ctr                 (RFC 4344)
    mac        hmac-sha2-256              (RFC 6668)
    compression none

Rekeying (RFC 4253 §9) is not implemented: connections are expected to
move well under the 2**32-packet / 1 GB-per-key guidance for gateway
sessions; a peer-initiated KEXINIT raises and drops the connection
rather than silently continuing on stale keys.
"""

from __future__ import annotations

import hashlib
import os

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey, Ed25519PublicKey)
from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey)
from cryptography.hazmat.primitives import serialization

from .ssh_wire import (PacketStream, Reader, derive_key, mpint, name_list,
                       ssh_string, u32, u8)

# RFC 4253 §12 message numbers
MSG_DISCONNECT = 1
MSG_IGNORE = 2
MSG_UNIMPLEMENTED = 3
MSG_DEBUG = 4
MSG_SERVICE_REQUEST = 5
MSG_SERVICE_ACCEPT = 6
MSG_KEXINIT = 20
MSG_NEWKEYS = 21
MSG_KEX_ECDH_INIT = 30
MSG_KEX_ECDH_REPLY = 31

KEX_ALGOS = ["curve25519-sha256", "curve25519-sha256@libssh.org"]
HOSTKEY_ALGOS = ["ssh-ed25519"]
CIPHERS = ["aes128-ctr"]
MACS = ["hmac-sha2-256"]
COMPRESSION = ["none"]

VERSION = "SSH-2.0-SeaweedFSTPU_1.0"


def ed25519_blob(pub: Ed25519PublicKey) -> bytes:
    raw = pub.public_bytes(serialization.Encoding.Raw,
                           serialization.PublicFormat.Raw)
    return ssh_string("ssh-ed25519") + ssh_string(raw)


def ed25519_from_blob(blob: bytes) -> Ed25519PublicKey:
    r = Reader(blob)
    alg = r.text()
    if alg != "ssh-ed25519":
        raise ValueError(f"unsupported host key algorithm {alg}")
    return Ed25519PublicKey.from_public_bytes(r.string())


class SshError(ConnectionError):
    pass


class Transport:
    """One SSH connection after key exchange: encrypted packet IO plus
    the negotiated session_id (needed by publickey userauth)."""

    def __init__(self, sock, server: bool,
                 host_key: Ed25519PrivateKey | None = None,
                 expected_host_key: bytes | None = None):
        """Server role needs `host_key`; client role may pin the
        server's raw ed25519 public key via `expected_host_key`
        (trust-on-first-use when None — the reference's client side,
        pkg/sftp tests, does the same with InsecureIgnoreHostKey)."""
        self.stream = PacketStream(sock)
        self.server = server
        self.host_key = host_key
        self.expected_host_key = expected_host_key
        self.session_id = b""
        self.peer_version = ""
        self._kex()

    # -- key exchange ------------------------------------------------------

    def _kexinit_payload(self) -> bytes:
        return (u8(MSG_KEXINIT) + os.urandom(16) +
                name_list(KEX_ALGOS) + name_list(HOSTKEY_ALGOS) +
                name_list(CIPHERS) + name_list(CIPHERS) +
                name_list(MACS) + name_list(MACS) +
                name_list(COMPRESSION) + name_list(COMPRESSION) +
                name_list([]) + name_list([]) +
                b"\x00" + b"\x00\x00\x00\x00")

    @staticmethod
    def _check_negotiation(peer_kexinit: bytes) -> None:
        """RFC 4253 §7.1: first match of the client list present in the
        server list.  With single-algorithm lists, membership suffices."""
        r = Reader(peer_kexinit)
        r.u8()
        r._take(16)
        offered = [r.name_list() for _ in range(8)]
        for ours, name in ((KEX_ALGOS, "kex"), (HOSTKEY_ALGOS, "hostkey"),
                           (CIPHERS, "cipher c2s"), (CIPHERS, "cipher s2c"),
                           (MACS, "mac c2s"), (MACS, "mac s2c"),
                           (COMPRESSION, "compression c2s"),
                           (COMPRESSION, "compression s2c")):
            peer = offered.pop(0)
            if not any(a in peer for a in ours):
                raise SshError(f"no common {name} algorithm: peer offers "
                               f"{peer}")

    def _kex(self) -> None:
        st = self.stream
        st.write_version_line(VERSION)
        self.peer_version = st.read_version_line()
        if not self.peer_version.startswith("SSH-2.0-"):
            raise SshError(f"unsupported peer {self.peer_version}")

        my_kexinit = self._kexinit_payload()
        st.send(my_kexinit)
        peer_kexinit = st.recv()
        if peer_kexinit[0] != MSG_KEXINIT:
            raise SshError("expected KEXINIT")
        self._check_negotiation(peer_kexinit)

        eph = X25519PrivateKey.generate()
        q_mine = eph.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

        if self.server:
            i_c, i_s = peer_kexinit, my_kexinit
            v_c, v_s = self.peer_version, VERSION
            pkt = st.recv()
            r = Reader(pkt)
            if r.u8() != MSG_KEX_ECDH_INIT:
                raise SshError("expected KEX_ECDH_INIT")
            q_c = r.string()
            q_s = q_mine
            shared = eph.exchange(X25519PublicKey.from_public_bytes(q_c))
            k_s = ed25519_blob(self.host_key.public_key())
            h = self._exchange_hash(v_c, v_s, i_c, i_s, k_s, q_c, q_s,
                                    shared)
            sig = (ssh_string("ssh-ed25519") +
                   ssh_string(self.host_key.sign(h)))
            st.send(u8(MSG_KEX_ECDH_REPLY) + ssh_string(k_s) +
                    ssh_string(q_s) + ssh_string(sig))
        else:
            i_c, i_s = my_kexinit, peer_kexinit
            v_c, v_s = VERSION, self.peer_version
            st.send(u8(MSG_KEX_ECDH_INIT) + ssh_string(q_mine))
            r = Reader(st.recv())
            if r.u8() != MSG_KEX_ECDH_REPLY:
                raise SshError("expected KEX_ECDH_REPLY")
            k_s, q_s, sig_blob = r.string(), r.string(), r.string()
            q_c = q_mine
            shared = eph.exchange(X25519PublicKey.from_public_bytes(q_s))
            h = self._exchange_hash(v_c, v_s, i_c, i_s, k_s, q_c, q_s,
                                    shared)
            host_pub = ed25519_from_blob(k_s)
            sr = Reader(sig_blob)
            if sr.text() != "ssh-ed25519":
                raise SshError("unexpected signature algorithm")
            host_pub.verify(sr.string(), h)   # raises InvalidSignature
            if self.expected_host_key is not None:
                raw = host_pub.public_bytes(
                    serialization.Encoding.Raw,
                    serialization.PublicFormat.Raw)
                if raw != self.expected_host_key:
                    raise SshError("server host key mismatch")

        self.session_id = h
        self.host_key_blob = k_s

        # RFC 8731 §3: K is the X25519 output interpreted as an integer
        k_mpint = mpint(int.from_bytes(shared, "big"))
        st.send(u8(MSG_NEWKEYS))
        if st.recv() != u8(MSG_NEWKEYS):
            raise SshError("expected NEWKEYS")

        def dk(letter, n):
            return derive_key(hashlib.sha256, k_mpint, h, letter, h, n)

        iv_c2s, iv_s2c = dk(b"A", 16), dk(b"B", 16)
        key_c2s, key_s2c = dk(b"C", 16), dk(b"D", 16)
        mac_c2s, mac_s2c = dk(b"E", 32), dk(b"F", 32)
        if self.server:
            st.arm(key_s2c, iv_s2c, key_c2s, iv_c2s, mac_s2c, mac_c2s)
        else:
            st.arm(key_c2s, iv_c2s, key_s2c, iv_s2c, mac_c2s, mac_s2c)

    @staticmethod
    def _exchange_hash(v_c, v_s, i_c, i_s, k_s, q_c, q_s, shared) -> bytes:
        return hashlib.sha256(
            ssh_string(v_c) + ssh_string(v_s) +
            ssh_string(i_c) + ssh_string(i_s) +
            ssh_string(k_s) + ssh_string(q_c) + ssh_string(q_s) +
            mpint(int.from_bytes(shared, "big"))).digest()

    # -- post-kex IO -------------------------------------------------------

    def send(self, payload: bytes) -> None:
        self.stream.send(payload)

    def recv(self) -> bytes:
        """Next payload, with transport-generic messages handled here:
        IGNORE/DEBUG dropped, DISCONNECT surfaced, a mid-session
        KEXINIT (rekey request) rejected per the module policy."""
        while True:
            p = self.stream.recv()
            t = p[0]
            if t in (MSG_IGNORE, MSG_DEBUG):
                continue
            if t == MSG_DISCONNECT:
                r = Reader(p)
                r.u8()
                code = r.u32()
                raise SshError(f"peer disconnected ({code}): {r.text()}")
            if t == MSG_KEXINIT:
                raise SshError("peer requested rekey (unsupported)")
            return p

    def disconnect(self, code: int = 11, msg: str = "bye") -> None:
        """Best-effort SSH_MSG_DISCONNECT (code 11 = by-application)."""
        try:
            self.send(u8(MSG_DISCONNECT) + u32(code) + ssh_string(msg) +
                      ssh_string(""))
        except OSError:
            pass    # best-effort goodbye on a dying socket

    # -- service negotiation ----------------------------------------------

    def request_service(self, name: str) -> None:
        self.send(u8(MSG_SERVICE_REQUEST) + ssh_string(name))
        r = Reader(self.recv())
        if r.u8() != MSG_SERVICE_ACCEPT or r.text() != name:
            raise SshError(f"service {name} refused")

    def accept_service(self, allowed: str) -> None:
        r = Reader(self.recv())
        if r.u8() != MSG_SERVICE_REQUEST:
            raise SshError("expected SERVICE_REQUEST")
        name = r.text()
        if name != allowed:
            raise SshError(f"unsupported service {name}")
        self.send(u8(MSG_SERVICE_ACCEPT) + ssh_string(name))
