"""SFTP v3 protocol (draft-ietf-secsh-filexfer-02) over the filer —
the analog of weed/sftpd/sftp_filer.go (op table), sftp_file_reader.go
(ranged reads) and sftp_file_writer.go (buffer + flush on close).

Each authenticated session gets one `SftpHandlers` bound to its User;
every operation runs the sftp_permissions.go longest-prefix check
before touching the filer.
"""

from __future__ import annotations

import stat as statmod
import time

from ..filer.entry import Entry
from .ssh_wire import Reader, ssh_string, u32, u8
from . import users as perm

# packet types
FXP_INIT, FXP_VERSION = 1, 2
FXP_OPEN, FXP_CLOSE, FXP_READ, FXP_WRITE = 3, 4, 5, 6
FXP_LSTAT, FXP_FSTAT, FXP_SETSTAT, FXP_FSETSTAT = 7, 8, 9, 10
FXP_OPENDIR, FXP_READDIR, FXP_REMOVE, FXP_MKDIR, FXP_RMDIR = 11, 12, 13, 14, 15
FXP_REALPATH, FXP_STAT, FXP_RENAME, FXP_READLINK, FXP_SYMLINK = \
    16, 17, 18, 19, 20
FXP_STATUS, FXP_HANDLE, FXP_DATA, FXP_NAME, FXP_ATTRS = 101, 102, 103, 104, 105

# status codes
FX_OK, FX_EOF, FX_NO_SUCH_FILE, FX_PERMISSION_DENIED, FX_FAILURE = 0, 1, 2, 3, 4
FX_OP_UNSUPPORTED = 8

# open pflags
FXF_READ, FXF_WRITE, FXF_APPEND = 0x01, 0x02, 0x04
FXF_CREAT, FXF_TRUNC, FXF_EXCL = 0x08, 0x10, 0x20

# attr flags
ATTR_SIZE, ATTR_UIDGID, ATTR_PERMISSIONS, ATTR_ACMODTIME = 1, 2, 4, 8


def encode_attrs(entry: Entry) -> bytes:
    a = entry.attributes
    mode = a.mode & 0o7777
    mode |= statmod.S_IFDIR if entry.is_directory else statmod.S_IFREG
    return (u32(ATTR_SIZE | ATTR_UIDGID | ATTR_PERMISSIONS |
                ATTR_ACMODTIME) +
            entry.total_size().to_bytes(8, "big") +
            u32(a.uid) + u32(a.gid) + u32(mode) +
            u32(int(a.mtime)) + u32(int(a.mtime)))


def decode_attrs(r: Reader) -> dict:
    flags = r.u32()
    out = {}
    if flags & ATTR_SIZE:
        out["size"] = r.u64()
    if flags & ATTR_UIDGID:
        out["uid"], out["gid"] = r.u32(), r.u32()
    if flags & ATTR_PERMISSIONS:
        out["mode"] = r.u32()
    if flags & ATTR_ACMODTIME:
        out["atime"], out["mtime"] = r.u32(), r.u32()
    return out


class _OpenFile:
    """sftp_file_writer.go SeaweedSftpFileWriter: random-access writes
    land in a sparse buffer, flushed to the filer as one entry on
    close.  Reads on a read-opened handle go straight to the filer
    with Range headers (sftp_file_reader.go)."""

    def __init__(self, path: str, pflags: int, base: bytes):
        self.path = path
        self.pflags = pflags
        self.buf = bytearray(base)
        self.dirty = False

    def write_at(self, offset: int, data: bytes) -> None:
        if self.pflags & FXF_APPEND:
            offset = len(self.buf)
        if offset + len(data) > len(self.buf):
            self.buf.extend(b"\x00" * (offset + len(data) - len(self.buf)))
        self.buf[offset:offset + len(data)] = data
        self.dirty = True


class SftpHandlers:
    """One SFTP session: handle table + dispatch.  `fs` is a Filer or
    FilerClient (duck-typed, same as WebDavServer)."""

    def __init__(self, fs, user):
        self.fs = fs
        self.user = user
        self._handles: dict[bytes, object] = {}
        self._next = 0

    # -- plumbing ----------------------------------------------------------

    def _alloc(self, obj) -> bytes:
        h = f"h{self._next}".encode()
        self._next += 1
        self._handles[h] = obj
        return h

    def _resolve(self, raw: str) -> str:
        """Absolute-ise against the user's home (reference resolves
        relative paths against HomeDir), squeeze dot segments."""
        p = raw if raw.startswith("/") else \
            self.user.home_dir.rstrip("/") + "/" + raw
        parts = []
        for seg in p.split("/"):
            if seg in ("", "."):
                continue
            if seg == "..":
                if parts:
                    parts.pop()
                continue
            parts.append(seg)
        return "/" + "/".join(parts)

    @staticmethod
    def _status(req_id: int, code: int, msg: str = "") -> bytes:
        return (u8(FXP_STATUS) + u32(req_id) + u32(code) +
                ssh_string(msg or {FX_OK: "ok", FX_EOF: "eof"}.get(
                    code, "error")) + ssh_string(""))

    def _check(self, path: str, p: str) -> bool:
        return self.user.allowed(path, p)

    # -- dispatch ----------------------------------------------------------

    def handle(self, packet: bytes) -> bytes:
        """One request in, one response out."""
        r = Reader(packet)
        t = r.u8()
        if t == FXP_INIT:
            return u8(FXP_VERSION) + u32(3)
        req_id = r.u32()
        try:
            fn = {
                FXP_OPEN: self._open, FXP_CLOSE: self._close,
                FXP_READ: self._read, FXP_WRITE: self._write,
                FXP_LSTAT: self._stat, FXP_STAT: self._stat,
                FXP_FSTAT: self._fstat, FXP_SETSTAT: self._setstat,
                FXP_FSETSTAT: self._fsetstat,
                FXP_OPENDIR: self._opendir, FXP_READDIR: self._readdir,
                FXP_REMOVE: self._remove, FXP_MKDIR: self._mkdir,
                FXP_RMDIR: self._rmdir, FXP_REALPATH: self._realpath,
                FXP_RENAME: self._rename,
            }.get(t)
            if fn is None:
                return self._status(req_id, FX_OP_UNSUPPORTED,
                                    f"sftp op {t}")
            return fn(req_id, r)
        except FileNotFoundError as e:
            return self._status(req_id, FX_NO_SUCH_FILE, str(e))
        except PermissionError as e:
            return self._status(req_id, FX_PERMISSION_DENIED, str(e))
        except Exception as e:                  # noqa: BLE001
            return self._status(req_id, FX_FAILURE,
                                f"{type(e).__name__}: {e}")

    # -- file ops ----------------------------------------------------------

    def _open(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        pflags = r.u32()
        decode_attrs(r)
        entry = self.fs.find_entry(path)
        if pflags & (FXF_WRITE | FXF_APPEND):
            if not self._check(path, perm.PERM_WRITE):
                raise PermissionError(path)
        else:
            if not self._check(path, perm.PERM_READ):
                raise PermissionError(path)
        if entry and entry.is_directory:
            return self._status(req_id, FX_FAILURE, "is a directory")
        if entry is None:
            if not pflags & FXF_CREAT:
                raise FileNotFoundError(path)
            base = b""
        elif pflags & FXF_EXCL:
            return self._status(req_id, FX_FAILURE, "file exists")
        elif pflags & FXF_TRUNC:
            base = b""
        elif pflags & (FXF_WRITE | FXF_APPEND):
            base = self.fs.read_file(path)
        else:
            base = b""                   # read handles stream on demand
        f = _OpenFile(path, pflags, base)
        # creating an empty file must materialise it even if never
        # written (touch semantics), and TRUNC on an existing file must
        # persist the truncation even if nothing lands in the buffer
        f.dirty = (entry is None and bool(pflags & FXF_CREAT)) or \
            (entry is not None and bool(pflags & FXF_TRUNC))
        return u8(FXP_HANDLE) + u32(req_id) + ssh_string(self._alloc(f))

    def _write_preserving_attrs(self, path: str, data: bytes) -> None:
        """Content writes rebuild the entry with default attributes, so
        carry mode/uid/gid across the PUT — otherwise a chmod would
        silently revert on the next upload (mount/weedfs.py flush()
        does the same for the same reason)."""
        prev = self.fs.find_entry(path)
        self.fs.write_file(path, data)
        if prev is not None and hasattr(self.fs, "update_attrs"):
            a = prev.attributes
            self.fs.update_attrs(path, mode=a.mode, uid=a.uid, gid=a.gid)

    def _close(self, req_id: int, r: Reader) -> bytes:
        h = r.string()
        obj = self._handles.pop(h, None)
        if isinstance(obj, _OpenFile) and obj.dirty:
            self._write_preserving_attrs(obj.path, bytes(obj.buf))
        return self._status(req_id, FX_OK)

    def _read(self, req_id: int, r: Reader) -> bytes:
        h, offset, length = r.string(), r.u64(), r.u32()
        f = self._handles.get(h)
        if not isinstance(f, _OpenFile):
            return self._status(req_id, FX_FAILURE, "bad handle")
        if f.dirty:
            data = bytes(f.buf[offset:offset + length])
        else:
            data = self.fs.read_file(f.path, offset,
                                     min(length, 1 << 20))
        if not data:
            return self._status(req_id, FX_EOF)
        return u8(FXP_DATA) + u32(req_id) + ssh_string(data)

    def _write(self, req_id: int, r: Reader) -> bytes:
        h, offset, data = r.string(), r.u64(), r.string()
        f = self._handles.get(h)
        if not isinstance(f, _OpenFile):
            return self._status(req_id, FX_FAILURE, "bad handle")
        f.write_at(offset, data)
        return self._status(req_id, FX_OK)

    # -- stat family -------------------------------------------------------

    def _entry_or_raise(self, path: str) -> Entry:
        e = self.fs.find_entry(path)
        if e is None:
            raise FileNotFoundError(path)
        return e

    def _stat(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        if not self._check(path, perm.PERM_READ):
            raise PermissionError(path)
        e = self._entry_or_raise(path)
        return u8(FXP_ATTRS) + u32(req_id) + encode_attrs(e)

    def _fstat(self, req_id: int, r: Reader) -> bytes:
        f = self._handles.get(r.string())
        if not isinstance(f, _OpenFile):
            return self._status(req_id, FX_FAILURE, "bad handle")
        if f.dirty:
            # unflushed handle: size comes from the write buffer
            return (u8(FXP_ATTRS) + u32(req_id) +
                    u32(ATTR_SIZE) + len(f.buf).to_bytes(8, "big"))
        e = self._entry_or_raise(f.path)
        return u8(FXP_ATTRS) + u32(req_id) + encode_attrs(e)

    def _apply_setstat(self, path: str, attrs: dict) -> None:
        if not self._check(path, perm.PERM_WRITE):
            raise PermissionError(path)
        e = self._entry_or_raise(path)
        if "size" in attrs and not e.is_directory:
            data = self.fs.read_file(path)
            size = attrs["size"]
            data = data[:size] + b"\x00" * (size - len(data))
            self._write_preserving_attrs(path, data)
        if hasattr(self.fs, "update_attrs"):
            kw = {}
            if "mode" in attrs:
                kw["mode"] = attrs["mode"] & 0o7777
            if "mtime" in attrs:
                kw["mtime"] = attrs["mtime"]
            if "uid" in attrs:
                kw["uid"], kw["gid"] = attrs["uid"], attrs["gid"]
            if kw:
                self.fs.update_attrs(path, **kw)

    def _setstat(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        self._apply_setstat(path, decode_attrs(r))
        return self._status(req_id, FX_OK)

    def _fsetstat(self, req_id: int, r: Reader) -> bytes:
        f = self._handles.get(r.string())
        if not isinstance(f, _OpenFile):
            return self._status(req_id, FX_FAILURE, "bad handle")
        attrs = decode_attrs(r)
        if "size" in attrs and f.pflags & (FXF_WRITE | FXF_APPEND):
            size = attrs.pop("size")
            del f.buf[size:]
            if size > len(f.buf):
                f.buf.extend(b"\x00" * (size - len(f.buf)))
            f.dirty = True
        if attrs:
            self._apply_setstat(f.path, attrs)
        return self._status(req_id, FX_OK)

    # -- directory ops -----------------------------------------------------

    def _opendir(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        if not self._check(path, perm.PERM_LIST):
            raise PermissionError(path)
        e = self._entry_or_raise(path)
        if not e.is_directory:
            return self._status(req_id, FX_FAILURE, "not a directory")
        return (u8(FXP_HANDLE) + u32(req_id) +
                ssh_string(self._alloc(self._dir_batches(path))))

    def _dir_batches(self, path: str, batch: int = 100):
        """Page the filer listing and yield READDIR batches small
        enough that one FXP_NAME reply stays far under the 256 KB
        message cap common in clients; no entry-count ceiling."""
        last = ""
        while True:
            page = self.fs.list_directory(path, start_file=last,
                                          limit=batch)
            if not page:
                return
            yield page
            last = page[-1].name
            if len(page) < batch:
                return

    def _readdir(self, req_id: int, r: Reader) -> bytes:
        it = self._handles.get(r.string())
        if it is None:
            return self._status(req_id, FX_FAILURE, "bad handle")
        batch = next(it, None)
        if batch is None:
            return self._status(req_id, FX_EOF)
        out = u8(FXP_NAME) + u32(req_id) + u32(len(batch))
        for e in batch:
            kind = "d" if e.is_directory else "-"
            longname = (f"{kind}rw-r--r-- 1 {e.attributes.uid} "
                        f"{e.attributes.gid} {e.total_size()} "
                        f"{time.strftime('%b %d %H:%M')} {e.name}")
            out += (ssh_string(e.name) + ssh_string(longname) +
                    encode_attrs(e))
        return out

    def _mkdir(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        if not self._check(path, perm.PERM_MKDIR):
            raise PermissionError(path)
        self.fs.create_entry(Entry(path, is_directory=True))
        return self._status(req_id, FX_OK)

    def _rmdir(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        if not self._check(path, perm.PERM_DELETE):
            raise PermissionError(path)
        e = self._entry_or_raise(path)
        if not e.is_directory:
            return self._status(req_id, FX_FAILURE, "not a directory")
        if self.fs.list_directory(path, limit=1):
            return self._status(req_id, FX_FAILURE,
                                "directory not empty")
        self.fs.delete_entry(path)
        return self._status(req_id, FX_OK)

    def _remove(self, req_id: int, r: Reader) -> bytes:
        path = self._resolve(r.text())
        if not self._check(path, perm.PERM_DELETE):
            raise PermissionError(path)
        e = self._entry_or_raise(path)
        if e.is_directory:
            return self._status(req_id, FX_FAILURE, "is a directory")
        self.fs.delete_entry(path)
        return self._status(req_id, FX_OK)

    def _realpath(self, req_id: int, r: Reader) -> bytes:
        raw = r.text()
        path = self.user.home_dir if raw in (".", "") \
            else self._resolve(raw)
        fake = Entry(path, is_directory=True)
        return (u8(FXP_NAME) + u32(req_id) + u32(1) +
                ssh_string(path) + ssh_string(path) +
                encode_attrs(fake))

    def _rename(self, req_id: int, r: Reader) -> bytes:
        old = self._resolve(r.text())
        new = self._resolve(r.text())
        if not (self._check(old, perm.PERM_RENAME) and
                self._check(new, perm.PERM_WRITE)):
            raise PermissionError(f"{old} -> {new}")
        self.fs.rename(old, new)
        return self._status(req_id, FX_OK)
