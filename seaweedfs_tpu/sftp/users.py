"""SFTP user store (reference: weed/sftpd/user/user.go + filestore.go).

A JSON file of users, each with password and/or authorized public
keys, a home directory, per-path permission lists, and uid/gid for
file ownership — the same schema the reference's FileStore persists.
One deviation: passwords may be stored as `passwordSha256` (hex of
salt:hash) instead of the reference's plaintext `password`; both are
accepted so reference user files load unchanged.
"""

from __future__ import annotations

import hashlib
import hmac
import json
import os
import secrets
import threading

# sftp_permissions.go permission vocabulary
PERM_READ = "read"
PERM_WRITE = "write"
PERM_LIST = "list"
PERM_DELETE = "delete"
PERM_MKDIR = "mkdir"
PERM_RENAME = "rename"
PERM_ALL = "*"

_WRITE_CLASS = {PERM_WRITE, PERM_DELETE, PERM_MKDIR, PERM_RENAME}


def _hash_password(password: str, salt: str | None = None) -> str:
    salt = salt or secrets.token_hex(8)
    digest = hashlib.sha256((salt + password).encode()).hexdigest()
    return f"{salt}:{digest}"


class User:
    """user/user.go User."""

    def __init__(self, username: str, home_dir: str = "",
                 uid: int | None = None, gid: int | None = None):
        self.username = username
        self.home_dir = home_dir or f"/home/{username}"
        # user.go NewUser: random 1000..60000 keeps out of system range
        rid = 1000 + secrets.randbelow(59000)
        self.uid = uid if uid is not None else rid
        self.gid = gid if gid is not None else self.uid
        self.password_plain = ""          # reference-compatible field
        self.password_hashed = ""         # salt:sha256 deviation
        self.public_keys: list[str] = []  # OpenSSH "ssh-ed25519 <b64>"
        self.permissions: dict[str, list[str]] = {}

    def set_password(self, password: str) -> None:
        self.password_hashed = _hash_password(password)
        self.password_plain = ""

    def check_password(self, password: str) -> bool:
        if self.password_hashed:
            salt, _ = self.password_hashed.split(":", 1)
            return hmac.compare_digest(
                _hash_password(password, salt), self.password_hashed)
        if self.password_plain:
            return hmac.compare_digest(self.password_plain, password)
        return False

    def add_public_key(self, key: str) -> None:
        key = " ".join(key.split()[:2])   # strip the comment field
        if key not in self.public_keys:
            self.public_keys.append(key)

    def has_public_key(self, alg: str, blob_b64: str) -> bool:
        return f"{alg} {blob_b64}" in self.public_keys

    # -- permissions (sftp_permissions.go CheckFilePermission) ------------

    def allowed(self, path: str, perm: str) -> bool:
        """sftp_permissions.go CheckFilePermission order: the home
        directory implicitly grants everything FIRST (so a broad "/"
        rule cannot lock a user out of their own home), then the most
        specific configured path containing `path` decides."""
        home = self.home_dir.rstrip("/")
        if home and (path == home or path.startswith(home + "/")):
            return True
        best, best_len = None, -1
        for p, perms in self.permissions.items():
            cp = p.rstrip("/") or "/"
            if path == cp or path.startswith(cp + "/") or cp == "/":
                if len(cp) > best_len:
                    best, best_len = perms, len(cp)
        if best is None:
            return False
        return PERM_ALL in best or perm in best or (
            "readwrite" in best and
            (perm in _WRITE_CLASS or perm in (PERM_READ, PERM_LIST)))

    def to_json(self) -> dict:
        return {"username": self.username, "homeDir": self.home_dir,
                "uid": self.uid, "gid": self.gid,
                "password": self.password_plain,
                "passwordSha256": self.password_hashed,
                "publicKeys": self.public_keys,
                "permissions": self.permissions}

    @classmethod
    def from_json(cls, d: dict) -> "User":
        u = cls(d["username"], d.get("homeDir", ""),
                d.get("uid"), d.get("gid"))
        u.password_plain = d.get("password", "")
        u.password_hashed = d.get("passwordSha256", "")
        u.public_keys = list(d.get("publicKeys", []))
        u.permissions = {k: list(v)
                         for k, v in d.get("permissions", {}).items()}
        return u


class UserStore:
    """user/filestore.go: load-at-start, save-on-mutate JSON store."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._users: dict[str, User] = {}
        self._lock = threading.Lock()
        if path and os.path.exists(path):
            with open(path) as f:
                for d in json.load(f):
                    u = User.from_json(d)
                    self._users[u.username] = u

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump([u.to_json() for u in self._users.values()],
                          f, indent=1)
            os.replace(tmp, self.path)

    def get(self, username: str) -> User | None:
        return self._users.get(username)

    def put(self, user: User) -> None:
        self._users[user.username] = user
        self.save()

    def delete(self, username: str) -> None:
        self._users.pop(username, None)
        self.save()

    def __iter__(self):
        return iter(self._users.values())
