"""SFTP gateway (reference: weed/sftpd/).

The reference wraps golang.org/x/crypto/ssh + github.com/pkg/sftp and
adds the seaweed parts: a JSON user store with per-path permissions
(sftpd/user/filestore.go), password/publickey auth (sftpd/auth/), and
filer-backed file handlers (sftpd/sftp_filer.go).  This image has no
SSH library at all (no paramiko/asyncssh), so the transport itself is
implemented here from the RFCs:

- ssh_wire:   RFC 4251 types + RFC 4253 binary packet protocol
- transport:  version exchange, curve25519-sha256 kex (RFC 8731),
              ssh-ed25519 host keys, aes128-ctr + hmac-sha2-256,
              both server and client roles
- users:      user store (sftpd/user/user.go, filestore.go)
- handlers:   SFTP v3 op table over the filer (sftpd/sftp_filer.go)
- server:     accept loop + userauth + session channels + subsystem
- client:     minimal SSH/SFTP client (tests + `weed sftp.get/put`)
"""

from .server import SftpService
from .users import User, UserStore

__all__ = ["SftpService", "User", "UserStore"]
