"""Minimal SSH/SFTP client over the same transport.

The image has no OpenSSH or paramiko, so interop tests and the
`sftp.get/put` CLI drive the gateway with this client (the reference's
sftp_server_test.go does the same with pkg/sftp's client).
"""

from __future__ import annotations

import base64
import socket

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey)
from cryptography.hazmat.primitives import serialization

from . import handlers as fx
from . import server as msg
from .ssh_wire import Reader, ssh_bool, ssh_string, u32, u8
from .transport import SshError, Transport


def openssh_pubkey(key: Ed25519PrivateKey, comment: str = "") -> str:
    """'ssh-ed25519 <base64-blob> comment' authorized_keys line."""
    raw = key.public_key().public_bytes(
        serialization.Encoding.Raw, serialization.PublicFormat.Raw)
    blob = ssh_string("ssh-ed25519") + ssh_string(raw)
    b64 = base64.b64encode(blob).decode()
    return f"ssh-ed25519 {b64} {comment}".strip()


class SftpError(OSError):
    def __init__(self, code: int, text: str):
        super().__init__(f"sftp status {code}: {text}")
        self.code = code


class SftpClient:
    def __init__(self, host: str, port: int, username: str,
                 password: str | None = None,
                 key: Ed25519PrivateKey | None = None,
                 expected_host_key: bytes | None = None,
                 timeout: float = 30.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        self.tr = Transport(self.sock, server=False,
                            expected_host_key=expected_host_key)
        self.tr.request_service("ssh-userauth")
        self._auth(username, password, key)
        self._open_channel()
        self._req_id = 0
        self._inbuf = b""
        v = self._rpc_raw(u8(fx.FXP_INIT) + u32(3))
        r = Reader(v)
        if r.u8() != fx.FXP_VERSION or r.u32() != 3:
            raise SshError("sftp version negotiation failed")

    def close(self) -> None:
        try:
            self.tr.send(u8(msg.MSG_CHANNEL_CLOSE) + u32(0))
        except OSError:
            pass    # best-effort goodbye on a dying transport
        self.sock.close()

    # -- ssh plumbing ------------------------------------------------------

    def _auth(self, username, password, key) -> None:
        if key is not None:
            raw = key.public_key().public_bytes(
                serialization.Encoding.Raw,
                serialization.PublicFormat.Raw)
            blob = ssh_string("ssh-ed25519") + ssh_string(raw)
            body = (ssh_string(username) +
                    ssh_string("ssh-connection") +
                    ssh_string("publickey") + ssh_bool(True) +
                    ssh_string("ssh-ed25519") + ssh_string(blob))
            signed = ssh_string(self.tr.session_id) + \
                u8(msg.MSG_USERAUTH_REQUEST) + body
            sig = (ssh_string("ssh-ed25519") +
                   ssh_string(key.sign(signed)))
            self.tr.send(u8(msg.MSG_USERAUTH_REQUEST) + body +
                         ssh_string(sig))
        else:
            self.tr.send(u8(msg.MSG_USERAUTH_REQUEST) +
                         ssh_string(username) +
                         ssh_string("ssh-connection") +
                         ssh_string("password") + ssh_bool(False) +
                         ssh_string(password or ""))
        while True:
            r = Reader(self.tr.recv())
            t = r.u8()
            if t == msg.MSG_USERAUTH_SUCCESS:
                return
            if t == msg.MSG_USERAUTH_BANNER:
                continue
            if t == msg.MSG_USERAUTH_FAILURE:
                raise PermissionError(
                    f"auth failed (server allows {r.name_list()})")
            raise SshError(f"unexpected userauth reply {t}")

    def _open_channel(self) -> None:
        self.recv_window = msg.WINDOW
        self.tr.send(u8(msg.MSG_CHANNEL_OPEN) + ssh_string("session") +
                     u32(0) + u32(msg.WINDOW) + u32(msg.MAX_PACKET))
        r = Reader(self.tr.recv())
        if r.u8() != msg.MSG_CHANNEL_OPEN_CONFIRMATION:
            raise SshError("channel open refused")
        r.u32()
        self.chan_peer = r.u32()
        self.peer_window = r.u32()
        self.peer_max_packet = min(r.u32(), 1 << 20)
        self.tr.send(u8(msg.MSG_CHANNEL_REQUEST) + u32(self.chan_peer) +
                     ssh_string("subsystem") + ssh_bool(True) +
                     ssh_string("sftp"))
        r = Reader(self.tr.recv())
        if r.u8() != msg.MSG_CHANNEL_SUCCESS:
            raise SshError("sftp subsystem refused")

    def _send_data(self, data: bytes) -> None:
        step = max(1024, self.peer_max_packet - 16)
        for i in range(0, len(data), step):
            chunk = data[i:i + step]
            while self.peer_window < len(chunk):
                self._pump()
            self.peer_window -= len(chunk)
            self.tr.send(u8(msg.MSG_CHANNEL_DATA) +
                         u32(self.chan_peer) + ssh_string(chunk))

    def _pump(self) -> None:
        """Process one incoming connection-layer message."""
        r = Reader(self.tr.recv())
        t = r.u8()
        if t == msg.MSG_CHANNEL_DATA:
            r.u32()
            data = r.string()
            self._inbuf += data
            self.recv_window -= len(data)
            if self.recv_window < msg.WINDOW // 2:
                grow = msg.WINDOW - self.recv_window
                self.tr.send(u8(msg.MSG_CHANNEL_WINDOW_ADJUST) +
                             u32(self.chan_peer) + u32(grow))
                self.recv_window += grow
        elif t == msg.MSG_CHANNEL_WINDOW_ADJUST:
            r.u32()
            self.peer_window += r.u32()
        elif t in (msg.MSG_CHANNEL_EOF, msg.MSG_CHANNEL_CLOSE):
            raise ConnectionError("sftp channel closed")
        else:
            raise SshError(f"unexpected channel message {t}")

    def _rpc_raw(self, body: bytes) -> bytes:
        self._send_data(u32(len(body)) + body)
        while True:
            if len(self._inbuf) >= 4:
                n = int.from_bytes(self._inbuf[:4], "big")
                if len(self._inbuf) >= 4 + n:
                    resp = self._inbuf[4:4 + n]
                    self._inbuf = self._inbuf[4 + n:]
                    return resp
            self._pump()

    def _rpc(self, t: int, body: bytes) -> Reader:
        self._req_id += 1
        resp = self._rpc_raw(u8(t) + u32(self._req_id) + body)
        r = Reader(resp)
        rt = r.u8()
        rid = r.u32()
        if rid != self._req_id:
            raise SshError(f"response id {rid} != {self._req_id}")
        if rt == fx.FXP_STATUS:
            code = r.u32()
            text = r.text()
            if code not in (fx.FX_OK, fx.FX_EOF):
                raise SftpError(code, text)
            r.code = code  # type: ignore[attr-defined]
        r.type = rt        # type: ignore[attr-defined]
        return r

    # -- sftp surface ------------------------------------------------------

    def open(self, path: str, pflags: int) -> bytes:
        r = self._rpc(fx.FXP_OPEN, ssh_string(path) + u32(pflags) +
                      u32(0))
        if r.type != fx.FXP_HANDLE:
            raise SftpError(fx.FX_FAILURE, "no handle")
        return r.string()

    def close_handle(self, h: bytes) -> None:
        self._rpc(fx.FXP_CLOSE, ssh_string(h))

    def write_file(self, path: str, data: bytes,
                   chunk: int = 24 * 1024) -> None:
        h = self.open(path, fx.FXF_WRITE | fx.FXF_CREAT | fx.FXF_TRUNC)
        try:
            for off in range(0, len(data), chunk):
                self._rpc(fx.FXP_WRITE, ssh_string(h) +
                          off.to_bytes(8, "big") +
                          ssh_string(data[off:off + chunk]))
        finally:
            self.close_handle(h)

    def read_file(self, path: str, chunk: int = 24 * 1024) -> bytes:
        h = self.open(path, fx.FXF_READ)
        out = bytearray()
        try:
            while True:
                r = self._rpc(fx.FXP_READ, ssh_string(h) +
                              len(out).to_bytes(8, "big") + u32(chunk))
                if r.type == fx.FXP_STATUS:   # EOF
                    break
                out += r.string()
        finally:
            self.close_handle(h)
        return bytes(out)

    def write_at(self, h: bytes, offset: int, data: bytes) -> None:
        self._rpc(fx.FXP_WRITE, ssh_string(h) +
                  offset.to_bytes(8, "big") + ssh_string(data))

    def listdir(self, path: str) -> list[tuple[str, dict]]:
        h = self._rpc(fx.FXP_OPENDIR, ssh_string(path)).string()
        names = []
        try:
            while True:
                r = self._rpc(fx.FXP_READDIR, ssh_string(h))
                if r.type == fx.FXP_STATUS:
                    break
                for _ in range(r.u32()):
                    name = r.text()
                    r.string()               # longname
                    names.append((name, _parse_attrs(r)))
        finally:
            self.close_handle(h)
        return names

    def stat(self, path: str) -> dict:
        r = self._rpc(fx.FXP_STAT, ssh_string(path))
        if r.type != fx.FXP_ATTRS:
            raise SftpError(fx.FX_FAILURE, "no attrs")
        return _parse_attrs(r)

    def setstat(self, path: str, mode: int | None = None,
                size: int | None = None) -> None:
        flags, body = 0, b""
        if size is not None:
            flags |= fx.ATTR_SIZE
            body += size.to_bytes(8, "big")
        if mode is not None:
            flags |= fx.ATTR_PERMISSIONS
            body += u32(mode)
        self._rpc(fx.FXP_SETSTAT, ssh_string(path) + u32(flags) + body)

    def mkdir(self, path: str) -> None:
        self._rpc(fx.FXP_MKDIR, ssh_string(path) + u32(0))

    def rmdir(self, path: str) -> None:
        self._rpc(fx.FXP_RMDIR, ssh_string(path))

    def remove(self, path: str) -> None:
        self._rpc(fx.FXP_REMOVE, ssh_string(path))

    def rename(self, old: str, new: str) -> None:
        self._rpc(fx.FXP_RENAME, ssh_string(old) + ssh_string(new))

    def realpath(self, path: str) -> str:
        r = self._rpc(fx.FXP_REALPATH, ssh_string(path))
        r.u32()
        return r.text()


def _parse_attrs(r: Reader) -> dict:
    flags = r.u32()
    out = {}
    if flags & fx.ATTR_SIZE:
        out["size"] = r.u64()
    if flags & fx.ATTR_UIDGID:
        out["uid"], out["gid"] = r.u32(), r.u32()
    if flags & fx.ATTR_PERMISSIONS:
        out["mode"] = r.u32()
    if flags & fx.ATTR_ACMODTIME:
        out["atime"], out["mtime"] = r.u32(), r.u32()
    return out
