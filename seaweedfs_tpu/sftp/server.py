"""SFTP service: TCP accept loop → SSH transport → userauth →
session channel → sftp subsystem (reference: weed/sftpd/sftp_server.go
+ sftp_service.go + auth/).

Auth mirrors auth/password.go and auth/publickey.go: password checks
against the user store; publickey first answers the signature-less
probe with PK_OK, then verifies an ed25519 signature over
session_id || userauth-request (RFC 4252 §7).
"""

from __future__ import annotations

import base64
import socket
import threading

from cryptography.exceptions import InvalidSignature
from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey, Ed25519PublicKey)
from cryptography.hazmat.primitives import serialization

from .handlers import SftpHandlers
from .ssh_wire import Reader, name_list, ssh_bool, ssh_string, u32, u8
from .transport import SshError, Transport
from .users import UserStore

# RFC 4252
MSG_USERAUTH_REQUEST = 50
MSG_USERAUTH_FAILURE = 51
MSG_USERAUTH_SUCCESS = 52
MSG_USERAUTH_BANNER = 53
MSG_USERAUTH_PK_OK = 60

# RFC 4254
MSG_CHANNEL_OPEN = 90
MSG_CHANNEL_OPEN_CONFIRMATION = 91
MSG_CHANNEL_OPEN_FAILURE = 92
MSG_CHANNEL_WINDOW_ADJUST = 93
MSG_CHANNEL_DATA = 94
MSG_CHANNEL_EOF = 96
MSG_CHANNEL_CLOSE = 97
MSG_CHANNEL_REQUEST = 98
MSG_CHANNEL_SUCCESS = 99
MSG_CHANNEL_FAILURE = 100

WINDOW = 1 << 22
MAX_PACKET = 1 << 15


class SftpService:
    """sftp_service.go SFTPService: options + user store + accept loop.
    `fs` is an in-process Filer or a FilerClient (weed sftp -filer)."""

    def __init__(self, fs, user_store: UserStore,
                 host_key: Ed25519PrivateKey | None = None,
                 port: int = 0, ip: str = "127.0.0.1",
                 auth_methods: tuple = ("password", "publickey"),
                 max_auth_tries: int = 6, banner: str = "",
                 ldap=None):
        self.fs = fs
        self.users = user_store
        # optional LDAP provider (iam/ldap.py): password auth consults
        # the directory when the local store has no such user — the
        # reference's ldap identity provider role (iam/ldap/
        # ldap_provider.go) applied to the sftp gateway
        self.ldap = ldap
        self.host_key = host_key or Ed25519PrivateKey.generate()
        self.port = port
        self.ip = ip
        self.auth_methods = list(auth_methods)
        self.max_auth_tries = max_auth_tries
        self.banner = banner
        self._sock = None
        self._threads: list[threading.Thread] = []
        self._stopping = False

    @property
    def host_public_raw(self) -> bytes:
        return self.host_key.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

    def start(self) -> "SftpService":
        self._sock = socket.create_server((self.ip, self.port))
        self.port = self._sock.getsockname()[1]
        t = threading.Thread(target=self._accept_loop,
                             name="sftp-accept", daemon=True)
        t.start()
        self._threads.append(t)
        return self

    def stop(self) -> None:
        self._stopping = True
        try:
            self._sock.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        while not self._stopping:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            # daemon threads, deliberately untracked: appending every
            # connection's thread would leak one object per session
            # over the gateway's lifetime
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(60)
            tr = Transport(conn, server=True, host_key=self.host_key)
            tr.accept_service("ssh-userauth")
            user = self._authenticate(tr)
            if user is None:
                return
            _Session(tr, SftpHandlers(self.fs, user)).run()
        except (SshError, ConnectionError, ValueError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- userauth (RFC 4252; auth/password.go, auth/publickey.go) ---------

    def _authenticate(self, tr: Transport):
        if self.banner:
            tr.send(u8(MSG_USERAUTH_BANNER) + ssh_string(self.banner) +
                    ssh_string(""))
        tries = 0
        while tries < self.max_auth_tries:
            r = Reader(tr.recv())
            if r.u8() != MSG_USERAUTH_REQUEST:
                raise SshError("expected USERAUTH_REQUEST")
            username, service, method = r.text(), r.text(), r.text()
            if service != "ssh-connection":
                raise SshError(f"unsupported service {service}")
            user = self.users.get(username)
            ok = False
            if method == "none":
                # method discovery (RFC 4252 §5.2): answer with the
                # available list without burning a try
                tr.send(u8(MSG_USERAUTH_FAILURE) +
                        name_list(self.auth_methods) + ssh_bool(False))
                continue
            tries += 1
            if method == "password" and "password" in self.auth_methods:
                r.boolean()
                password = r.text()
                ok = user is not None and user.check_password(password)
                if not ok and user is None and self.ldap is not None:
                    # directory-backed users (iam/ldap.py): the bind
                    # IS the credential check on every login — nothing
                    # is written to the local user store, so the
                    # directory stays the source of truth and repeat
                    # logins re-bind.  An LDAP OUTAGE (LdapError or
                    # socket-level OSError) reads as auth failure, not
                    # a dropped session; the try still burns.
                    from ..iam.ldap import LdapError
                    try:
                        ident = self.ldap.authenticate(username,
                                                       password)
                    except (LdapError, OSError):
                        ident = None
                    if ident is not None:
                        from .users import User
                        user = User(username)  # session-scoped only
                        ok = True
            elif (method == "publickey" and
                  "publickey" in self.auth_methods):
                has_sig = r.boolean()
                alg = r.text()
                blob = r.string()
                known = (user is not None and alg == "ssh-ed25519" and
                         user.has_public_key(
                             alg, base64.b64encode(blob).decode()))
                if not has_sig:
                    # signature-less probe (RFC 4252 §7) — not a real
                    # attempt: clients cycling an agent's keys need
                    # probes free, or the matching key is never reached
                    tries -= 1
                    if known:
                        tr.send(u8(MSG_USERAUTH_PK_OK) +
                                ssh_string(alg) + ssh_string(blob))
                        continue
                    # fall through to FAILURE so the client moves on
                if known and has_sig:
                    sig = r.string()
                    sr = Reader(sig)
                    if sr.text() == "ssh-ed25519":
                        signed = (ssh_string(tr.session_id) +
                                  u8(MSG_USERAUTH_REQUEST) +
                                  ssh_string(username) +
                                  ssh_string(service) +
                                  ssh_string("publickey") +
                                  ssh_bool(True) + ssh_string(alg) +
                                  ssh_string(blob))
                        pub = Ed25519PublicKey.from_public_bytes(
                            _pub_raw_from_blob(blob))
                        try:
                            pub.verify(sr.string(), signed)
                            ok = True
                        except InvalidSignature:
                            ok = False
            if ok:
                tr.send(u8(MSG_USERAUTH_SUCCESS))
                return user
            tr.send(u8(MSG_USERAUTH_FAILURE) +
                    name_list(self.auth_methods) + ssh_bool(False))
        return None


def _pub_raw_from_blob(blob: bytes) -> bytes:
    r = Reader(blob)
    if r.text() != "ssh-ed25519":
        raise ValueError("not an ed25519 key blob")
    return r.string()


class _Session:
    """One authenticated connection's channel layer: a single session
    channel carrying the sftp subsystem (RFC 4254 §5-6)."""

    def __init__(self, tr: Transport, handlers: SftpHandlers):
        self.tr = tr
        self.handlers = handlers
        self.chan_peer = None
        self.peer_window = 0
        self.peer_max_packet = MAX_PACKET
        self.recv_window = WINDOW
        self._inbuf = b""

    def run(self) -> None:
        while True:
            r = Reader(self.tr.recv())
            t = r.u8()
            if t == MSG_CHANNEL_OPEN:
                self._open(r)
            elif t == MSG_CHANNEL_REQUEST:
                self._request(r)
            elif t == MSG_CHANNEL_DATA:
                r.u32()
                self._data(r.string())
            elif t == MSG_CHANNEL_WINDOW_ADJUST:
                r.u32()
                self.peer_window += r.u32()
            elif t in (MSG_CHANNEL_EOF, MSG_CHANNEL_CLOSE):
                if t == MSG_CHANNEL_CLOSE:
                    self.tr.send(u8(MSG_CHANNEL_CLOSE) + u32(
                        self.chan_peer or 0))
                    return
            else:
                raise SshError(f"unexpected channel message {t}")

    def _open(self, r: Reader) -> None:
        ctype = r.text()
        peer_id = r.u32()
        self.peer_window = r.u32()
        self.peer_max_packet = min(r.u32(), 1 << 20)
        if ctype != "session" or self.chan_peer is not None:
            self.tr.send(u8(MSG_CHANNEL_OPEN_FAILURE) + u32(peer_id) +
                         u32(1) + ssh_string("only one session") +
                         ssh_string(""))
            return
        self.chan_peer = peer_id
        self.tr.send(u8(MSG_CHANNEL_OPEN_CONFIRMATION) + u32(peer_id) +
                     u32(0) + u32(WINDOW) + u32(MAX_PACKET))

    def _request(self, r: Reader) -> None:
        r.u32()
        rtype = r.text()
        want_reply = r.boolean()
        ok = rtype == "subsystem" and r.text() == "sftp"
        if want_reply:
            self.tr.send(u8(MSG_CHANNEL_SUCCESS if ok else
                            MSG_CHANNEL_FAILURE) +
                         u32(self.chan_peer))

    def _data(self, data: bytes) -> None:
        self.recv_window -= len(data)
        if self.recv_window < WINDOW // 2:
            grow = WINDOW - self.recv_window
            self.tr.send(u8(MSG_CHANNEL_WINDOW_ADJUST) +
                         u32(self.chan_peer) + u32(grow))
            self.recv_window += grow
        self._inbuf += data
        # SFTP packets: uint32 length || body — may arrive split or
        # coalesced across CHANNEL_DATA boundaries
        while len(self._inbuf) >= 4:
            n = int.from_bytes(self._inbuf[:4], "big")
            if len(self._inbuf) < 4 + n:
                break
            body, self._inbuf = self._inbuf[4:4 + n], self._inbuf[4 + n:]
            resp = self.handlers.handle(body)
            self._send_sftp(resp)

    def _send_sftp(self, resp: bytes) -> None:
        out = u32(len(resp)) + resp
        # respect the peer's max packet; window handling is lenient on
        # the server side (our responses are small except DATA, and the
        # client grows its window aggressively)
        step = max(1024, self.peer_max_packet - 16)
        for i in range(0, len(out), step):
            self.tr.send(u8(MSG_CHANNEL_DATA) + u32(self.chan_peer) +
                         ssh_string(out[i:i + step]))
