"""Pluggable filer metadata stores (weed/filer/filerstore.go).

The reference ships 24 backends; we ship the two archetypes the rest
derive from: an in-memory dict store (tests / ephemeral) and a SQLite
store (the abstract_sql family — one (dirhash, name)-keyed table, the
same schema shape as filer/abstract_sql/abstract_sql_store.go) giving a
durable single-node default with real prefix-scans.
"""

from __future__ import annotations

import json
import sqlite3
import threading

from .entry import Entry, normalize_path


class FilerStore:
    """Interface: insert/update/find/delete/list, per directory."""

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry | None:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryStore(FilerStore):
    def __init__(self):
        self._by_dir: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._by_dir.setdefault(entry.parent, {})[entry.name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            return self._by_dir.get(parent or "/", {}).get(name)

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._by_dir.get(parent or "/", {}).pop(name, None)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._by_dir.pop(path, None)
            for d in [d for d in self._by_dir
                      if d.startswith(path + "/")]:
                self._by_dir.pop(d, None)

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        with self._lock:
            names = sorted(self._by_dir.get(dir_path, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_file:
                    if n < start_file or \
                            (n == start_file and not include_start):
                        continue
                out.append(self._by_dir[dir_path][n])
                if len(out) >= limit:
                    break
            return out


class SqliteStore(FilerStore):
    """abstract_sql-family store: one table keyed (directory, name)."""

    def __init__(self, path: str = ":memory:"):
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.RLock()
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory TEXT NOT NULL,"
            " name TEXT NOT NULL,"
            " meta TEXT NOT NULL,"
            " PRIMARY KEY (directory, name))")
        self._db.execute(
            "CREATE INDEX IF NOT EXISTS filemeta_dir "
            "ON filemeta (directory, name)")
        self._db.commit()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO filemeta "
                "(directory, name, meta) VALUES (?, ?, ?)",
                (entry.parent, entry.name,
                 json.dumps(entry.to_json())))
            self._db.commit()

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            row = self._db.execute(
                "SELECT meta FROM filemeta WHERE directory=? AND name=?",
                (parent or "/", name)).fetchone()
        return Entry.from_json(json.loads(row[0])) if row else None

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._db.execute(
                "DELETE FROM filemeta WHERE directory=? AND name=?",
                (parent or "/", name))
            self._db.commit()

    @staticmethod
    def _like_escape(s: str) -> str:
        r"""Escape LIKE wildcards; every LIKE here uses ESCAPE '\'."""
        return s.replace("\\", "\\\\").replace("%", r"\%") \
                .replace("_", r"\_")

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._db.execute(
                "DELETE FROM filemeta WHERE directory=? OR "
                r"directory LIKE ? ESCAPE '\'",
                (path, self._like_escape(path) + "/%"))
            self._db.commit()

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        op = ">=" if include_start else ">"
        q = ("SELECT meta FROM filemeta WHERE directory=? AND "
             f"name {op} ? ")
        args: list = [dir_path, start_file]
        if prefix:
            q += r"AND name LIKE ? ESCAPE '\' "
            args.append(self._like_escape(prefix) + "%")
        q += "ORDER BY name LIMIT ?"
        args.append(limit)
        with self._lock:
            rows = self._db.execute(q, args).fetchall()
        return [Entry.from_json(json.loads(r[0])) for r in rows]

    def close(self) -> None:
        self._db.close()
