"""Pluggable filer metadata stores (weed/filer/filerstore.go).

The reference ships 24 backends; we ship the two archetypes the rest
derive from: an in-memory dict store (tests / ephemeral) and a SQLite
store (the abstract_sql family — one (dirhash, name)-keyed table, the
same schema shape as filer/abstract_sql/abstract_sql_store.go) giving a
durable single-node default with real prefix-scans.
"""

from __future__ import annotations

import json

import threading

from .entry import Entry, normalize_path


class FilerStore:
    """Interface: insert/update/find/delete/list, per directory."""

    # the meta plane (filer/meta_plane.py) treats the metalog as the
    # filer's WAL and this store as an async checkpoint — only stores
    # that are DURABLE and LOCAL opt in (a remote store shared with a
    # filer we cannot hear from must stay synchronously committed, or
    # that filer would read our acked writes only after our applier
    # got to them)
    supports_meta_plane = False

    def insert_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def apply_events(self, records: list) -> None:
        """Meta-plane checkpoint applier hook: apply a batch of
        metalog events.  `records` = [(op, new_path, raw_meta,
        new_dict, old_path)] in log order.  The base implementation
        loops the CRUD ops; stores with a transaction boundary
        override to commit the whole batch ONCE."""
        for op, npath, _raw, new, opath in records:
            if npath:
                self.insert_entry(Entry.from_json(new))
            if opath and op in ("delete", "rename") and opath != npath:
                self.delete_entry(opath)

    def update_entry(self, entry: Entry) -> None:
        raise NotImplementedError

    def find_entry(self, path: str) -> Entry | None:
        raise NotImplementedError

    def delete_entry(self, path: str) -> None:
        raise NotImplementedError

    def delete_folder_children(self, path: str) -> None:
        raise NotImplementedError

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class MemoryStore(FilerStore):
    def __init__(self):
        self._by_dir: dict[str, dict[str, Entry]] = {}
        self._lock = threading.RLock()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._by_dir.setdefault(entry.parent, {})[entry.name] = entry

    update_entry = insert_entry

    def find_entry(self, path: str) -> Entry | None:
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            return self._by_dir.get(parent or "/", {}).get(name)

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._by_dir.get(parent or "/", {}).pop(name, None)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._by_dir.pop(path, None)
            for d in [d for d in self._by_dir
                      if d.startswith(path + "/")]:
                self._by_dir.pop(d, None)

    def list_directory_entries(self, dir_path: str, start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        with self._lock:
            names = sorted(self._by_dir.get(dir_path, {}))
            out = []
            for n in names:
                if prefix and not n.startswith(prefix):
                    continue
                if start_file:
                    if n < start_file or \
                            (n == start_file and not include_start):
                        continue
                out.append(self._by_dir[dir_path][n])
                if len(out) >= limit:
                    break
            return out


# imported AFTER FilerStore exists (abstract_sql imports it back)
from .abstract_sql import (AbstractSqlStore, SqlDialect,  # noqa: E402
                           SqliteDialect)


class SqliteStore(AbstractSqlStore):
    """abstract_sql-family store: one table keyed (directory, name).
    The always-available engine of the AbstractSqlStore family
    (filer/abstract_sql.py; reference weed/filer/sqlite/ over
    weed/filer/abstract_sql/)."""

    def __init__(self, path: str = ":memory:"):
        dialect = SqliteDialect()
        # file-backed stores get the WAL read plane (per-thread read
        # connections that never block behind the writer); :memory:
        # databases are private per connection, so reads stay on the
        # shared conn under the lock
        read_factory = (lambda: dialect.connect(path)) \
            if path != ":memory:" else None
        super().__init__(dialect.connect(path), dialect,
                         read_factory=read_factory)
        # the meta plane checkpoints into this store only when it is
        # durable: a :memory: database dies with the process, so a
        # persisted checkpoint would outlive the state it describes
        self.supports_meta_plane = path != ":memory:"

    # kept for callers/tests that exercised the escaping directly
    _like_escape = staticmethod(SqlDialect.like_escape)
