"""Chunk visibility resolution (weed/filer/filechunks.go).

Chunks may overlap after overwrites; later-written chunks win.  The
visible-interval sweep mirrors ReadResolvedChunks/NonOverlappingVisible-
Intervals: order by (mtime, appearance), overlay onto an interval list,
then produce ChunkViews for any requested [offset, offset+size) range.
"""

from __future__ import annotations

from dataclasses import dataclass

from .entry import FileChunk


@dataclass
class ChunkView:
    file_id: str
    chunk_offset: int   # offset inside the stored chunk blob
    size: int
    logical_offset: int  # offset in the file
    chunk_size: int = 0  # FULL size of the stored chunk blob (the
    #                      filer's chunk cache keys whole bodies by
    #                      fid, so a partial view must know whether
    #                      caching the whole blob is worth it)


@dataclass
class _Visible:
    start: int
    stop: int
    file_id: str
    chunk_start: int  # file-logical offset where this chunk begins
    chunk_size: int = 0


def non_overlapping_visible_intervals(chunks: list[FileChunk]
                                      ) -> list[_Visible]:
    visibles: list[_Visible] = []
    ordered = sorted(enumerate(chunks),
                     key=lambda t: (t[1].mtime_ns, t[0]))
    for _, c in ordered:
        new = _Visible(c.offset, c.offset + c.size, c.file_id,
                       c.offset, c.size)
        out: list[_Visible] = []
        for v in visibles:
            if v.stop <= new.start or v.start >= new.stop:
                out.append(v)
                continue
            if v.start < new.start:
                out.append(_Visible(v.start, new.start, v.file_id,
                                    v.chunk_start, v.chunk_size))
            if v.stop > new.stop:
                out.append(_Visible(new.stop, v.stop, v.file_id,
                                    v.chunk_start, v.chunk_size))
        out.append(new)
        out.sort(key=lambda v: v.start)
        visibles = out
    return visibles


def view_from_chunks(chunks: list[FileChunk], offset: int, size: int
                     ) -> list[ChunkView]:
    """ChunkViews covering [offset, offset+size); gaps are skipped (the
    reader zero-fills them)."""
    views: list[ChunkView] = []
    stop = offset + size
    for v in non_overlapping_visible_intervals(chunks):
        lo = max(offset, v.start)
        hi = min(stop, v.stop)
        if lo >= hi:
            continue
        views.append(ChunkView(
            file_id=v.file_id,
            chunk_offset=lo - v.chunk_start,
            size=hi - lo,
            logical_offset=lo,
            chunk_size=v.chunk_size))
    return views


def total_size(chunks: list[FileChunk]) -> int:
    return max((c.offset + c.size for c in chunks), default=0)
