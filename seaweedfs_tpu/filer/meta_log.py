"""Persistent filer metadata log with timestamp replay.

The reference appends every namespace mutation to segment files under
`/topics/.system/log/<yyyy-mm-dd>/<HH-MM>` through the filer's own chunk
machinery (weed/filer/filer_notify_append.go appendToFile), and replays
them by timestamp, pruning whole segments by their date/minute names
(weed/filer/filer_notify_read.go CollectLogFileRefs).  Subscribers that
reconnect resume from their last-seen tsNs and never silently skip
events — the round-2 in-memory ring dropped history on overflow.

This build keeps the same two-level `<yyyy-mm-dd>/<HH-MM>.log` naming so
replay prunes segments exactly like the reference, but appends JSON
lines to local files under the filer's data dir: the log IS the
filer's durability domain here, while the reference's detour through
volume-server chunks exists because its log doubles as an MQ topic.
A bounded in-memory tail keeps the common `events_since(recent)` query
off the disk.  Timestamps are made strictly monotonic at append time so
`> sinceNs` resume can never skip a same-timestamp sibling.

Durability is GROUP-COMMITTED (util/group_commit.py): appenders stamp
and enqueue their serialized line under the stamp lock, then meet at a
shared barrier — one leader drains the queue and lands the whole batch
with ONE `os.write` on an `O_APPEND` fd; every appender returns only
after a write that covers its line.  Ack semantics are identical to
the old flush-per-event loop (an acked event survives SIGKILL; a torn
tail line is always an unacked event), but N concurrent appenders
share one barrier instead of serializing N of them — and because the
batch is a single kernel append, SIBLING instances over the same dir
(pre-fork filer workers, two filers over one sqlite store) interleave
whole batches, never partial lines.

Since ISSUE 13 this log is the filer's WRITE-AHEAD LOG proper
(filer/meta_plane.py): a namespace mutation is acked once its event
clears this barrier, and the sqlite/LSM store is an asynchronously
maintained CHECKPOINT of it.  `append_raw` is the WAL fast path — the
caller passes the entry JSON it already serialized, the line carries
an `nl` length field so the async applier can slice those exact bytes
back out (serialize once, reuse everywhere), and the returned durable
log position anchors the overlay index's eviction protocol.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from ..util.group_commit import CommitBarrier

# per-process instance counter so every MetaLog over a shared dir owns
# a distinct watermark file (two filers in one test process share a
# pid; their logs must not clobber one file)
_WM_SEQ_LOCK = threading.Lock()
_WM_SEQ = [0]


def _format_wm(ts: int) -> str:
    """Fixed-width watermark payload with a mod-97 check suffix: the
    publish path is a single in-place pwrite (not an atomic replace),
    so a sibling's read racing the write could see a torn mix of old
    and new digits — the check digit makes a tear DETECTABLE, and the
    reader treats it conservatively (serve nothing from cache this
    probe) instead of parsing a possibly-LOWER value and serving
    stale metadata."""
    return f"{ts:020d}.{ts % 97:02d}"


def _parse_wm(text: str) -> "int | None":
    """Parse a watermark payload; None = torn/invalid (the reader
    must fail CONSERVATIVE, never low)."""
    text = text.strip()
    if not text:
        return 0
    num, dot, chk = text.partition(".")
    try:
        v = int(num)
        if dot and v % 97 != int(chk):
            return None
        return v
    except ValueError:
        return None


def _segment_name(ts_ns: int) -> "tuple[str, str]":
    """(day, minute) segment names, UTC — filer_notify_read.go:33
    startDate / :53 startHourMinute."""
    t = time.gmtime(ts_ns / 1e9)
    return (f"{t.tm_year:04d}-{t.tm_mon:02d}-{t.tm_mday:02d}",
            f"{t.tm_hour:02d}-{t.tm_min:02d}")


def alloc_writer_identity(dir_path: str) -> "tuple[str, str]":
    """Mint a (wid, watermark_path) pair for an EXTERNAL sibling
    writer over `dir_path` — the native meta plane (native/
    meta_plane.cc) appends WAL lines as its own writer instance, so it
    needs the same uniqueness guarantees a MetaLog gives itself: the
    per-process seq (two writers in one pid must not clobber one
    watermark file) and the random wid suffix (pid recycling must not
    make a follower skip a dead instance's lines as its own).

    The watermark file is pre-created here via the same tmp + atomic
    replace first-publish protocol as MetaLog._write_watermark, seeded
    at 0 (conservative: readers treat it as "nothing durable yet"), so
    the native side's publish path is a bare pwrite from byte one."""
    import binascii
    with _WM_SEQ_LOCK:
        _WM_SEQ[0] += 1
        seq = _WM_SEQ[0]
    wid = (f"{os.getpid()}-{seq}-"
           f"{binascii.hexlify(os.urandom(3)).decode()}")
    wm_path = os.path.join(dir_path, f".watermark.{os.getpid()}.{seq}")
    os.makedirs(dir_path, exist_ok=True)
    tmp = f"{wm_path}.tmp"
    with open(tmp, "w", encoding="ascii") as f:
        f.write(_format_wm(0))
    os.replace(tmp, wm_path)
    return wid, wm_path


def strip_wal_fields(event: dict) -> dict:
    """Drop the WAL-plumbing fields a persisted line carries (`nl` =
    newEntry length for the applier's byte-reuse slice, `wid` = writer
    instance id) before the event reaches subscribers — the event API
    contract stays {op, tsNs, newEntry, oldEntry}."""
    event.pop("nl", None)
    event.pop("wid", None)
    return event


# a log position: (day, minute, byte offset after the line/batch).
# Tuples compare lexicographically and segment names are zero-padded,
# so plain tuple comparison orders positions across rotations.
LOG_START: "tuple[str, str, int]" = ("", "", 0)


class MetaLog:
    """Append-only metadata event log: strictly-monotonic stamps,
    per-minute segment files (when `dir_path` is set), timestamp replay
    across restart."""

    def __init__(self, dir_path: str | None = None,
                 max_memory_events: int = 10_000):
        self.dir = dir_path
        self._mem: deque[dict] = deque(maxlen=max_memory_events)
        self._lock = threading.Lock()
        self._last_ts = 0
        # stamped-and-buffered lines awaiting the shared barrier, in
        # stamp order (stamping and enqueueing share self._lock)
        self._pending: "list[tuple[int, str]]" = []
        self._open_name: "tuple[str, str] | None" = None
        self._open_fd: "int | None" = None
        # durable position: (day, minute, offset) just past the last
        # batch this instance's barrier landed — an appender reads it
        # after commit() returns as a conservative "my line is at or
        # before here" cover for the meta plane's overlay eviction
        self._durable_pos: "tuple[str, str, int]" = LOG_START
        # own-batch extents [(day, minute, start, end)]: each barrier
        # write is ONE contiguous kernel append of only OUR lines, so
        # the meta plane's coherence follower can jump over it by
        # arithmetic instead of reading and skip-scanning bytes it
        # ingested at ack time.  Bounded; overflow just means the
        # follower reads those bytes the slow way.
        self._own_extents: deque = deque(maxlen=4096)
        # highest stamp whose line a barrier has flushed: the memory
        # tail may briefly lead the disk (stamped, queued, pre-flush),
        # and events_since must not serve an event a crash could still
        # lose — a subscriber that recorded its tsNs would silently
        # skip it on resume after replay
        self._durable_ts = 0
        self._barrier = CommitBarrier(self._group_commit_drain,
                                      site="filer.metalog")
        # durable-ts WATERMARK file (the filer metadata cache's
        # cross-instance coherence probe): this instance's group-commit
        # leader stamps `.watermark.<pid>.<seq>` with its batch's last
        # flushed ts, so a SIBLING MetaLog over the same dir (two
        # filers sharing one sqlite store share its .metalog by
        # construction) can ask "has anyone ELSE durably committed
        # since my cache fills?" with tiny page-cached reads instead
        # of replaying segments.  Own events don't need the file: the
        # owning filer's cache is invalidated synchronously by its
        # event listener.
        self._wm_path: "str | None" = None
        self._wm_fd: "int | None" = None
        self._wm_last = 0
        self._wm_names: "list[str]" = []
        self._wm_listed = 0.0
        # writer instance id, stamped into every WAL line so the meta
        # plane's log follower can tell its own (already-ingested)
        # events from sibling instances' cheaply
        self.wid = ""
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)
            self._last_ts = self._scan_last_ts()
            self._durable_ts = self._last_ts
            self._durable_pos = self.end_pos()
            with _WM_SEQ_LOCK:
                _WM_SEQ[0] += 1
                seq = _WM_SEQ[0]
            # random suffix: pid+seq alone can recur across restarts
            # (pid recycling), and a recurring wid would make a new
            # instance's follower skip-scan a DEAD instance's lines
            # as its own
            import binascii
            self.wid = (f"{os.getpid()}-{seq}-"
                        f"{binascii.hexlify(os.urandom(3)).decode()}")
            self._wm_path = os.path.join(
                self.dir, f".watermark.{os.getpid()}.{seq}")
            # adopt-and-prune: watermark files at or below the scanned
            # history are redundant (the scan read those events); a
            # LIVE sibling's file above the scan is kept verbatim.
            # Only files untouched for a minute are prune candidates:
            # a read-then-remove on an ACTIVE sibling's file could
            # race its atomic advance and delete a value the sibling's
            # monotonic guard won't republish until its next commit.
            now = time.time()
            for name in os.listdir(self.dir):
                if not name.startswith(".watermark."):
                    continue
                p = os.path.join(self.dir, name)
                try:
                    if now - os.path.getmtime(p) < 60.0:  # noqa: SWFS011 — cross-process file-mtime age, wall clock is the only shared clock
                        continue
                    with open(p, encoding="ascii") as f:
                        val = _parse_wm(f.read(64))
                    if val is not None and val <= self._last_ts:
                        os.remove(p)
                except (OSError, ValueError):
                    continue

    # -- append -----------------------------------------------------------

    def append(self, event: dict) -> dict:
        """Stamp and persist one event.  The event's tsNs is bumped if
        needed so stamps are strictly increasing even across restarts
        (replay uses `> sinceNs`; two events sharing a stamp would let
        a resumer skip the second).  Returns only after the shared
        group-commit barrier has landed the event's line — an acked
        event survives SIGKILL, exactly like the old per-event flush."""
        with self._lock:
            ts = self._stamp_locked(event)
            self._mem.append(event)
            if self.dir:
                self._pending.append(
                    (ts, json.dumps(event, separators=(",", ":"))))
        if self.dir:
            self._barrier.commit()
        return event

    def _stamp_locked(self, event: dict) -> int:
        ts = int(event.get("tsNs") or time.time_ns())
        if ts <= self._last_ts:
            ts = self._last_ts + 1
        self._last_ts = ts
        event["tsNs"] = ts
        return ts

    def append_raw(self, op: str, new_dict: "dict | None",
                   old_dict: "dict | None", raw_new: "str | None",
                   raw_old: "str | None"
                   ) -> "tuple[dict, tuple[str, str, int]]":
        """WAL fast path (meta plane): the caller already serialized
        the entry payloads ONCE (`raw_new`/`raw_old` are the JSON of
        `new_dict`/`old_dict`), so the line is composed by string
        splice instead of re-serializing, and the `nl` field records
        `len(raw_new)` so the async store applier can slice the exact
        newEntry bytes back out of the line (the store's meta column
        is that same JSON — zero re-serialization end to end).
        newEntry sits LAST in the line, which makes the slice
        `line[-(nl + 1):-1]` — exact regardless of what the payloads
        contain.  Returns (event, cover_pos): the event dict handed to
        listeners (no WAL fields), and a durable log position at or
        after the event's line (the overlay eviction cover)."""
        event = {"op": op, "newEntry": new_dict, "oldEntry": old_dict}
        with self._lock:
            ts = self._stamp_locked(event)
            self._mem.append(event)
            if self.dir:
                rn = raw_new if raw_new is not None else "null"
                ro = raw_old if raw_old is not None else "null"
                line = (f'{{"nl":{len(rn)},"wid":"{self.wid}",'
                        f'"op":"{op}","tsNs":{ts},'
                        f'"oldEntry":{ro},"newEntry":{rn}}}')
                self._pending.append((ts, line))
        if self.dir:
            self._barrier.commit()
            with self._lock:
                pos = self._durable_pos
        else:
            pos = LOG_START
        return event, pos

    def _group_commit_drain(self) -> None:
        """The barrier's designated flush helper: drain every queued
        line and land each segment's run with ONE `os.write` on the
        O_APPEND fd.  Only ever entered by one leader at a time
        (CommitBarrier serializes batches), so the fd needs no lock of
        its own.  A single kernel append per batch is also what makes
        the shared-dir topology safe: sibling processes' batches
        interleave whole, never mid-line."""
        with self._lock:
            batch, self._pending = self._pending, []
        if not batch:
            return
        i, n, end_pos = 0, len(batch), None
        while i < n:
            name = _segment_name(batch[i][0])
            j = i
            while j < n and _segment_name(batch[j][0]) == name:
                j += 1
            if name != self._open_name:
                self._rotate(name)
            buf = "".join(line + "\n"
                          for _ts, line in batch[i:j]).encode("utf-8")
            # short writes must FAIL the batch, not ack it: os.write
            # may land fewer bytes (ENOSPC mid-write, RLIMIT_FSIZE)
            # without raising, and this is the filer's WAL ack point —
            # an exception here propagates to every member of the
            # barrier batch (CommitBarrier's error fan-out), so nobody
            # is acked by bytes that never reached the kernel.  A torn
            # partial line left behind is an UNACKED tail, which every
            # reader already tolerates.
            mv = memoryview(buf)
            while mv:
                wrote = os.write(self._open_fd, mv)
                if wrote <= 0:
                    raise OSError(
                        f"metalog WAL append wrote {wrote} of "
                        f"{len(mv)} bytes")
                mv = mv[wrote:]
            # O_APPEND leaves the fd offset at the end of OUR write
            # (later sibling appends don't move it) — the exact cover
            end = os.lseek(self._open_fd, 0, os.SEEK_CUR)
            end_pos = (name[0], name[1], end)
            self._own_extents.append(
                (name[0], name[1], end - len(buf), end))
            i = j
        with self._lock:
            self._durable_ts = max(self._durable_ts, batch[-1][0])
            if end_pos is not None and end_pos > self._durable_pos:
                self._durable_pos = end_pos
        self._write_watermark(batch[-1][0])

    def _write_watermark(self, ts: int) -> None:
        """Publish the durable ts for sibling instances (one tiny
        write per COMMIT WINDOW, not per event).  Barrier leaders are
        serialized per instance, so the monotonic guard needs no
        lock.

        Fast path: one pwrite of a FIXED-WIDTH 20-digit value at
        offset 0 over a kept-open fd — the open/replace dance cost
        ~0.5ms of syscalls per commit window (cProfile'd as the
        single largest slice of the filer's metalog wall, ISSUE 12),
        which at group-commit window rates was a measurable share of
        the gateway's per-request budget.  Fixed width keeps every
        publish byte-for-byte aligned, so a reader never sees mixed
        digit lengths; the first publish still creates the file
        atomically via the tmp+replace path so sibling discovery
        (listdir) never lists a half-created name."""
        if self._wm_path is None or ts <= self._wm_last:
            return
        self._wm_last = ts
        payload = _format_wm(ts).encode("ascii")
        if self._wm_fd is not None:
            try:
                os.pwrite(self._wm_fd, payload, 0)
                return
            except OSError:
                try:
                    os.close(self._wm_fd)
                except OSError:
                    pass
                self._wm_fd = None
        tmp = f"{self._wm_path}.tmp"
        try:
            with open(tmp, "w", encoding="ascii") as f:
                f.write(payload.decode("ascii"))
            os.replace(tmp, self._wm_path)
            self._wm_fd = os.open(self._wm_path, os.O_WRONLY)
        except OSError:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def foreign_watermark(self) -> int:
        """Highest timestamp a SIBLING instance over this log dir has
        durably flushed — the filer metadata cache's staleness probe.
        A cache fill stamped before this value may pre-date a foreign
        write, so the serve rule is `current foreign_watermark <=
        fill stamp` ("never serve an entry older than the watermark
        from cache").  Own events never appear here: the owning
        filer's listener invalidates them synchronously.  0 when no
        sibling has ever committed (single-filer fast path: the probe
        is a memoized listdir once a second, no file reads)."""
        if not self.dir:
            return 0
        now = time.monotonic()
        if now - self._wm_listed > 1.0:
            # new sibling instances appear rarely: re-list at most
            # once a second, read the known files on every probe
            own = os.path.basename(self._wm_path or "")
            try:
                self._wm_names = [
                    n for n in os.listdir(self.dir)
                    if n.startswith(".watermark.") and
                    not n.endswith(".tmp") and n != own]
            except OSError:
                self._wm_names = []
            self._wm_listed = now
        best = 0
        for name in self._wm_names:
            try:
                with open(os.path.join(self.dir, name),
                          encoding="ascii") as f:
                    val = _parse_wm(f.read(64))
            except OSError:
                continue
            if val is None:
                # torn read (racing a sibling's in-place pwrite):
                # fail CONSERVATIVE — an impossibly-new watermark
                # makes every cache fill unservable for this probe,
                # which costs one store round-trip, never staleness
                return 1 << 62
            best = max(best, val)
        return best

    def _rotate(self, name: "tuple[str, str]") -> None:
        """Caller is the barrier leader (serialized)."""
        if self._open_fd is not None:
            os.close(self._open_fd)
        day_dir = os.path.join(self.dir, name[0])
        os.makedirs(day_dir, exist_ok=True)
        self._open_fd = os.open(
            os.path.join(day_dir, name[1] + ".log"),
            os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        self._open_name = name

    def end_pos(self) -> "tuple[str, str, int]":
        """Current end-of-log position (newest segment + size) — the
        meta plane's checkpoint baseline on first enablement, when
        everything already in the log was written by the synchronous
        (pre-WAL) path and is therefore already in the store."""
        if not self.dir:
            return LOG_START
        try:
            days = sorted((d for d in os.listdir(self.dir)
                           if os.path.isdir(os.path.join(self.dir, d))),
                          reverse=True)
        except OSError:
            return LOG_START
        for day in days:
            day_dir = os.path.join(self.dir, day)
            for minute in sorted(os.listdir(day_dir), reverse=True):
                if not minute.endswith(".log"):
                    continue
                try:
                    size = os.path.getsize(
                        os.path.join(day_dir, minute))
                except OSError:
                    continue
                return (day, minute[:-4], size)
        return LOG_START

    def durable_pos(self) -> "tuple[str, str, int]":
        with self._lock:
            return self._durable_pos

    def own_extent_at(self, day: str, minute: str,
                      off: int) -> "int | None":
        """If an own-batch extent STARTS exactly at (day, minute,
        off), consume every contiguous own extent from there and
        return the final end offset — the coherence follower's
        fast-skip.  None when the next bytes were written by a
        sibling (or the extent record was evicted): read normally."""
        ext = self._own_extents
        end = None
        while ext:
            d, m, start, e = ext[0]
            if (d, m) != (day, minute) or e <= off:
                ext.popleft()       # stale: the follower moved past
                continue
            if start > off:
                break               # a sibling's bytes come first
            off = end = e
            ext.popleft()
        return end

    # -- replay -----------------------------------------------------------

    def events_since(self, ts_ns: int, limit: int = 0) -> list[dict]:
        """All events with tsNs > ts_ns, oldest first.  Served from the
        in-memory tail when it still covers ts_ns; otherwise replayed
        from the persisted segments (pruned by day/minute name like
        CollectLogFileRefs)."""
        with self._lock:
            mem = list(self._mem)
            durable = self._durable_ts
        if self.dir:
            # serve only barrier-flushed events: an event still queued
            # for its flush is not yet acked, and a crash could erase
            # it — mem visibility must imply durability, as it did
            # when append flushed under the lock
            mem = [e for e in mem if e["tsNs"] <= durable]
        if mem and (mem[0]["tsNs"] <= ts_ns or not self.dir):
            out = [e for e in mem if e["tsNs"] > ts_ns]
            return out[:limit] if limit else out
        if not self.dir:
            return []
        # disk replay: lines queued at the barrier are in _mem but may
        # not be in their segments yet — force a barrier so the replay
        # below cannot miss a just-acked sibling
        self._barrier.sync()
        out = []
        start_day, start_min = _segment_name(ts_ns) if ts_ns else ("", "")
        for day in sorted(os.listdir(self.dir)):
            if day < start_day:
                continue
            day_dir = os.path.join(self.dir, day)
            if not os.path.isdir(day_dir):
                continue
            for minute in sorted(os.listdir(day_dir)):
                if day == start_day and minute[:-4] < start_min:
                    continue
                with open(os.path.join(day_dir, minute),
                          encoding="utf-8") as f:
                    for line in f:
                        try:
                            e = json.loads(line)
                        except ValueError:
                            continue  # torn tail write after a crash
                        if e.get("tsNs", 0) > ts_ns:
                            out.append(strip_wal_fields(e))
                            if limit and len(out) >= limit:
                                return out
        return out

    def last_ts(self) -> int:
        with self._lock:
            return self._last_ts

    def _scan_last_ts(self) -> int:
        """Resume the monotonic stamp clock from the newest persisted
        event (so a restarted filer can't stamp below history)."""
        days = sorted((d for d in os.listdir(self.dir)
                       if os.path.isdir(os.path.join(self.dir, d))),
                      reverse=True)
        for day in days:
            day_dir = os.path.join(self.dir, day)
            for minute in sorted(os.listdir(day_dir), reverse=True):
                last = 0
                with open(os.path.join(day_dir, minute),
                          encoding="utf-8") as f:
                    for line in f:
                        try:
                            last = max(last, json.loads(line)
                                       .get("tsNs", 0))
                        except ValueError:
                            continue
                if last:
                    return last
        return 0

    def close(self) -> None:
        if self.dir:
            self._barrier.sync()   # drain queued lines before closing
        # the segment fd is owned by barrier leaders (serialized by
        # the barrier, not by self._lock); after the final sync above
        # no leader is active
        if self._open_fd is not None:
            os.close(self._open_fd)
            self._open_fd = None
            self._open_name = None
        if self._wm_fd is not None:
            try:
                os.close(self._wm_fd)
            except OSError:
                pass
            self._wm_fd = None
