"""Elasticsearch-shaped filer store — the document-DB archetype
(reference: weed/filer/elastic/v7/elastic_store.go: entries become
documents, directories become term filters, listings are sorted
searches).

The client is hand-rolled over the ES JSON-HTTP wire (this
environment ships no ES driver, same build rule as the RESP client in
redis_store.py):

    PUT    /{index}/_doc/{id}            index a document
    GET    /{index}/_doc/{id}            fetch ({"found": bool})
    DELETE /{index}/_doc/{id}
    POST   /{index}/_delete_by_query     {"query": ...}
    POST   /{index}/_search              bool-filter + sort + size
    POST   /{index}/_refresh             make writes searchable

Document model (single index, vs the reference's index-per-top-dir —
one index keeps create/delete of top-level dirs free of index
lifecycle management while serving the same queries):

    _id      urlsafe-b64(full_path)   (ES ids must be path-safe)
    directory  parent directory (exact-match term for listings)
    name       entry name (range/sort key for pagination)
    entry      the full entry JSON

The store is write-through searchable: mutations refresh the index so
a subsequent listing sees them (the filer contract; production ES
would batch refreshes, the reference issues them per write too).
"""

from __future__ import annotations

import base64
import json
import urllib.parse

from ..server.httpd import http_bytes
from .entry import Entry
from .filer_store import FilerStore, normalize_path

INDEX = "seaweedfs_entries"


class ElasticError(RuntimeError):
    pass


class ElasticClient:
    """Minimal ES JSON-HTTP client (driver role)."""

    def __init__(self, address: str):
        self.base = address if address.startswith("http") \
            else f"http://{address}"

    def _req(self, method: str, path: str,
             body: "dict | None" = None,
             ok_404: bool = False) -> dict:
        payload = json.dumps(body).encode() if body is not None \
            else None
        headers = {"Content-Type": "application/json"} \
            if payload else {}
        try:
            st, raw, _ = http_bytes(method, self.base + path,
                                    payload, headers)
        except OSError as e:
            raise ElasticError(f"elastic {self.base}: {e}")
        doc = json.loads(raw) if raw else {}
        if st == 404 and ok_404:
            return doc          # semantic not-found (doc fetch/del)
        if st >= 400:
            # a swallowed 400 (mapping conflict, bad search) would
            # read as "write succeeded" / "directory empty" — every
            # protocol error must surface
            raise ElasticError(f"elastic {method} {path}: {st} "
                               f"{doc}")
        return doc

    def ping(self) -> None:
        self._req("GET", "/")

    def ensure_index(self, idx: str) -> None:
        """Create the index with KEYWORD mappings when absent
        (elastic_store.go CreateIndex): under ES dynamic mapping,
        `directory`/`name` would become analyzed text — term filters
        would tokenize and sort would be refused."""
        try:
            self._req("GET", f"/{idx}")
            return
        except ElasticError:
            pass
        self._req("PUT", f"/{idx}", {
            "mappings": {"properties": {
                "directory": {"type": "keyword"},
                "name": {"type": "keyword"},
                "entry": {"type": "object", "enabled": False},
            }}})

    def index(self, idx: str, doc_id: str, body: dict) -> None:
        self._req("PUT", f"/{idx}/_doc/"
                         f"{urllib.parse.quote(doc_id, safe='')}",
                  body)
        self._req("POST", f"/{idx}/_refresh")

    def get(self, idx: str, doc_id: str) -> "dict | None":
        doc = self._req("GET", f"/{idx}/_doc/"
                               f"{urllib.parse.quote(doc_id, safe='')}",
                        ok_404=True)
        return doc.get("_source") if doc.get("found") else None

    def delete(self, idx: str, doc_id: str) -> None:
        self._req("DELETE", f"/{idx}/_doc/"
                            f"{urllib.parse.quote(doc_id, safe='')}",
                  ok_404=True)
        self._req("POST", f"/{idx}/_refresh")

    def delete_by_query(self, idx: str, query: dict) -> None:
        self._req("POST", f"/{idx}/_delete_by_query",
                  {"query": query})
        self._req("POST", f"/{idx}/_refresh")

    def search(self, idx: str, query: dict, sort: list,
               size: int) -> list:
        doc = self._req("POST", f"/{idx}/_search",
                        {"query": query, "sort": sort, "size": size})
        return [h["_source"]
                for h in doc.get("hits", {}).get("hits", [])]


def _doc_id(path: str) -> str:
    return base64.urlsafe_b64encode(path.encode()).decode()


class ElasticFilerStore(FilerStore):
    """FilerStore over ElasticClient (elastic_store.go shape)."""

    def __init__(self, client: ElasticClient):
        self.es = client
        self.es.ping()
        self.es.ensure_index(INDEX)

    def insert_entry(self, entry: Entry) -> None:
        self.es.index(INDEX, _doc_id(entry.full_path), {
            "directory": entry.parent, "name": entry.name,
            "entry": entry.to_json()})

    update_entry = insert_entry

    def find_entry(self, path: str) -> "Entry | None":
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        src = self.es.get(INDEX, _doc_id(path))
        return Entry.from_json(src["entry"]) if src else None

    def delete_entry(self, path: str) -> None:
        self.es.delete(INDEX, _doc_id(normalize_path(path)))

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path).rstrip("/")
        # children at every depth: their directory is the folder
        # itself or starts with "<folder>/" (the reference deletes by
        # directory prefix the same way)
        self.es.delete_by_query(INDEX, {"bool": {"should": [
            {"term": {"directory": path or "/"}},
            {"prefix": {"directory": (path or "") + "/"}},
        ]}})

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> "list[Entry]":
        dir_path = normalize_path(dir_path).rstrip("/") or "/"
        filters: list = [{"term": {"directory": dir_path}}]
        if start_file:
            op = "gte" if include_start else "gt"
            filters.append({"range": {"name": {op: start_file}}})
        if prefix:
            filters.append({"prefix": {"name": prefix}})
        hits = self.es.search(
            INDEX, {"bool": {"filter": filters}},
            [{"name": "asc"}], limit)
        return [Entry.from_json(h["entry"]) for h in hits]

    def close(self) -> None:
        pass
