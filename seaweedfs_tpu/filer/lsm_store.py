"""Embedded log-structured filer store — the LOCAL ordered-KV
archetype (reference: weed/filer/leveldb2/, the filer's DEFAULT store;
ours is a from-scratch LSM-lite rather than a binding, since no
leveldb library exists in the image).

Design (the leveldb shape, miniaturized):
  - a WAL absorbs every mutation (JSON lines, fsync-free append —
    the same durability window as the reference's leveldb WAL with
    sync=false, its default)
  - an in-memory sorted memtable serves reads/scans
  - at MEMTABLE_LIMIT the memtable flushes to an immutable sorted
    segment file and the WAL resets
  - reads consult memtable, then segments newest-first; deletes are
    tombstones
  - when segments pile past COMPACT_AT, everything merges into one
    segment (tombstones dropped)

Keys are entry paths; range scans over the sorted keyspace give
directory listings without touching unrelated subtrees.
"""

from __future__ import annotations

import bisect

from ..util.skiplist import SkipList
import heapq
import json
import os
import threading

from ..util.group_commit import CommitBarrier
from .entry import Entry
from .filer_store import FilerStore

MEMTABLE_LIMIT = 1000
COMPACT_AT = 4
TOMBSTONE = None          # JSON null marks a delete
_MEM_MISS = object()      # distinguishes "absent" from a tombstone


class LsmTree:
    """Generic ordered str->dict store with WAL + segments."""

    def __init__(self, directory: str):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        # one lock for memtable/WAL/segment state: the store serves
        # concurrent HTTP threads (MemoryStore/SqliteStore lock too)
        self._lock = threading.RLock()
        # ordered memtable (util/skiplist, the reference's
        # weed/util/skiplist role): inserts keep order, so flushes
        # and range scans read it in-order with NO per-call sort
        self._mem = SkipList()
        self._segments: list[tuple[list[str], list]] = []  # old->new
        self._seg_paths: list[str] = []
        self._next_seq = 0
        with self._lock:
            self._recover()
        self._wal = open(self._wal_path, "a")
        # WAL durability is group-committed: writers append under the
        # lock, one barrier leader flushes for the whole window
        self._barrier = CommitBarrier(self._group_commit_flush,
                                      site="filer.lsm_wal")

    def _group_commit_flush(self) -> None:
        with self._lock:
            self._wal.flush()

    @property
    def _wal_path(self) -> str:
        return os.path.join(self.dir, "wal.log")

    def _recover(self) -> None:
        """Caller holds the lock (init-time replay)."""
        names = sorted(n for n in os.listdir(self.dir)
                       if n.endswith(".seg"))
        for name in names:
            path = os.path.join(self.dir, name)
            keys, vals = [], []
            with open(path) as f:
                for line in f:
                    try:
                        k, v = json.loads(line)
                    except ValueError:
                        continue    # torn tail of a crashed flush
                    keys.append(k)
                    vals.append(v)
            self._segments.append((keys, vals))
            self._seg_paths.append(path)
            self._next_seq = max(self._next_seq,
                                 int(name.split(".")[0]) + 1)
        if os.path.exists(self._wal_path):
            with open(self._wal_path) as f:
                for line in f:
                    try:
                        k, v = json.loads(line)
                    except ValueError:
                        continue    # torn tail: drop
                    self._mem.insert(k, v)

    # -- mutations ---------------------------------------------------------

    def put(self, key: str, value: "dict | None") -> None:
        with self._lock:
            self._wal.write(json.dumps([key, value],
                                       separators=(",", ":")) + "\n")
            self._mem.insert(key, value)
            if len(self._mem) >= MEMTABLE_LIMIT:
                self.flush_memtable()
        # ack after the shared WAL barrier (same durability window as
        # the old per-put flush, one flush per commit window)
        self._barrier.commit()

    def put_many(self, pairs: "list[tuple[str, dict | None]]") -> None:
        """Batched put: one WAL write run + ONE barrier for the whole
        batch — the meta plane's applier path (its events already
        cleared the metalog barrier, so this WAL is belt-and-braces
        checkpoint durability, amortized)."""
        if not pairs:
            return
        with self._lock:
            self._wal.write("".join(
                json.dumps([k, v], separators=(",", ":")) + "\n"
                for k, v in pairs))
            for k, v in pairs:
                self._mem.insert(k, v)
            if len(self._mem) >= MEMTABLE_LIMIT:
                self.flush_memtable()
        self._barrier.commit()

    def delete(self, key: str) -> None:
        self.put(key, TOMBSTONE)

    def flush_memtable(self) -> None:
      with self._lock:
        if not self._mem:
            return
        seq = self._next_seq
        self._next_seq += 1
        path = os.path.join(self.dir, f"{seq:08d}.seg")
        tmp = path + ".tmp"
        pairs = list(self._mem.items())     # already in key order
        keys = [k for k, _ in pairs]
        with open(tmp, "w") as f:
            for k, v in pairs:
                f.write(json.dumps([k, v],
                                   separators=(",", ":")) + "\n")
            f.flush()  # noqa: SWFS012 — once-per-memtable segment seal, not per-put
            os.fsync(f.fileno())  # noqa: SWFS012 — once-per-memtable segment seal
        os.replace(tmp, path)
        self._segments.append((keys, [v for _, v in pairs]))
        self._seg_paths.append(path)
        self._mem = SkipList()
        # the flushed state is durable in the segment: reset the WAL
        self._wal.close()
        os.remove(self._wal_path)
        self._wal = open(self._wal_path, "a")
        if len(self._segments) >= COMPACT_AT:
            self._compact()

    def _compact(self) -> None:
        """Caller holds the lock.  Merge every segment into one,
        newest value wins, tombstones dropped (they have nothing
        older left to shadow).  The merged segment is INSTALLED
        (under a name that sorts newest) before
        the old ones are removed — a crash mid-compaction must leave
        a recoverable superset, never a hole."""
        merged: dict[str, "dict | None"] = {}
        for keys, vals in self._segments:      # old -> new
            for k, v in zip(keys, vals):
                merged[k] = v
        live = {k: v for k, v in merged.items() if v is not TOMBSTONE}
        seq = self._next_seq
        self._next_seq += 1
        path = os.path.join(self.dir, f"{seq:08d}.seg")
        tmp = path + ".tmp"
        keys = sorted(live)
        with open(tmp, "w") as f:
            for k in keys:
                f.write(json.dumps([k, live[k]],
                                   separators=(",", ":")) + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)          # durable BEFORE any removal
        for p in self._seg_paths:
            try:
                os.remove(p)
            except OSError:
                pass
        self._segments = [(keys, [live[k] for k in keys])]
        self._seg_paths = [path]

    # -- reads -------------------------------------------------------------

    def get(self, key: str) -> "dict | None":
        with self._lock:
            hit = self._mem.get(key, _MEM_MISS)
            if hit is not _MEM_MISS:
                return hit
            for keys, vals in reversed(self._segments):
                i = bisect.bisect_left(keys, key)
                if i < len(keys) and keys[i] == key:
                    return vals[i]
            return None

    def scan(self, lo: str, hi: str):
        """LAZY merged ordered iteration over [lo, hi): newest layer
        wins, tombstones suppress.  A heap-merge over per-layer
        cursors — a caller that stops after one listing page pays for
        that page, not the whole range (the memtable is bounded by
        MEMTABLE_LIMIT, so its per-call sort is cheap; segments are
        immutable, so index cursors are safe outside the lock)."""
        with self._lock:
            mem = list(self._mem.items(lo, hi))  # in-order, no sort
            segs = list(self._segments)
        # priority 0 = newest (memtable), then segments newest-first
        layers: list[tuple[list, list]] = [
            ([k for k, _ in mem], [v for _, v in mem])]
        layers += [seg for seg in reversed(segs)]
        heap = []
        for pri, (keys, _vals) in enumerate(layers):
            i = bisect.bisect_left(keys, lo)
            if i < len(keys) and keys[i] < hi:
                heap.append((keys[i], pri, i))
        heapq.heapify(heap)
        last_key = None
        while heap:
            key, pri, i = heapq.heappop(heap)
            keys, vals = layers[pri]
            if i + 1 < len(keys) and keys[i + 1] < hi:
                heapq.heappush(heap, (keys[i + 1], pri, i + 1))
            if key == last_key:
                continue        # an older layer's shadowed value
            last_key = key
            if vals[i] is not TOMBSTONE:
                yield key, vals[i]

    def close(self) -> None:
        with self._lock:
            try:
                self._wal.close()
            except OSError:
                pass


class LsmStore(FilerStore):
    """FilerStore over LsmTree (filer/leveldb2/leveldb2_store.go
    shape: one key per entry path, range scans for listings)."""

    supports_meta_plane = True     # durable, local, single-process

    def __init__(self, directory: str):
        self.tree = LsmTree(directory)

    def insert_entry(self, entry: Entry) -> None:
        self.tree.put(entry.full_path, entry.to_json())  # noqa: SWFS015 — the synchronous-commit (meta-plane-off) path serializes here by design

    def apply_events(self, records: list) -> None:
        """Meta-plane applier: one WAL batch + one barrier for the
        whole event window (the LSM value is the parsed entry dict the
        WAL line already carries — no re-serialization of the entry
        beyond the tree's own key/value line)."""
        pairs: "list[tuple[str, dict | None]]" = []
        for op, npath, _raw, new, opath in records:
            if npath:
                pairs.append((npath, new))
            if opath and op in ("delete", "rename") and opath != npath:
                pairs.append((opath, TOMBSTONE))
        self.tree.put_many(pairs)

    def update_entry(self, entry: Entry) -> None:
        self.insert_entry(entry)

    def find_entry(self, path: str) -> "Entry | None":
        if path == "/":
            # the root always exists (same contract as the other
            # stores: clients stat it before anything else)
            return Entry("/", is_directory=True)
        v = self.tree.get(path)
        return Entry.from_json(v) if v is not None else None

    def delete_entry(self, path: str) -> None:
        self.tree.delete(path)

    def delete_folder_children(self, path: str) -> None:
        base = path.rstrip("/")
        for k, _ in list(self.tree.scan(base + "/",
                                        base + "0")):
            self.tree.delete(k)

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> "list[Entry]":
        base = dir_path.rstrip("/")
        lo = base + "/" + (prefix or "")
        # exclusive bound: "/"+1 = "0" covers EVERY
        # continuation, incl. astral-plane names a U+FFFF
        # bound would miss
        hi = base + "0"
        out: list[Entry] = []
        for k, v in self.tree.scan(lo, hi):
            name = k[len(base) + 1:]
            if "/" in name:
                continue              # deeper descendant, not a child
            if prefix and not name.startswith(prefix):
                break
            if start_file:
                if name < start_file or (name == start_file and
                                         not include_start):
                    continue
            out.append(Entry.from_json(v))
            if len(out) >= limit:
                break
        return out

    def close(self) -> None:
        self.tree.flush_memtable()
        self.tree.close()
