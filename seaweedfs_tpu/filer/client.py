"""Remote filer client SDK: the Filer-shaped API over a RUNNING filer
server's HTTP surface (the analog of the reference's filer_pb client,
used by `weed webdav -filer=...`, `weed mount`, filer.sync).

Duck-typed to the in-process `Filer` for the read/write/namespace
methods gateways consume, so WebDavServer (and future gateways) can be
handed either — attaching to a shared namespace instead of spawning a
private store.
"""

from __future__ import annotations

import json
import urllib.parse

from ..server.httpd import http_bytes
from .entry import Entry, normalize_path


class FilerClient:
    def __init__(self, filer: str):
        self.filer = filer

    def _url(self, path: str, suffix: str = "") -> str:
        return self.filer + urllib.parse.quote(path) + suffix

    # -- namespace --------------------------------------------------------

    def find_entry(self, path: str) -> "Entry | None":
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        st, body, _ = http_bytes(
            "GET", f"{self.filer}/__meta__/lookup?path=" +
            urllib.parse.quote(path))
        if st == 404:
            return None
        if st != 200:
            raise OSError(f"filer lookup {path}: {st}")
        return Entry.from_json(json.loads(body))

    def list_directory(self, path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1000,
                       prefix: str = "") -> "list[Entry]":
        q = urllib.parse.urlencode({
            "limit": limit, "lastFileName": start_file,
            "prefix": prefix})
        st, body, _ = http_bytes(
            "GET", self._url(path.rstrip("/") + "/", "?" + q))
        if st != 200:
            raise OSError(f"filer list {path}: {st}")
        return [Entry.from_json(e)
                for e in json.loads(body).get("entries", [])]

    def create_entry(self, entry: Entry,
                     create_parents: bool = True) -> None:
        """Full-entry create/replace via /__meta__/put_entry
        (filer.proto CreateEntry): carries attributes, extended
        metadata and the chunk list — gateways mutate entries they
        fetched (etag/SSE/lock config) or assembled (multipart
        completion) and write them back whole."""
        st, body, _ = http_bytes(
            "POST", f"{self.filer}/__meta__/put_entry",
            json.dumps(entry.to_json()).encode(),
            {"Content-Type": "application/json"})
        if st != 200:
            raise OSError(f"filer put_entry {entry.full_path}: {st} "
                          f"{body[:200]!r}")

    def delete_entry(self, path: str, recursive: bool = False,
                     delete_chunks: bool = True) -> None:
        q = []
        if recursive:
            q.append("recursive=true")
        if not delete_chunks:
            # metadata-only delete: the chunks now belong to another
            # entry (multipart completion)
            q.append("ignoreChunks=true")
        st, body, _ = http_bytes(
            "DELETE",
            self._url(path, "?" + "&".join(q) if q else ""))
        if st == 409:
            raise IsADirectoryError(body.decode(errors="replace"))
        if st not in (204, 200, 404):
            raise OSError(f"filer delete {path}: {st}")

    def rename(self, old_path: str, new_path: str) -> None:
        st, body, _ = http_bytes(
            "POST", f"{self.filer}/__meta__/rename",
            json.dumps({"oldPath": old_path,
                        "newPath": new_path}).encode(),
            {"Content-Type": "application/json"})
        if st == 404:
            raise FileNotFoundError(old_path)
        if st != 200:
            raise OSError(f"filer rename {old_path}: {st}")

    def update_attrs(self, path: str, **kw) -> None:
        """Attribute-only update via /__meta__/set_attrs (the endpoint
        replaces the whole attribute block, so read-modify-write)."""
        entry = self.find_entry(path)
        if entry is None:
            raise FileNotFoundError(path)
        for k, v in kw.items():
            setattr(entry.attributes, k, v)
        st, _, _ = http_bytes(
            "POST", f"{self.filer}/__meta__/set_attrs",
            json.dumps({"path": path,
                        "attributes": entry.attributes.to_json()}
                       ).encode(),
            {"Content-Type": "application/json"})
        if st != 200:
            raise OSError(f"filer set_attrs {path}: {st}")

    # -- content ----------------------------------------------------------

    def write_file(self, path: str, data: bytes, mime: str = "",
                   mode: int = 0o660) -> Entry:
        headers = {"Content-Type": mime} if mime else {}
        st, body, _ = http_bytes("PUT", self._url(path), data, headers)
        if st not in (200, 201):
            raise OSError(f"filer write {path}: {st} "
                          f"{body[:200]!r}")
        entry = self.find_entry(path)
        if entry is None:
            raise OSError(f"filer write {path}: entry vanished")
        return entry

    def read_file(self, path: str, offset: int = 0,
                  size: "int | None" = None) -> bytes:
        headers = {}
        if offset or size is not None:
            end = "" if size is None else str(offset + size - 1)
            headers["Range"] = f"bytes={offset}-{end}"
        st, body, _ = http_bytes("GET", self._url(path), None, headers)
        if st == 404:
            raise FileNotFoundError(path)
        if st == 416:
            return b""
        if st not in (200, 206):
            raise OSError(f"filer read {path}: {st}")
        if st == 200 and (offset or size is not None):
            body = body[offset:offset + size if size else None]
        return body
