"""`filer.sync` — continuously replicate one filer's namespace to
another (weed/command/filer_sync.go).

The reference subscribes to the source filer's metadata stream
(SubscribeMetadata), applies each event to the target, and persists a
per-direction progress offset so a restarted sync resumes mid-stream
(command/filer_sync.go setOffset/getOffset).  Active-active runs one
such pipeline in each direction.

This build runs the same shape over the JSON-HTTP plane: poll
`GET <source>/__meta__/events?sinceNs=<offset>` (served from the
persistent MetaLog, so a restart of EITHER side never loses events),
apply each event to the target's filer API, and checkpoint the offset
to a local state file after every applied event.  The offset advances
ONLY after the event fully applied — an application failure aborts the
batch and retries, never skips.  Content is copied by read-through
(source filer ranged read -> target filer auto-chunk upload): chunk
fids are cluster-local and cannot be replicated verbatim, matching the
reference's re-upload behavior; attributes ride separately via
`/__meta__/set_attrs` (filer.proto UpdateEntry).

Unidirectional per instance; run two instances for active-active (the
reference suppresses echo loops via signature exclusion — not yet
implemented here, so active-active needs disjoint subtrees).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import urllib.parse

from ..server.httpd import http_bytes, http_json

log = logging.getLogger("seaweedfs_tpu.filer.sync")


def _quote(path: str) -> str:
    return urllib.parse.quote(path)


def default_state_path(source: str, target: str) -> str:
    """Per-direction checkpoint name: two opposite-direction syncs in
    one cwd must never share (and silently clobber) a state file."""
    safe = (source + "-" + target).replace(":", "_").replace("/", "_")
    return f"filer.sync.{safe}.offset"


class FilerSync:
    def __init__(self, source: str, target: str,
                 state_path: str | None = None,
                 poll_interval: float = 0.2):
        self.source = source
        self.target = target
        self.state_path = state_path or default_state_path(source,
                                                           target)
        self.poll_interval = poll_interval
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- offset checkpoint (filer_sync.go getOffset/setOffset) ------------

    def offset(self) -> int:
        try:
            with open(self.state_path, encoding="utf-8") as f:
                state = json.load(f)
        except OSError:
            return 0
        except ValueError as e:
            raise RuntimeError(
                f"filer.sync: corrupt state file {self.state_path}: {e}")
        src, tgt = state.get("source"), state.get("target")
        if (src, tgt) != (self.source, self.target):
            # an offset is a position in ONE source's log for ONE
            # direction; reading another direction's checkpoint would
            # silently skip (or mass-replay) events
            raise RuntimeError(
                f"filer.sync: state file {self.state_path} belongs to "
                f"{src} -> {tgt}, not {self.source} -> {self.target}; "
                f"pass a distinct -state per direction")
        return int(state.get("sinceNs", 0))

    def _save_offset(self, ts_ns: int) -> None:
        tmp = self.state_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"sinceNs": ts_ns, "source": self.source,
                       "target": self.target}, f)
        os.replace(tmp, self.state_path)

    # -- event application ------------------------------------------------

    def _apply(self, ev: dict) -> None:
        """Apply one event to the target; raises on ANY failed
        application so the offset never advances past a lost mutation."""
        op = ev.get("op")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        if op in ("create", "update") and new:
            self._copy_entry(new)
        elif op == "delete" and old:
            st, body, _ = http_bytes(
                "DELETE", self.target + _quote(old["fullPath"]) +
                "?recursive=true")
            if st >= 300 and st != 404:  # 404 = already gone: idempotent
                raise RuntimeError(
                    f"filer.sync: delete {old['fullPath']}: "
                    f"{st} {body[:200]!r}")
        elif op == "rename" and new and old:
            st, body, _ = http_bytes(
                "POST", self.target + "/__meta__/rename",
                json.dumps({"oldPath": old["fullPath"],
                            "newPath": new["fullPath"]}).encode(),
                {"Content-Type": "application/json"})
            if st == 404:
                # target never saw the old path (e.g. sync started
                # mid-history): materialize the new path instead
                self._copy_entry(new)
            elif st >= 300:
                raise RuntimeError(
                    f"filer.sync: rename {old['fullPath']} -> "
                    f"{new['fullPath']}: {st} {body[:200]!r}")

    def _copy_entry(self, entry: dict) -> None:
        path = entry["fullPath"]
        if entry.get("isDirectory"):
            st, body, _ = http_bytes("PUT",
                                     self.target + _quote(path) + "/")
            if st >= 300:
                raise RuntimeError(
                    f"filer.sync: mkdir {path}: {st} {body[:200]!r}")
        else:
            st, body, _ = http_bytes("GET", self.source + _quote(path))
            if st == 404:
                return  # deleted since; the delete event will follow
            if st >= 300:
                raise RuntimeError(
                    f"filer.sync: read {path} from {self.source}: {st}")
            mime = (entry.get("attributes") or {}).get("mime") or ""
            headers = {"Content-Type": mime} if mime else {}
            st, body, _ = http_bytes("PUT", self.target + _quote(path),
                                     body, headers)
            if st >= 300:
                raise RuntimeError(
                    f"filer.sync: write {path} to {self.target}: "
                    f"{st} {body[:200]!r}")
        attrs = entry.get("attributes")
        if attrs:
            # mode/uid/gid/mtime/crtime/ttl/symlink can't ride the
            # content PUT; mirror them explicitly (UpdateEntry)
            st, body, _ = http_bytes(
                "POST", self.target + "/__meta__/set_attrs",
                json.dumps({"path": path,
                            "attributes": attrs}).encode(),
                {"Content-Type": "application/json"})
            if st >= 300:
                raise RuntimeError(
                    f"filer.sync: set_attrs {path}: {st} "
                    f"{body[:200]!r}")

    # -- loop -------------------------------------------------------------

    def sync_once(self, batch: int = 1000) -> int:
        """Pull and apply one batch; returns the number applied.  The
        offset checkpoints after EVERY event, so a crash between events
        re-applies at most one (applications are idempotent)."""
        since = self.offset()
        r = http_json("GET", f"{self.source}/__meta__/events"
                             f"?sinceNs={since}&limit={batch}")
        if "events" not in r:
            raise RuntimeError(
                f"filer.sync: source {self.source} events: "
                f"{r.get('error', r)}")
        events = r["events"]
        for ev in events:
            self._apply(ev)
            self._save_offset(int(ev["tsNs"]))
        return len(events)

    def run(self) -> None:
        failures = 0
        while not self._stop.is_set():
            try:
                n = self.sync_once()
                failures = 0
            except Exception as e:  # noqa: BLE001 — keep syncing; a
                n = 0               # down peer is retried next tick
                failures += 1
                if failures in (1, 10) or failures % 100 == 0:
                    log.warning(
                        "filer.sync %s -> %s failing (attempt %d): %s",
                        self.source, self.target, failures, e)
            if n == 0:
                self._stop.wait(self.poll_interval)

    def start(self) -> "FilerSync":
        self._thread = threading.Thread(target=self.run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
