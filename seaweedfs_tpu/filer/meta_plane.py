"""Filer meta plane: the metalog as the filer's WRITE-AHEAD LOG, the
store as an asynchronously maintained CHECKPOINT (ISSUE 13).

PR 11's durability trick, applied one layer up.  The volume plane
already treats the `.dat` as the WAL and the `.idx` as a checkpoint
rebuilt by tail replay; here the filer treats the METALOG the same
way.  A namespace mutation is acknowledged once its event clears the
metalog's group-commit barrier — already durable (page-cache write,
the same tier as a sqlite WAL commit), already batched.  The
sqlite/LSM store is applied *asynchronously* in per-window batched
transactions by ONE designated applier (an `flock` on the shared log
dir elects it across pre-fork workers, so the cross-process sqlite
WAL-lock convoy disappears: one committer instead of N).

Reads stay EXACT through an in-memory overlay of the unapplied tail:

* every acked event is ingested into `{path -> entry|tombstone}` (and
  a per-directory name index) before the ack returns;
* `find`/`list` consult overlay-over-store — an entry the applier has
  not reached yet is served from the overlay, a tombstone hides the
  store's stale row, listings merge both;
* sibling instances' events arrive by FOLLOWING the shared log
  (`_Cursor`): `catch_up()` is a cheap stat probe on the read path —
  any event durably appended before a read began is ingested before
  that read is served, which is exactly the write-through-worker-A /
  read-through-worker-B-immediately-fresh contract, WITHOUT the
  watermark-invalidation storms that made the worker-mode meta cache
  thrash (sibling commits now arrive as point invalidations);
* overlay entries are evicted once the applier's CHECKPOINT — a
  `(segment, offset)` cursor persisted in the log dir, advanced only
  AFTER the covering store transaction commits — passes their
  position.  Eviction re-invalidates the meta cache for the path, so
  a fill that raced the unapplied window can never resurface.

Crash safety: the checkpoint is a conservative lower bound of what
the store holds, and replaying the log from any such bound re-applies
an idempotent prefix in file order — so a SIGKILL anywhere between
ack and apply loses nothing (boot replay), and a crash between a
store commit and its checkpoint write merely re-applies a window.
Rotation is multi-writer racy by nature (a sibling can land a late
line in a segment the cursor already left), so the cursor re-reads
left-behind segments for a grace period and the checkpoint never
advances past an unsealed segment (`_Cursor.safe_pos`).

`SEAWEEDFS_TPU_FILER_META_PLANE=0` is the kill switch restoring the
synchronous store commit; its boot path still replays any unapplied
tail a planed run left behind (`recover_sync`), so flipping the knob
never un-acks history.
"""

from __future__ import annotations

import json
import os
import threading
import time
import zlib
from collections import deque

from .entry import Entry
from .meta_log import LOG_START, _segment_name

_OMISS = object()          # lookup(): "the overlay has no opinion"

CHECKPOINT_FILE = "checkpoint"
APPLIER_LOCK_FILE = "applier.lock"
_CKPT_WIDTH = 256
_ROTATE_GRACE_S = 2.0
_APPLY_BATCH_MAX = 4096


def meta_plane_enabled() -> "bool | None":
    """SEAWEEDFS_TPU_FILER_META_PLANE: "0" forces the synchronous
    commit path, "1" forces the plane on (where the store supports
    it), unset = auto (on for durable local stores with a metalog
    dir)."""
    v = os.environ.get("SEAWEEDFS_TPU_FILER_META_PLANE", "")
    if v == "0":
        return False
    if v in ("1", "force"):
        return True
    return None


def plane_interval_s() -> float:
    """SEAWEEDFS_TPU_META_PLANE_INTERVAL_MS — the applier/follower
    tick (default 20ms).  The crash suite inflates it to hold the
    ack-to-apply window open under SIGKILL."""
    try:
        ms = int(os.environ.get(
            "SEAWEEDFS_TPU_META_PLANE_INTERVAL_MS", "") or 20)
    except ValueError:
        ms = 20
    return max(1, ms) / 1e3


# -- checkpoint file ------------------------------------------------------

def _encode_ckpt(pos: "tuple[str, str, int]", ts: int) -> bytes:
    body = json.dumps({"day": pos[0], "minute": pos[1],
                       "offset": pos[2], "tsNs": ts},
                      separators=(",", ":"))
    body = f"{body}|{zlib.crc32(body.encode('ascii')) & 0xFFFFFFFF:08x}"
    return body.ljust(_CKPT_WIDTH).encode("ascii")


def _decode_ckpt(data: bytes):
    """(pos, tsNs), or None when torn/invalid — the reader treats a
    torn checkpoint as LOG_START (replay more, never less)."""
    try:
        text = data.decode("ascii").strip()
        body, sep, crc = text.rpartition("|")
        if not sep or \
                int(crc, 16) != zlib.crc32(body.encode("ascii")) & \
                0xFFFFFFFF:
            return None
        d = json.loads(body)
        return ((d["day"], d["minute"], int(d["offset"])),
                int(d.get("tsNs", 0)))
    except (ValueError, KeyError, UnicodeError):
        return None


def read_checkpoint(dir_path: str):
    """(pos, tsNs); (LOG_START, 0) when the file is torn (replay is
    idempotent, so low is the safe direction); None when the plane
    has never run over this log."""
    try:
        with open(os.path.join(dir_path, CHECKPOINT_FILE), "rb") as f:
            data = f.read(_CKPT_WIDTH)
    except OSError:
        return None
    return _decode_ckpt(data) or (LOG_START, 0)


# -- log follower ---------------------------------------------------------

class _Cursor:
    """Follow the metalog segment files from a position, yielding
    parsed events with their end-of-line positions.  Positions are
    `(day, minute, offset)` tuples ordered by plain comparison.

    Rotation: segment choice is per-writer (each picks by its event's
    stamp), so around a minute boundary a sibling can append a LATE
    line to a segment this cursor already left.  Left segments are
    therefore re-read for `_ROTATE_GRACE_S` (late lines are delivered
    out of order — the overlay's position rule makes that safe), and
    `safe_pos()` pins the checkpoint below any unsealed segment so a
    crash can never strand a late-acked line behind the cursor."""

    READ_MAX = 1 << 20

    def __init__(self, dir_path: str, pos: "tuple[str, str, int]",
                 skip_wid: str = "", skip_fn=None):
        self.dir = dir_path
        self.day, self.minute, self.off = pos
        # own-batch extent oracle (MetaLog.own_extent_at): lets the
        # coherence follower jump over bytes this instance appended
        # without a single read syscall
        self._skip_fn = skip_fn
        # [day, minute, offset, grace deadline] per left-behind segment
        self._left: "list[list]" = []
        self._mtime_root = -1
        self._mtime_day = -1
        # coherence cursors pass their own writer id: lines this
        # instance appended are already in the overlay (ingested at
        # ack), so they are skip-scanned by a substring check instead
        # of json-parsed — the wid field sits in the line's fixed
        # header region
        self._skip_marker = f'"wid":"{skip_wid}"' if skip_wid else ""
        self._fh = None              # cached active-segment handle
        self._fh_seg: "tuple[str, str] | None" = None

    def pos(self) -> "tuple[str, str, int]":
        return (self.day, self.minute, self.off)

    def safe_pos(self) -> "tuple[str, str, int]":
        p = self.pos()
        for d, m, off, _dl in self._left:
            p = min(p, (d, m, off))
        return p

    def _seg_path(self, day: str, minute: str) -> str:
        return os.path.join(self.dir, day, minute + ".log")

    def _next_segment(self, day: str, minute: str):
        try:
            days = sorted(
                d for d in os.listdir(self.dir)
                if os.path.isdir(os.path.join(self.dir, d)))
        except OSError:
            return None
        for d in days:
            if day and d < day:
                continue
            try:
                minutes = sorted(
                    m[:-4]
                    for m in os.listdir(os.path.join(self.dir, d))
                    if m.endswith(".log"))
            except OSError:
                continue
            for m in minutes:
                if d == day and m <= minute:
                    continue
                return (d, m)
        return None

    def _active_handle(self, day: str, minute: str):
        """Cached read handle for the cursor's active segment (an
        open()+BufferedReader per poll was a measurable share of the
        read-path coherence probe); non-active (grace) segments open
        transiently."""
        seg = (day, minute)
        if self._fh is not None and self._fh_seg == seg:
            return self._fh, False
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None
        self._fh = open(self._seg_path(day, minute), "rb")
        self._fh_seg = seg
        return self._fh, False

    def _read_lines(self, day: str, minute: str, off: int,
                    transient: bool = False):
        """Complete lines at `off`: ([(event, raw_new, pos, wid)],
        new_offset).  Unparseable lines (a torn tail later sealed
        over by O_APPEND writers) are skipped but still advance the
        offset, matching events_since's torn-line tolerance; own
        lines (skip_wid) are skip-scanned without parsing."""
        out: list = []
        try:
            if transient:
                f = open(self._seg_path(day, minute), "rb")
            else:
                f, _ = self._active_handle(day, minute)
            try:
                f.seek(off)
                data = f.read(self.READ_MAX)
            finally:
                if transient:
                    f.close()
        except OSError:
            return out, off
        end = data.rfind(b"\n")
        if end < 0:
            return out, off
        line_off = off
        skip = self._skip_marker.encode("ascii") \
            if self._skip_marker else b""
        for raw in data[:end + 1].split(b"\n")[:-1]:
            line_off += len(raw) + 1
            if not raw:
                continue
            if skip and skip in raw[:72]:
                continue     # own line: already ingested at ack time
            try:
                text = raw.decode("utf-8")
                ev = json.loads(text)
            except (ValueError, UnicodeDecodeError):
                continue
            if not isinstance(ev, dict):
                continue
            nl = ev.pop("nl", None)
            wid = ev.pop("wid", "")
            raw_new = None
            if isinstance(nl, int) and 0 < nl <= len(text) - 2:
                # newEntry is the line's LAST value: exactly the bytes
                # the appender serialized once (meta_log.append_raw)
                raw_new = text[-(nl + 1):-1]
            out.append((ev, raw_new, (day, minute, line_off), wid))
        return out, off + end + 1

    def _next_segment_cached(self):
        """`_next_segment` behind a dir-mtime memo: creating a segment
        bumps its parent dir's mtime, so unchanged mtimes mean no
        rotation and the two listdirs are skipped (they were a
        measurable share of the read-path coherence probe).  Mtimes
        are sampled BEFORE the listing, and the memo advances only
        when the listing finds nothing — a creation racing the
        listing re-checks next call instead of getting lost."""
        try:
            rt = os.stat(self.dir).st_mtime_ns
        except OSError:
            return None
        dt = -1
        if self.day:
            try:
                dt = os.stat(os.path.join(
                    self.dir, self.day)).st_mtime_ns
            except OSError:
                dt = -1
        if rt == self._mtime_root and dt == self._mtime_day:
            return None
        nxt = self._next_segment(self.day or "", self.minute or "")
        if nxt is None:
            self._mtime_root, self._mtime_day = rt, dt
        return nxt

    def poll(self, limit: int = 0) -> list:
        """Drain newly appended events (all of them, or up to
        `limit`), following rotations."""
        now = time.monotonic()
        out: list = []
        kept = []
        for ent in self._left:
            evs, new_off = self._read_lines(ent[0], ent[1], ent[2],
                                            transient=True)
            if new_off != ent[2]:
                out.extend(evs)
                ent[2] = new_off
                ent[3] = now + _ROTATE_GRACE_S  # still warm
                kept.append(ent)
            elif now < ent[3]:
                kept.append(ent)
        self._left = kept
        while not limit or len(out) < limit:
            if not self.day:
                nxt = self._next_segment_cached()
                if nxt is None:
                    break
                self.day, self.minute, self.off = nxt[0], nxt[1], 0
            if self._skip_fn is not None:
                end = self._skip_fn(self.day, self.minute, self.off)
                if end is not None and end > self.off:
                    self.off = end
                    continue
            evs, new_off = self._read_lines(self.day, self.minute,
                                            self.off)
            if new_off != self.off:
                out.extend(evs)
                self.off = new_off
                continue
            nxt = self._next_segment_cached()
            if nxt is None:
                break
            self._left.append([self.day, self.minute, self.off,
                               now + _ROTATE_GRACE_S])
            self.day, self.minute, self.off = nxt[0], nxt[1], 0
        return out

    def probe(self) -> bool:
        """Cheap "is there anything unread?" — one fstat on the
        cached active-segment handle (exact for the common in-segment
        append; fstat skips the path walk, which matters on slow
        network/9p filesystems), with the rotation check gated on the
        WALL-CLOCK segment name: a newer segment than the cursor's
        can only exist once the shared clock's minute has moved past
        it (writers pick segments from their event stamps, and every
        process reads the same host clock), so the steady state under
        write load is pure arithmetic plus ONE fstat.  Own-batch
        extents are consumed first."""
        if self.day:
            if self._skip_fn is not None:
                end = self._skip_fn(self.day, self.minute, self.off)
                if end is not None and end > self.off:
                    self.off = end
            try:
                f, _ = self._active_handle(self.day, self.minute)
                if os.fstat(f.fileno()).st_size > self.off:
                    return True
            except OSError:
                pass
        for d, m, off, _dl in self._left:
            try:
                if os.path.getsize(self._seg_path(d, m)) > off:
                    return True
            except OSError:
                continue
        if self.day and \
                _segment_name(time.time_ns()) == (self.day,
                                                  self.minute):
            return False     # the cursor is ON the live segment
        return self._next_segment_cached() is not None

    def close(self) -> None:
        if self._fh is not None:
            try:
                self._fh.close()
            except OSError:
                pass
            self._fh = None


def _records(batch: list) -> "tuple[list, int]":
    """Apply records from cursor events: [(op, new_path, raw_new,
    new_dict, old_path)], plus the batch's max stamp."""
    recs, last_ts = [], 0
    for ev, raw_new, _pos, _wid in batch:
        op = ev.get("op", "")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        npath = new.get("fullPath") if isinstance(new, dict) else None
        opath = old.get("fullPath") if isinstance(old, dict) else None
        if npath is None and opath is None:
            continue
        recs.append((op, npath, raw_new, new, opath))
        ts = int(ev.get("tsNs", 0) or 0)
        if ts > last_ts:
            last_ts = ts
    return recs, last_ts


def _note_sub(stage: str, seconds: float) -> None:
    # per-stage observers resolved once (stats.Metrics.observer,
    # ROADMAP 1d): this runs on every meta commit's ack path
    from ..stats import META_SUB_BUCKETS, PROCESS
    obs = PROCESS.obs_memo.get(("filer_meta_sub_seconds", stage))
    if obs is None:
        obs = PROCESS.obs_memo[("filer_meta_sub_seconds", stage)] = \
            PROCESS.observer(
                "filer_meta_sub_seconds", buckets=META_SUB_BUCKETS,
                help_text="filer meta-commit sub-stage wall: "
                          "serialize (entry -> WAL bytes, once), "
                          "barrier (metalog group-commit = the ack), "
                          "apply (async store transaction, per-event "
                          "share)", stage=stage)
    obs(seconds)


class MetaPlane:
    """One filer instance's half of the WAL/checkpoint protocol: the
    overlay index, the coherence follower, and (when this instance
    holds the applier lock) the batched store applier."""

    def __init__(self, store, meta_log, interval: "float | None" = None):
        self.store = store
        self.log = meta_log
        self.dir = meta_log.dir
        self.cache = None          # FilerMetaCache, wired by Filer
        # optional tap on the coherence follower: called (outside the
        # overlay/cursor locks) with every batch of SIBLING events the
        # follower ingests — the native meta plane driver uses it to
        # track foreign directory truth (server/meta_plane_native.py)
        self.sink = None
        self._interval = plane_interval_s() if interval is None \
            else interval
        self._olock = threading.Lock()
        # serializes the coherence cursor (probe/poll are single-
        # consumer and syscall-heavy); ALWAYS taken outside _olock,
        # never inside, so overlay lookups and ack-path ingests don't
        # queue behind a sibling drain's file reads
        self._clock = threading.Lock()
        # path -> (pos, tsNs, Entry | None-for-tombstone)
        self._paths: "dict[str, tuple]" = {}
        self._dirs: "dict[str, set]" = {}      # dir -> child names
        self._evq: deque = deque()             # (pos, path) in order
        self._cursor = _Cursor(self.dir, LOG_START)
        self._stop = threading.Event()
        self._holder = False
        self._lockf = None
        self._apply_cursor: "_Cursor | None" = None
        self._ckpt_fd: "int | None" = None
        self._ckpt_pos = LOG_START
        self._ckpt_ts = 0
        self._ckpt_memo: "tuple" = (LOG_START, 0.0)
        self._last_acquire = 0.0
        self.applied = 0

        ckpt = read_checkpoint(self.dir)
        if ckpt is None:
            # first enablement: everything already in the log was
            # committed synchronously by the pre-plane path, so the
            # store has it — anchor the checkpoint at the END, and do
            # it DURABLY before the first WAL-only ack can happen
            pos = self.log.end_pos()
            self._create_checkpoint(pos)
        else:
            pos = ckpt[0]
        self._ckpt_pos = pos
        self._cursor = _Cursor(self.dir, pos, skip_wid=meta_log.wid,
                               skip_fn=meta_log.own_extent_at)
        # boot replay into the overlay, synchronously: events a dead
        # process acked but never applied must be readable before the
        # first request is served (the applier re-applies them to the
        # store in the background)
        self._ingest(self._cursor.poll())
        self._thread = threading.Thread(
            target=self._run, name="filer-meta-plane", daemon=True)
        self._thread.start()

    # -- the ack path ------------------------------------------------

    def commit(self, op: str, new_entry, old_entry) -> dict:
        """Serialize ONCE, clear the WAL barrier (the durability
        point — this IS the ack), ingest into the overlay.  Returns
        the event for the filer's listeners."""
        t0 = time.perf_counter()
        new_dict = new_entry.to_json() if new_entry is not None else None
        old_dict = old_entry.to_json() if old_entry is not None else None
        raw_new = json.dumps(new_dict, separators=(",", ":")) \
            if new_dict is not None else None
        raw_old = json.dumps(old_dict, separators=(",", ":")) \
            if old_dict is not None else None
        t1 = time.perf_counter()
        event, pos = self.log.append_raw(op, new_dict, old_dict,
                                         raw_new, raw_old)
        t2 = time.perf_counter()
        ts = event["tsNs"]
        with self._olock:
            if new_entry is not None and new_entry.full_path != "/":
                self._ingest_locked(new_entry.full_path,
                                    new_entry.clone(), ts, pos)
            if old_entry is not None and op in ("delete", "rename") \
                    and (new_entry is None or
                         old_entry.full_path != new_entry.full_path) \
                    and old_entry.full_path != "/":
                self._ingest_locked(old_entry.full_path, None, ts, pos)
        _note_sub("serialize", t1 - t0)
        _note_sub("barrier", t2 - t1)
        return event

    def _ingest_locked(self, path: str, entry, ts: int,
                       pos: "tuple[str, str, int]") -> bool:
        """File-order-wins by position; STAMP-order-wins on a
        position tie.  Two racing writers to one path that land in
        the same barrier batch share the batch-end cover position and
        reach this ingest in _olock-acquisition order — which is NOT
        event order — so the tie-break must be the stamp (strictly
        monotonic per instance; same-instance is the only way to
        share a batch).  A follower's re-delivery of an
        already-ingested line sits strictly below the ack-time cover
        and stays a no-op."""
        cur = self._paths.get(path)
        if cur is not None and (cur[0] > pos or
                                (cur[0] == pos and cur[1] >= ts)):
            return False
        self._paths[path] = (pos, ts, entry)
        parent, _, name = path.rpartition("/")
        self._dirs.setdefault(parent or "/", set()).add(name)
        self._evq.append((pos, path))
        return True

    # -- reads -------------------------------------------------------

    def _materialize_locked(self, path: str, rec: tuple):
        """Overlay values from SIBLING events are kept as their
        parsed-JSON dicts and turned into Entry objects only when a
        read actually wants them — most overlay records are evicted
        unread, so the per-event Entry construction would be pure
        follower overhead."""
        val = rec[2]
        if type(val) is dict:
            val = Entry.from_json(val)
            self._paths[path] = (rec[0], rec[1], val)
        return val

    def lookup(self, path: str):
        """Entry clone source / tombstone (None) / _OMISS."""
        with self._olock:
            rec = self._paths.get(path)
            if rec is None:
                return _OMISS
            return self._materialize_locked(path, rec)

    def overlay_dir(self, dir_path: str) -> "dict | None":
        """{name: Entry|None} snapshot of this directory's unapplied
        tail, or None when the overlay has nothing for it (the common
        fast path: one dict probe)."""
        base = dir_path.rstrip("/")
        with self._olock:
            names = self._dirs.get(dir_path if dir_path == "/"
                                   else (base or "/"))
            if not names:
                return None
            out = {}
            for n in names:
                p = f"{base}/{n}"
                rec = self._paths.get(p)
                if rec is not None:
                    out[n] = self._materialize_locked(p, rec)
            return out or None

    def catch_up(self) -> None:
        """Read-path coherence: ingest any event durably appended by a
        SIBLING before this read began.  One fstat in the common case
        (`_Cursor.probe`).  Poll and ingest share the cursor lock's
        critical section: a reader that found the cursor clean must be
        able to rely on every polled event being IN the overlay
        already, not in some other thread's hands."""
        if self._stop.is_set():
            return
        inv = None
        evs = None
        with self._clock:
            if self._cursor.probe():
                evs = self._cursor.poll()
                if evs:
                    with self._olock:
                        inv = self._ingest_events_locked(evs)
        self._invalidate(inv)
        self._drain_sink(evs)

    def _ingest(self, batch: list) -> None:
        with self._olock:
            inv = self._ingest_events_locked(batch)
        self._invalidate(inv)
        self._drain_sink(batch)

    def _drain_sink(self, evs) -> None:
        if evs and self.sink is not None:
            try:
                self.sink(evs)
            except Exception:  # noqa: SWFS004 — the tap is advisory;
                pass           # coherence never depends on it

    def _invalidate(self, paths) -> None:
        if paths and self.cache is not None:
            for p in paths:
                self.cache.invalidate(p)

    def _ingest_events_locked(self, batch: list) -> list:
        """Sibling events -> overlay + point cache invalidations (own
        events were ingested at ack time and their cache entries
        invalidated by the filer's listener — the wid check skips the
        redundant Entry.from_json)."""
        inv = []
        own = self.log.wid
        for ev, _raw, pos, wid in batch:
            if wid and wid == own:
                continue
            ts = int(ev.get("tsNs", 0) or 0)
            op = ev.get("op", "")
            new = ev.get("newEntry")
            old = ev.get("oldEntry")
            if isinstance(new, dict) and \
                    isinstance(new.get("fullPath"), str) and \
                    new.get("fullPath") != "/":
                # ingest the parsed dict as-is; Entry materialization
                # is deferred to the first read (_materialize_locked)
                npath = new["fullPath"]
                if self._ingest_locked(npath, new, ts, pos):
                    inv.append(npath)
            if isinstance(old, dict) and op in ("delete", "rename"):
                opath = old.get("fullPath", "/")
                npath = new.get("fullPath") if isinstance(new, dict) \
                    else None
                if opath != "/" and opath != npath and \
                        self._ingest_locked(opath, None, ts, pos):
                    inv.append(opath)
        return inv

    # -- applier -----------------------------------------------------

    def _run(self) -> None:
        from ..util import wlog
        while not self._stop.wait(self._interval):
            try:
                self.catch_up()
                self._tick_applier()
                self._evict()
            except Exception as e:  # noqa: BLE001 — the plane thread
                wlog.warning("meta plane tick: %s", e,  # must survive
                             component="filer")
                time.sleep(0.2)

    def _try_acquire(self) -> bool:
        import fcntl
        if self._lockf is None:
            self._lockf = open(
                os.path.join(self.dir, APPLIER_LOCK_FILE), "a+")
        try:
            fcntl.flock(self._lockf.fileno(),
                        fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            return False
        return True

    def _tick_applier(self) -> None:
        if not self._holder:
            now = time.monotonic()
            if now - self._last_acquire < 0.25:
                return
            self._last_acquire = now
            if not self._try_acquire():
                return
            self._holder = True
            # fresh holder: apply from the DURABLE checkpoint (a dead
            # sibling's applier may be arbitrarily behind our own
            # coherence cursor)
            ckpt = read_checkpoint(self.dir)
            self._ckpt_pos = ckpt[0] if ckpt else LOG_START
            self._ckpt_ts = ckpt[1] if ckpt else 0
            self._apply_cursor = _Cursor(self.dir, self._ckpt_pos)
        self._apply_pending()

    def _apply_pending(self) -> None:
        cur = self._apply_cursor
        while not self._stop.is_set():
            batch = cur.poll(limit=_APPLY_BATCH_MAX)
            if not batch:
                # grace expiry can move the seal floor forward with no
                # new events; keep the checkpoint honest
                self._advance_checkpoint(cur.safe_pos(), self._ckpt_ts)
                return
            t0 = time.perf_counter()
            recs, last_ts = _records(batch)
            if recs:
                self.store.apply_events(recs)
            wall = time.perf_counter() - t0
            self.applied += len(recs)
            from ..stats import GROUP_COMMIT_BATCH_BUCKETS, PROCESS
            PROCESS.counter_add(
                "meta_plane_applied_total", float(len(recs)),
                help_text="metalog events applied to the filer store "
                          "by the async checkpoint applier")
            PROCESS.histogram_observe(
                "meta_plane_apply_batch", float(max(len(recs), 1)),
                buckets=GROUP_COMMIT_BATCH_BUCKETS,
                help_text="events per async store transaction")
            if recs:
                _note_sub("apply", wall / len(recs))
            self._advance_checkpoint(cur.safe_pos(), last_ts)

    # -- checkpoint --------------------------------------------------

    def _create_checkpoint(self, pos: "tuple[str, str, int]") -> None:
        """First-enablement anchor, O_EXCL so racing sibling boots
        cannot leapfrog each other past events acked in between —
        exactly one anchor wins, the rest adopt it."""
        path = os.path.join(self.dir, CHECKPOINT_FILE)
        try:
            fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL,
                         0o644)
        except FileExistsError:
            ckpt = read_checkpoint(self.dir)
            if ckpt is not None:
                self._ckpt_pos = ckpt[0]
            return
        try:
            os.pwrite(fd, _encode_ckpt(pos, 0), 0)
        finally:
            os.close(fd)

    def _advance_checkpoint(self, pos: "tuple[str, str, int]",
                            ts: int) -> None:
        """Applier-only (this process owns the applier flock, and the
        checkpoint fields are touched by the plane thread alone).
        Written AFTER the covering store commit and monotonic by
        construction — a crash between commit and write re-applies a
        window, never skips one."""
        ts = max(ts, self._ckpt_ts)
        if pos <= self._ckpt_pos and ts <= self._ckpt_ts:
            return
        pos = max(pos, self._ckpt_pos)
        if self._ckpt_fd is None:
            try:
                self._ckpt_fd = os.open(
                    os.path.join(self.dir, CHECKPOINT_FILE),
                    os.O_WRONLY | os.O_CREAT, 0o644)
            except OSError:
                return
        try:
            os.pwrite(self._ckpt_fd, _encode_ckpt(pos, ts), 0)
        except OSError:
            return
        self._ckpt_pos = pos
        self._ckpt_ts = ts

    def _evict_floor(self) -> "tuple[str, str, int]":
        if self._holder:
            return self._ckpt_pos
        now = time.monotonic()
        if now - self._ckpt_memo[1] > 0.05:
            ckpt = read_checkpoint(self.dir)
            self._ckpt_memo = (ckpt[0] if ckpt else LOG_START, now)
        return self._ckpt_memo[0]

    def _evict(self) -> None:
        """Drop overlay entries whose position the checkpoint passed:
        the store commit covering them is durable, so overlay and
        store agree.  No cache invalidation here, by proof rather
        than by accident: while a path is in the overlay, reads
        short-circuit before any cache fill, so the cache cannot
        ACQUIRE a value for it — and the fill that was in flight when
        the path's event arrived died on the event-time epoch bump
        (listener for own events, ingest for siblings).  Re-bumping
        per eviction would kill every in-flight fill at the cluster's
        full event rate — exactly the watermark-storm thrash this
        plane exists to remove."""
        floor = self._evict_floor()
        with self._olock:
            while self._evq and self._evq[0][0] <= floor:
                pos, path = self._evq.popleft()
                rec = self._paths.get(path)
                if rec is None or rec[0] != pos:
                    continue          # superseded by a later event
                del self._paths[path]
                parent, _, name = path.rpartition("/")
                names = self._dirs.get(parent or "/")
                if names is not None:
                    names.discard(name)
                    if not names:
                        self._dirs.pop(parent or "/", None)

    # -- introspection / teardown ------------------------------------

    def snapshot(self) -> dict:
        with self._olock:
            overlay = len(self._paths)
        return {"overlay": overlay, "holder": self._holder,
                "applied": self.applied,
                "checkpointTsNs": self._ckpt_ts}

    def close(self) -> None:
        from ..util import wlog
        self._stop.set()
        self._thread.join(timeout=10)
        try:
            if not self._holder and self._try_acquire():
                # a stalled/never-elected applier (inflated interval,
                # short-lived instance) still leaves the store a
                # complete checkpoint when it can take the lock now
                self._holder = True
                ckpt = read_checkpoint(self.dir)
                self._ckpt_pos = ckpt[0] if ckpt else LOG_START
                self._ckpt_ts = ckpt[1] if ckpt else 0
                self._apply_cursor = _Cursor(self.dir, self._ckpt_pos)
            if self._holder and self._apply_cursor is not None:
                # clean shutdown leaves the store a COMPLETE
                # checkpoint: apply everything, then advance
                self._stop.clear()
                try:
                    self._apply_pending()
                finally:
                    self._stop.set()
        except Exception as e:  # noqa: BLE001 — teardown must finish
            wlog.warning("meta plane final apply: %s", e,
                         component="filer")
        if self._lockf is not None:
            try:
                self._lockf.close()     # releases the flock
            except OSError:
                pass
            self._lockf = None
        self._holder = False
        self._cursor.close()
        if self._apply_cursor is not None:
            self._apply_cursor.close()
        if self._ckpt_fd is not None:
            try:
                os.close(self._ckpt_fd)
            except OSError:
                pass
            self._ckpt_fd = None


def recover_sync(meta_log, store) -> int:
    """Kill-switch boot replay: with the plane OFF, a checkpoint left
    by a previous planed run may trail WAL-acked events the store
    never saw.  Apply them synchronously (file order, idempotent)
    before serving, and advance the checkpoint.  Returns the number
    of events applied."""
    import fcntl
    d = meta_log.dir
    if not d:
        return 0
    ckpt = read_checkpoint(d)
    if ckpt is None:
        return 0                 # the plane never ran over this log
    lockf = open(os.path.join(d, APPLIER_LOCK_FILE), "a+")
    try:
        # the boot-time tail [checkpoint, end-at-entry) must be in the
        # store BEFORE this filer serves — whoever holds the applier
        # lock (a sibling's recover_sync, or a live plane-ON applier
        # in a mixed fleet) is applying it, so wait for EITHER the
        # lock (holder finished/died: flock releases) or a checkpoint
        # at/past the entry-time log end (holder applied our tail)
        end_at_entry = meta_log.end_pos()
        while True:
            try:
                fcntl.flock(lockf.fileno(),
                            fcntl.LOCK_EX | fcntl.LOCK_NB)
                break
            except OSError:
                ckpt = read_checkpoint(d)
                if ckpt is not None and ckpt[0] >= end_at_entry:
                    return 0     # the holder covered our tail
                time.sleep(0.05)
        ckpt = read_checkpoint(d) or (LOG_START, 0)
        cur = _Cursor(d, ckpt[0])
        applied, last_ts = 0, ckpt[1]
        fd = None
        try:
            while True:
                batch = cur.poll(limit=_APPLY_BATCH_MAX)
                if not batch:
                    break
                recs, ts = _records(batch)
                if recs:
                    store.apply_events(recs)
                applied += len(recs)
                last_ts = max(last_ts, ts)
                if fd is None:
                    fd = os.open(os.path.join(d, CHECKPOINT_FILE),
                                 os.O_WRONLY | os.O_CREAT, 0o644)
                os.pwrite(fd, _encode_ckpt(cur.safe_pos(), last_ts), 0)
        finally:
            if fd is not None:
                os.close(fd)
        return applied
    finally:
        lockf.close()
