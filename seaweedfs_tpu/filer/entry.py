"""Filer entry model (weed/filer/entry.go, filechunks.go).

An Entry is a directory or a file; files carry an ordered chunk list
[{file_id, offset, size, e_tag, mtime_ns}] over the volume store.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field


@dataclass
class FileChunk:
    file_id: str
    offset: int
    size: int
    e_tag: str = ""
    mtime_ns: int = 0

    def to_json(self) -> dict:
        return {"fileId": self.file_id, "offset": self.offset,
                "size": self.size, "eTag": self.e_tag,
                "mtime": self.mtime_ns}

    @classmethod
    def from_json(cls, d: dict) -> "FileChunk":
        return cls(d["fileId"], int(d.get("offset", 0)),
                   int(d.get("size", 0)), d.get("eTag", ""),
                   int(d.get("mtime", 0)))


@dataclass
class Attributes:
    mtime: float = field(default_factory=time.time)
    crtime: float = field(default_factory=time.time)
    mode: int = 0o660
    uid: int = 0
    gid: int = 0
    mime: str = ""
    ttl_sec: int = 0
    symlink_target: str = ""

    def to_json(self) -> dict:
        return {"mtime": self.mtime, "crtime": self.crtime,
                "mode": self.mode, "uid": self.uid, "gid": self.gid,
                "mime": self.mime, "ttlSec": self.ttl_sec,
                "symlinkTarget": self.symlink_target}

    @classmethod
    def from_json(cls, d: dict) -> "Attributes":
        return cls(d.get("mtime", 0), d.get("crtime", 0),
                   d.get("mode", 0o660), d.get("uid", 0),
                   d.get("gid", 0), d.get("mime", ""),
                   d.get("ttlSec", 0), d.get("symlinkTarget", ""))


@dataclass
class Entry:
    full_path: str                      # canonical, starts with /
    is_directory: bool = False
    attributes: Attributes = field(default_factory=Attributes)
    chunks: list[FileChunk] = field(default_factory=list)
    extended: dict = field(default_factory=dict)  # user metadata

    @property
    def name(self) -> str:
        return self.full_path.rsplit("/", 1)[-1]

    @property
    def parent(self) -> str:
        p = self.full_path.rsplit("/", 1)[0]
        return p or "/"

    def total_size(self) -> int:
        """filer/entry.go Size: max over chunk extents."""
        return max((c.offset + c.size for c in self.chunks), default=0)

    def to_json(self) -> dict:
        return {
            "fullPath": self.full_path,
            "isDirectory": self.is_directory,
            "attributes": self.attributes.to_json(),
            "chunks": [c.to_json() for c in self.chunks],
            "extended": self.extended,
        }

    @classmethod
    def from_json(cls, d: dict) -> "Entry":
        return cls(
            full_path=d["fullPath"],
            is_directory=d.get("isDirectory", False),
            attributes=Attributes.from_json(d.get("attributes", {})),
            chunks=[FileChunk.from_json(c)
                    for c in d.get("chunks", [])],
            extended=d.get("extended", {}),
        )

    def clone(self) -> "Entry":
        """Deep-enough copy for the metadata cache: callers mutate
        attributes, the chunk list AND individual FileChunks in place
        (update_attrs / append_chunks / _clip_chunks), so every
        mutable layer is copied — a cached entry must never alias one
        a handler is editing."""
        import copy as _copy
        return Entry(
            full_path=self.full_path,
            is_directory=self.is_directory,
            attributes=_copy.copy(self.attributes),
            chunks=[_copy.copy(c) for c in self.chunks],
            extended=dict(self.extended),
        )


def normalize_path(path: str) -> str:
    """Canonical /a/b/c (no trailing slash except root)."""
    if not path.startswith("/"):
        path = "/" + path
    while "//" in path:
        path = path.replace("//", "/")
    if len(path) > 1 and path.endswith("/"):
        path = path[:-1]
    return path
