"""Filer metadata cache, invalidated by the metalog subscription.

The filer read path pays a store round-trip (sqlite/redis/ES) per
`find`/`list` even for the hottest paths; under zipfian read traffic
that store hop dominates small-object serving (arXiv:1709.05365's
host-side-overhead finding applied to metadata).  This cache keeps
entry and listing results in memory with TWO coherence mechanisms,
both anchored on the metalog:

* **In-process events** — every mutation this filer performs flows
  through `Filer._notify`, whose listener invalidates the touched
  paths *synchronously after the event is durably appended* and
  advances the processed cursor.  A single-filer deployment therefore
  has exact read-your-writes coherence with per-path granularity.

* **Durable-ts watermark** (PR 8's group-commit watermark, published
  per commit window as `.watermark.<pid>.<seq>` files in the shared
  metalog dir) — a SECOND filer over the same store shares the same
  metalog dir by construction, and `MetaLog.foreign_watermark()` is
  the cheap probe "has a SIBLING durably committed since my cache
  fills?".  Fills are stamped with the foreign watermark probed
  *before* the store read; the serve rule `current foreign_watermark
  <= fill stamp` means a write through filer A is visible to filer
  B's *next* read: A's commit advances the watermark past every
  pre-write fill stamp, so B bypasses its cache and reads the store.
  **Never serve an entry older than the watermark from cache.**
  (Sibling timestamps are wall-clock incomparable with our own, which
  is why own events are handled by the synchronous listener and ONLY
  foreign commits ride the watermark.)  First contact with a brand-new
  sibling is bounded by the probe's one-second listdir memo.

Fills are guarded by a global epoch so an in-flight fill racing an
invalidation can never resurrect a stale value (classic
fill/invalidate race): `begin_fill` snapshots the epoch *before* the
store read, and the fill lands only if no invalidation intervened.

Stores with no shared metalog dir (redis/elastic: PR 6 deliberately
gives co-located filers DISTINCT dirs) cannot see each other's
watermarks, so FilerServer leaves this cache off for them unless
explicitly opted in (``SEAWEEDFS_TPU_FILER_META_CACHE=force``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from ..util.chunk_cache import _CacheMeter

_MISS = object()


def _no_watermark() -> int:
    return 0


def meta_cache_entries(default: int = 4096) -> int:
    """``SEAWEEDFS_TPU_FILER_META_CACHE`` — max cached entry lookups
    (0 disables; "force" enables with the default size even for
    stores without a shared metalog dir)."""
    import os
    raw = os.environ.get("SEAWEEDFS_TPU_FILER_META_CACHE", "")
    if raw in ("", "force"):
        return default
    try:
        return int(raw)
    except ValueError:
        return default


class FilerMetaCache:
    """Bounded LRU over entry lookups + directory listings with the
    watermark/epoch coherence rules described in the module doc."""

    MAX_LISTS = 512

    def __init__(self, meta_log, capacity: int = 4096,
                 name: "str | None" = "filer_meta",
                 watermark: bool = True):
        self._log = meta_log
        # watermark=False: meta-plane mode (ISSUE 13).  The plane's
        # log follower delivers every sibling commit as a POINT
        # invalidation before any read that could observe it
        # (Filer -> MetaPlane.catch_up on the read path), so the
        # coarse "kill every fill at or before the foreign watermark"
        # rule — which under pre-fork workers degenerated into an
        # invalidation storm killing every fill within one sibling
        # commit window — is both unnecessary and harmful here.
        self._probe = meta_log.foreign_watermark if watermark \
            else _no_watermark
        self._cap = max(int(capacity), 1)
        self._lock = threading.Lock()
        # path -> (fill_watermark, entry-or-None)
        self._entries: "OrderedDict[str, tuple]" = OrderedDict()
        # (dir, start, include_start, limit, prefix) ->
        #   (fill_watermark, [entries])
        self._lists: "OrderedDict[tuple, tuple]" = OrderedDict()
        self._dir_keys: "dict[str, set]" = {}
        self._epoch = 0
        self._processed = 0     # own-instance event cursor
        self._meter = _CacheMeter(name)
        # negative-directory cache (ROADMAP 1b): dir ->
        # (fill_watermark, set-of-child-names-that-might-exist).  A
        # directory lands here when we SEE its fresh creation (op=
        # create, no old entry) — at that instant it is provably
        # empty, so any child name never added to the set is provably
        # ABSENT and the old-entry store SELECT on its create can be
        # skipped.  Coherence rides the exact mechanisms above: every
        # invalidation (own synchronous listener, sibling follower
        # point-invalidations in plane mode) adds the touched name to
        # its parent's set, and in watermark mode the fill stamp
        # additionally kills the record on any foreign commit.
        self._fresh_dirs: "OrderedDict[str, tuple]" = OrderedDict()

    # -- fill protocol -----------------------------------------------

    def begin_fill(self) -> "tuple[int, int]":
        """(epoch, foreign watermark) token taken BEFORE the store
        read: the fill is discarded if any invalidation bumps the
        epoch while the store read is in flight, and the value is
        stamped with a foreign watermark that pre-dates the read
        (conservative: a sibling's commit landing mid-read can only
        make the fill look stale, never fresh)."""
        wm = self._probe()
        with self._lock:
            return self._epoch, wm

    @staticmethod
    def _valid(fill_wm: int, probe: int) -> bool:
        # no sibling has durably committed since this fill began; own
        # events never reach the watermark — the synchronous listener
        # already invalidated their paths point-wise
        return probe <= fill_wm

    # -- entries -------------------------------------------------------

    def lookup_entry(self, path: str):
        """Cached entry (or cached None for a known-absent path), or
        the _MISS sentinel.  Callers must clone before mutating."""
        probe = self._probe()
        with self._lock:
            hit = self._entries.get(path)
            if hit is None or not self._valid(hit[0], probe):
                self._meter.count("misses")
                return _MISS
            self._entries.move_to_end(path)
        self._meter.count("hits")
        return hit[1]

    def fill_entry(self, path: str, entry, token) -> None:
        epoch, wm = token
        with self._lock:
            if self._epoch != epoch:
                return           # an invalidation raced the fill
            self._entries[path] = (wm, entry)
            self._entries.move_to_end(path)
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)

    @property
    def capacity(self) -> int:
        return self._cap

    def set_capacity(self, capacity: int) -> None:
        """Runtime resize (SLO autopilot actuator, ISSUE 20) — an
        autopilot-controlled knob; mutate only through the actuator
        registry (devtools rule SWFS021).  Shrink trims LRU-first
        immediately; coherence is untouched (the watermark/epoch
        stamps live on the surviving fills)."""
        with self._lock:
            self._cap = max(int(capacity), 1)
            while len(self._entries) > self._cap:
                self._entries.popitem(last=False)

    # -- negative-directory cache (ROADMAP 1b) -------------------------

    MAX_FRESH_DIRS = 512
    MAX_FRESH_CHILDREN = 65536

    def mark_fresh_dir(self, path: str) -> None:
        """`path` was just created as a brand-new directory (no prior
        entry): start tracking it as provably-empty-except-what-we-
        see.  Called from the event listener, AFTER the create is
        durable."""
        wm = self._probe()
        with self._lock:
            self._fresh_dirs.pop(path, None)
            self._fresh_dirs[path] = (wm, set())
            while len(self._fresh_dirs) > self.MAX_FRESH_DIRS:
                self._fresh_dirs.popitem(last=False)

    def known_absent(self, path: str) -> bool:
        """True when `path` provably has no entry: its parent is a
        tracked fresh directory, no commit we could have missed has
        happened since tracking began, and the name was never touched.
        A True return makes the caller skip the old-entry store SELECT
        entirely — the negative-cache fast path on the create-heavy
        workload."""
        parent, _, name = path.rpartition("/")
        parent = parent or "/"
        probe = self._probe()
        with self._lock:
            rec = self._fresh_dirs.get(parent)
            if rec is not None and self._valid(rec[0], probe) \
                    and name not in rec[1]:
                hit = True
            else:
                hit = False
        from ..stats import PROCESS
        PROCESS.counter_add(
            "filer_meta_negative_dir_total", 1.0,
            help_text="negative-directory-cache consults on the "
                      "create path (hit = old-entry SELECT skipped)",
            result="hit" if hit else "miss")
        return hit

    def _note_child_locked(self, path: str) -> None:
        """Any touch of `path` (create/update/delete, own or sibling)
        poisons its name in the parent's fresh-dir set, and drops the
        path's own fresh-dir record (a foreign event on a tracked dir
        means we no longer know it)."""
        self._fresh_dirs.pop(path, None)
        parent, _, name = path.rpartition("/")
        rec = self._fresh_dirs.get(parent or "/")
        if rec is None:
            return
        if len(rec[1]) >= self.MAX_FRESH_CHILDREN:
            self._fresh_dirs.pop(parent or "/", None)
        else:
            rec[1].add(name)

    # -- listings ------------------------------------------------------

    def lookup_list(self, key: tuple):
        probe = self._probe()
        with self._lock:
            hit = self._lists.get(key)
            if hit is None or not self._valid(hit[0], probe):
                self._meter.count("misses")
                return _MISS
            self._lists.move_to_end(key)
        self._meter.count("hits")
        return hit[1]

    def fill_list(self, key: tuple, entries: list, token) -> None:
        epoch, wm = token
        with self._lock:
            if self._epoch != epoch:
                return
            self._lists[key] = (wm, entries)
            self._lists.move_to_end(key)
            self._dir_keys.setdefault(key[0], set()).add(key)
            while len(self._lists) > self.MAX_LISTS:
                old_key, _v = self._lists.popitem(last=False)
                keys = self._dir_keys.get(old_key[0])
                if keys is not None:
                    keys.discard(old_key)
                    if not keys:
                        self._dir_keys.pop(old_key[0], None)

    # -- invalidation --------------------------------------------------

    def invalidate(self, path: str) -> None:
        """Drop one path's entry, its parent's listings, and its own
        listings (when it is a directory); bump the epoch so racing
        fills die."""
        parent = path.rsplit("/", 1)[0] or "/"
        with self._lock:
            self._epoch += 1
            self._entries.pop(path, None)
            self._note_child_locked(path)
            dropped = 0
            for d in (parent, path):
                for key in self._dir_keys.pop(d, ()):  # noqa: B909
                    self._lists.pop(key, None)
                    dropped += 1
        self._meter.count("invalidations")

    def on_event(self, ev: dict) -> None:
        """The Filer._notify listener: runs synchronously after the
        event is durable, so by the time a writer's create/delete call
        returns, no reader can hit the pre-write cache."""
        for side in ("newEntry", "oldEntry"):
            e = ev.get(side)
            if e:
                self.invalidate(e.get("fullPath", ""))
        new = ev.get("newEntry")
        if new and new.get("isDirectory") and \
                ev.get("op") == "create" and not ev.get("oldEntry"):
            # a FRESH directory create (no prior entry) is the one
            # event that proves a dir empty — start negative tracking
            self.mark_fresh_dir(new.get("fullPath", ""))
        ts = int(ev.get("tsNs", 0))
        with self._lock:
            if ts > self._processed:
                self._processed = ts

    def clear(self) -> None:
        with self._lock:
            self._epoch += 1
            self._entries.clear()
            self._lists.clear()
            self._dir_keys.clear()
            self._fresh_dirs.clear()

    # -- introspection (tests / debug) ---------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "lists": len(self._lists),
                    "freshDirs": len(self._fresh_dirs),
                    "epoch": self._epoch,
                    "processed": self._processed}
