"""Filer: POSIX-ish namespace over the volume store (weed/filer).

Entries (directories + files) live in a pluggable FilerStore; file
content is a list of chunks, each a needle in some volume
(filer/filechunks.go ChunkView model).  The S3 / WebDAV gateways sit on
top of this layer.
"""

from .entry import Attributes, Entry, FileChunk  # noqa: F401
from .filer import Filer  # noqa: F401
from .filer_store import FilerStore, MemoryStore, SqliteStore  # noqa: F401
