"""Abstract-SQL FilerStore family (weed/filer/abstract_sql/
abstract_sql_store.go): ONE store implementation over a DB-API
connection + a dialect, the layer that powers the reference's
mysql/mysql2/postgres/postgres2/sqlite stores.

The schema is the reference's filemeta shape — (directory, name)
primary key with an opaque meta blob — and every query funnels through
the dialect so placeholder style, upsert syntax and LIKE escaping can
vary per engine without touching store logic.

Concrete dialects:
- SqliteDialect — used by filer_store.SqliteStore (the default filer
  store, always available).
- MysqlDialect / PostgresDialect — the reference's `%s`-placeholder
  engines.  The image ships no client drivers, so `connect()` raises
  with guidance; the dialect SQL itself is exercised by
  tests/test_filer_stores.py rendering queries against both dialects.
"""

from __future__ import annotations

import json
import threading

from .entry import Entry, normalize_path
from .filer_store import FilerStore


class SqlDialect:
    """Placeholder + syntax hooks (abstract_sql GenSqlInsert etc.)."""

    name = "generic"
    placeholder = "?"

    def create_table_sql(self) -> list[str]:
        return [
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory TEXT NOT NULL,"
            " name TEXT NOT NULL,"
            " meta TEXT NOT NULL,"
            " PRIMARY KEY (directory, name))",
        ]

    def upsert_sql(self) -> str:
        p = self.placeholder
        return (f"INSERT OR REPLACE INTO filemeta (directory, name, "
                f"meta) VALUES ({p}, {p}, {p})")

    def find_sql(self) -> str:
        p = self.placeholder
        return ("SELECT meta FROM filemeta WHERE directory=" + p +
                " AND name=" + p)

    def delete_sql(self) -> str:
        p = self.placeholder
        return ("DELETE FROM filemeta WHERE directory=" + p +
                " AND name=" + p)

    def delete_tree_sql(self) -> str:
        p = self.placeholder
        return ("DELETE FROM filemeta WHERE directory=" + p +
                r" OR directory LIKE " + p + r" ESCAPE '\'")

    def list_sql(self, include_start: bool, prefix: bool) -> str:
        p = self.placeholder
        op = ">=" if include_start else ">"
        q = ("SELECT meta FROM filemeta WHERE directory=" + p +
             f" AND name {op} " + p + " ")
        if prefix:
            q += r"AND name LIKE " + p + r" ESCAPE '\' "
        q += "ORDER BY name LIMIT " + p
        return q

    @staticmethod
    def like_escape(s: str) -> str:
        r"""Escape LIKE wildcards; every LIKE uses ESCAPE '\'."""
        return s.replace("\\", "\\\\").replace("%", r"\%") \
                .replace("_", r"\_")

    def connect(self, **kw):
        raise NotImplementedError


class SqliteDialect(SqlDialect):
    name = "sqlite"

    def connect(self, path: str = ":memory:", **kw):
        import sqlite3
        return sqlite3.connect(path, check_same_thread=False)


class MysqlDialect(SqlDialect):
    name = "mysql"
    placeholder = "%s"

    def create_table_sql(self) -> list[str]:
        return [
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory VARCHAR(512) NOT NULL,"
            " name VARCHAR(512) NOT NULL,"
            " meta LONGTEXT NOT NULL,"
            " PRIMARY KEY (directory, name))",
        ]

    def upsert_sql(self) -> str:
        return ("INSERT INTO filemeta (directory, name, meta) "
                "VALUES (%s, %s, %s) "
                "ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def connect(self, **kw):
        raise NotImplementedError(
            "no mysql client driver in this environment; point an "
            "AbstractSqlStore at a DB-API connection from "
            "mysql-connector/PyMySQL where available")


class PostgresDialect(SqlDialect):
    name = "postgres"
    placeholder = "%s"

    def upsert_sql(self) -> str:
        return ("INSERT INTO filemeta (directory, name, meta) "
                "VALUES (%s, %s, %s) "
                "ON CONFLICT (directory, name) "
                "DO UPDATE SET meta=EXCLUDED.meta")

    def connect(self, **kw):
        raise NotImplementedError(
            "no postgres client driver in this environment; point an "
            "AbstractSqlStore at a DB-API connection from psycopg "
            "where available")


class AbstractSqlStore(FilerStore):
    """The single store body shared by every SQL engine."""

    def __init__(self, conn, dialect: "SqlDialect | None" = None):
        self._db = conn
        self.dialect = dialect or SqliteDialect()
        self._lock = threading.RLock()
        with self._lock:
            for stmt in self.dialect.create_table_sql():
                self._db.execute(stmt)
            self._db.commit()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._db.execute(
                self.dialect.upsert_sql(),
                (entry.parent, entry.name,
                 json.dumps(entry.to_json())))
            self._db.commit()

    update_entry = insert_entry

    def find_entry(self, path: str) -> "Entry | None":
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            row = self._db.execute(
                self.dialect.find_sql(),
                (parent or "/", name)).fetchone()
        return Entry.from_json(json.loads(row[0])) if row else None

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._db.execute(self.dialect.delete_sql(),
                             (parent or "/", name))
            self._db.commit()

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._db.execute(
                self.dialect.delete_tree_sql(),
                (path, self.dialect.like_escape(path) + "/%"))
            self._db.commit()

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        args: list = [dir_path, start_file]
        if prefix:
            args.append(self.dialect.like_escape(prefix) + "%")
        args.append(limit)
        with self._lock:
            rows = self._db.execute(
                self.dialect.list_sql(include_start, bool(prefix)),
                args).fetchall()
        return [Entry.from_json(json.loads(r[0])) for r in rows]

    def close(self) -> None:
        self._db.close()
