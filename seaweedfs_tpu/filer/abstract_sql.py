"""Abstract-SQL FilerStore family (weed/filer/abstract_sql/
abstract_sql_store.go): ONE store implementation over a DB-API
connection + a dialect, the layer that powers the reference's
mysql/mysql2/postgres/postgres2/sqlite stores.

The schema is the reference's filemeta shape — (directory, name)
primary key with an opaque meta blob — and every query funnels through
the dialect so placeholder style, upsert syntax and LIKE escaping can
vary per engine without touching store logic.

Concrete dialects:
- SqliteDialect — used by filer_store.SqliteStore (the default filer
  store, always available).
- MysqlDialect / PostgresDialect — the reference's `%s`-placeholder
  engines.  The image ships no client drivers, so `connect()` raises
  with guidance; the dialect SQL itself is exercised by
  tests/test_filer_stores.py rendering queries against both dialects.
"""

from __future__ import annotations

import json
import os
import threading

from ..util.group_commit import CommitBarrier
from .entry import Entry, normalize_path
from .filer_store import FilerStore


def sqlite_sync_mode() -> str:
    """SEAWEEDFS_TPU_SQLITE_SYNC: sqlite `PRAGMA synchronous` for
    file-backed stores — "normal" (default; with WAL journaling a
    commit is a write() into the WAL, fsync only at checkpoint: the
    same process-kill durability tier as the volume plane's
    flush-then-ack, losing only a power-loss window), "full" (fsync
    per barrier — the seed's behavior and the bench A/B's off arm),
    or "off"."""
    v = os.environ.get("SEAWEEDFS_TPU_SQLITE_SYNC", "normal").lower()
    return v if v in ("normal", "full", "off") else "normal"


class SqlDialect:
    """Placeholder + syntax hooks (abstract_sql GenSqlInsert etc.)."""

    name = "generic"
    placeholder = "?"

    def create_table_sql(self) -> list[str]:
        return [
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory TEXT NOT NULL,"
            " name TEXT NOT NULL,"
            " meta TEXT NOT NULL,"
            " PRIMARY KEY (directory, name))",
        ]

    def upsert_sql(self) -> str:
        p = self.placeholder
        return (f"INSERT OR REPLACE INTO filemeta (directory, name, "
                f"meta) VALUES ({p}, {p}, {p})")

    def find_sql(self) -> str:
        p = self.placeholder
        return ("SELECT meta FROM filemeta WHERE directory=" + p +
                " AND name=" + p)

    def delete_sql(self) -> str:
        p = self.placeholder
        return ("DELETE FROM filemeta WHERE directory=" + p +
                " AND name=" + p)

    def delete_tree_sql(self) -> str:
        p = self.placeholder
        return ("DELETE FROM filemeta WHERE directory=" + p +
                r" OR directory LIKE " + p + r" ESCAPE '\'")

    def list_sql(self, include_start: bool, prefix: bool) -> str:
        p = self.placeholder
        op = ">=" if include_start else ">"
        q = ("SELECT meta FROM filemeta WHERE directory=" + p +
             f" AND name {op} " + p + " ")
        if prefix:
            q += r"AND name LIKE " + p + r" ESCAPE '\' "
        q += "ORDER BY name LIMIT " + p
        return q

    @staticmethod
    def like_escape(s: str) -> str:
        r"""Escape LIKE wildcards; every LIKE uses ESCAPE '\'."""
        return s.replace("\\", "\\\\").replace("%", r"\%") \
                .replace("_", r"\_")

    def connect(self, **kw):
        raise NotImplementedError


class SqliteDialect(SqlDialect):
    name = "sqlite"

    def connect(self, path: str = ":memory:", **kw):
        import sqlite3
        conn = sqlite3.connect(path, check_same_thread=False)
        if path != ":memory:":
            # WAL journaling: a commit appends to the write-ahead log
            # instead of the rollback-journal double-write (the delete
            # journal costs TWO fsyncs per transaction — measured
            # 7.4ms/commit on this box vs 0.12ms under WAL, and PR 7's
            # decomposition localized exactly this as ~80% of filer
            # write wall).  WAL also lets dedicated READ connections
            # run without blocking on — or behind — the writer (see
            # AbstractSqlStore._read_conn).  synchronous level per
            # sqlite_sync_mode().
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute(
                f"PRAGMA synchronous={sqlite_sync_mode().upper()}")
            conn.execute("PRAGMA busy_timeout=5000")
        return conn


class MysqlDialect(SqlDialect):
    name = "mysql"
    placeholder = "%s"

    def create_table_sql(self) -> list[str]:
        return [
            "CREATE TABLE IF NOT EXISTS filemeta ("
            " directory VARCHAR(512) NOT NULL,"
            " name VARCHAR(512) NOT NULL,"
            " meta LONGTEXT NOT NULL,"
            " PRIMARY KEY (directory, name))",
        ]

    def upsert_sql(self) -> str:
        return ("INSERT INTO filemeta (directory, name, meta) "
                "VALUES (%s, %s, %s) "
                "ON DUPLICATE KEY UPDATE meta=VALUES(meta)")

    def connect(self, **kw):
        raise NotImplementedError(
            "no mysql client driver in this environment; point an "
            "AbstractSqlStore at a DB-API connection from "
            "mysql-connector/PyMySQL where available")


class PostgresDialect(SqlDialect):
    name = "postgres"
    placeholder = "%s"

    def upsert_sql(self) -> str:
        return ("INSERT INTO filemeta (directory, name, meta) "
                "VALUES (%s, %s, %s) "
                "ON CONFLICT (directory, name) "
                "DO UPDATE SET meta=EXCLUDED.meta")

    def connect(self, **kw):
        raise NotImplementedError(
            "no postgres client driver in this environment; point an "
            "AbstractSqlStore at a DB-API connection from psycopg "
            "where available")


class AbstractSqlStore(FilerStore):
    """The single store body shared by every SQL engine.

    Mutations are GROUP-COMMITTED: each writer executes its statement
    under the store lock (cheap — the rows land in the connection's
    open transaction), then meets the shared barrier, where one leader
    runs `commit()` once for the whole batch.  Ack semantics are
    unchanged (a mutation returns only after a commit that covers it);
    the per-writer transaction fsync/write is amortized across every
    concurrent writer — classic database group commit.  Reads on the
    same connection see the open transaction, so a writer's own
    find_entry is never stale."""

    def __init__(self, conn, dialect: "SqlDialect | None" = None,
                 read_factory=None):
        self._db = conn
        self.dialect = dialect or SqliteDialect()
        self._lock = threading.RLock()
        self._barrier = CommitBarrier(self._group_commit_flush,
                                      site="filer.store")
        # WAL read plane: when the engine supports concurrent readers
        # (sqlite WAL, any server engine), each reader thread gets its
        # OWN connection and never touches the write lock — the
        # profiler showed find_entry threads piling up behind
        # concurrent writers' execute/commit windows.  Readers see the
        # last COMMITTED state, which is exactly the ack contract
        # (a mutation is visible to others only once its barrier
        # commit has made it durable).  None = reads share the write
        # connection under the lock (the :memory: store).
        self._read_factory = read_factory
        self._read_local = threading.local()
        with self._lock:
            for stmt in self.dialect.create_table_sql():
                self._db.execute(stmt)
            self._db.commit()

    def _read_conn(self):
        if self._read_factory is None:
            return None
        conn = getattr(self._read_local, "conn", None)
        if conn is None:
            conn = self._read_local.conn = self._read_factory()
        return conn

    def _group_commit_flush(self) -> None:
        """Designated barrier helper: one commit covering every
        statement executed so far (CommitBarrier serializes leaders)."""
        with self._lock:
            self._db.commit()

    def insert_entry(self, entry: Entry) -> None:
        with self._lock:
            self._db.execute(
                self.dialect.upsert_sql(),
                (entry.parent, entry.name,
                 json.dumps(entry.to_json())))  # noqa: SWFS015 — the synchronous-commit (meta-plane-off) path serializes here by design
        self._barrier.commit()

    update_entry = insert_entry

    def apply_events(self, records: list) -> None:
        """Meta-plane applier: the whole batch in ONE transaction,
        ONE commit — the designated place a per-batch store commit
        lives (SWFS015's exempt helper).  Upserts reuse the exact
        entry bytes the WAL line carries (`raw`), so the hot path's
        single serialization is the only one end to end.  Consecutive
        upserts run through `executemany` (the statement compiles
        once and the rows loop in C); the ordered flush before each
        delete preserves per-path apply order."""
        if not records:
            return
        up = self.dialect.upsert_sql()
        dele = self.dialect.delete_sql()
        rows: list = []
        with self._lock:
            for op, npath, raw, new, opath in records:
                if npath:
                    parent, _, name = npath.rpartition("/")
                    rows.append((parent or "/", name,
                                 raw if raw is not None
                                 else json.dumps(new)))
                if opath and op in ("delete", "rename") and \
                        opath != npath:
                    if rows:
                        self._db.executemany(up, rows)
                        rows = []
                    parent, _, name = opath.rpartition("/")
                    self._db.execute(dele, (parent or "/", name))
            if rows:
                self._db.executemany(up, rows)
            self._db.commit()

    def find_entry(self, path: str) -> "Entry | None":
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        parent, name = path.rsplit("/", 1)
        rc = self._read_conn()
        if rc is not None:
            row = rc.execute(self.dialect.find_sql(),
                             (parent or "/", name)).fetchone()
        else:
            with self._lock:
                row = self._db.execute(
                    self.dialect.find_sql(),
                    (parent or "/", name)).fetchone()
        return Entry.from_json(json.loads(row[0])) if row else None

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        parent, name = path.rsplit("/", 1)
        with self._lock:
            self._db.execute(self.dialect.delete_sql(),
                             (parent or "/", name))
        self._barrier.commit()

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        with self._lock:
            self._db.execute(
                self.dialect.delete_tree_sql(),
                (path, self.dialect.like_escape(path) + "/%"))
        self._barrier.commit()

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        args: list = [dir_path, start_file]
        if prefix:
            args.append(self.dialect.like_escape(prefix) + "%")
        args.append(limit)
        rc = self._read_conn()
        if rc is not None:
            rows = rc.execute(
                self.dialect.list_sql(include_start, bool(prefix)),
                args).fetchall()
        else:
            with self._lock:
                rows = self._db.execute(
                    self.dialect.list_sql(include_start, bool(prefix)),
                    args).fetchall()
        return [Entry.from_json(json.loads(r[0])) for r in rows]

    def close(self) -> None:
        with self._lock:
            try:
                # sqlite rolls an open transaction back on close; any
                # rows here belong to mutations that already passed
                # (or are about to pass) the barrier — commit them
                self._db.commit()
            except Exception:  # noqa: SWFS004 — DB-API error base
                pass           # varies per engine; teardown must finish
            self._db.close()
