"""Redis FilerStore — a concrete wire-protocol store archetype
(weed/filer/redis2/universal_redis_store.go; interface
weed/filer/filerstore.go).

Two pieces, both from scratch:

- **RespClient** — a hand-rolled RESP2 client (redis serialization
  protocol): inline command arrays out, typed replies
  (+simple / -error / :integer / $bulk / *array) back, over one
  pooled socket with reconnect-on-failure.  No third-party driver
  (the image carries none), and nothing redis-specific beyond the
  protocol — it speaks to a real `redis-server` unchanged.
- **RedisFilerStore** — the reference redis2 data model: the entry
  body lives at key `<path>` (JSON here; the reference uses protobuf
  Entry encoding), and each directory keeps a SORTED SET at
  `<dir>\\x00` with one member per child name (score 0), so listing
  is ZRANGEBYLEX — ordered, resumable pagination without scanning.

Tested against an EXTERNAL RESP server process
(tests/resp_fake.py via subprocess — the same contract suite every
other store passes), mirroring how the reference's CI runs its redis
stores against a service container.
"""

from __future__ import annotations

import json
import socket
import threading

from .entry import Entry
from .filer_store import FilerStore, normalize_path

DIR_LIST_MARKER = "\x00"   # redis2 DIR_LIST_MARKER


class RespError(RuntimeError):
    """Server-reported -ERR reply."""


class RespClient:
    """Minimal RESP2 client over one reconnecting socket."""

    def __init__(self, host: str = "127.0.0.1", port: int = 6379,
                 timeout: float = 10.0):
        self.host, self.port, self.timeout = host, port, timeout
        self._lock = threading.Lock()
        self._sock: "socket.socket | None" = None
        self._buf = b""

    def _connect(self) -> None:
        """Caller holds the lock."""
        self._sock = socket.create_connection(
            (self.host, self.port), timeout=self.timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._buf = b""

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None

    # -- wire ----------------------------------------------------------

    @staticmethod
    def _encode(args: "tuple") -> bytes:
        out = [b"*%d\r\n" % len(args)]
        for a in args:
            if isinstance(a, str):
                a = a.encode()
            elif isinstance(a, (int, float)):
                a = str(a).encode()
            out.append(b"$%d\r\n%s\r\n" % (len(a), a))
        return b"".join(out)

    def _read_line(self) -> bytes:
        """Caller holds the lock."""
        while b"\r\n" not in self._buf:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise OSError("RESP connection closed")
            self._buf += chunk
        line, self._buf = self._buf.split(b"\r\n", 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        """Caller holds the lock."""
        while len(self._buf) < n + 2:
            chunk = self._sock.recv(65536)
            if not chunk:
                raise OSError("RESP connection closed")
            self._buf += chunk
        data, self._buf = self._buf[:n], self._buf[n + 2:]
        return data

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest.decode()
        if kind == b"-":
            raise RespError(rest.decode())
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            return None if n < 0 else self._read_exact(n)
        if kind == b"*":
            n = int(rest)
            return None if n < 0 else [self._read_reply()
                                       for _ in range(n)]
        raise RespError(f"unparseable reply {line[:40]!r}")

    def call(self, *args):
        """One command round-trip; reconnects once on a dead pooled
        socket (commands used by the store are idempotent writes —
        SET/ZADD/DEL replay safely)."""
        with self._lock:
            for attempt in (0, 1):
                if self._sock is None:
                    self._connect()
                try:
                    self._sock.sendall(self._encode(args))
                    return self._read_reply()
                except OSError:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                    if attempt:
                        raise


class RedisFilerStore(FilerStore):
    """redis2's data model over RespClient (see module docstring)."""

    def __init__(self, client: RespClient):
        self.r = client

    @staticmethod
    def _dir_key(dir_path: str) -> str:
        return dir_path + DIR_LIST_MARKER

    def insert_entry(self, entry: Entry) -> None:
        self.r.call("SET", entry.full_path,
                    json.dumps(entry.to_json()))
        if entry.name:
            self.r.call("ZADD", self._dir_key(entry.parent), 0,
                        entry.name)

    update_entry = insert_entry

    def find_entry(self, path: str) -> "Entry | None":
        path = normalize_path(path)
        if path == "/":
            return Entry("/", is_directory=True)
        raw = self.r.call("GET", path)
        if raw is None:
            return None
        return Entry.from_json(json.loads(raw))

    def delete_entry(self, path: str) -> None:
        path = normalize_path(path)
        self.r.call("DEL", path)
        parent, _, name = path.rpartition("/")
        if name:
            self.r.call("ZREM", self._dir_key(parent or "/"), name)

    def delete_folder_children(self, path: str) -> None:
        path = normalize_path(path)
        names = self.r.call("ZRANGEBYLEX", self._dir_key(path),
                            "-", "+") or []
        for raw in names:
            name = raw.decode() if isinstance(raw, bytes) else raw
            child = path.rstrip("/") + "/" + name
            # recurse into directories BEFORE dropping the child key
            raw_e = self.r.call("GET", child)
            if raw_e is not None:
                try:
                    if json.loads(raw_e).get("isDirectory"):
                        self.delete_folder_children(child)
                except ValueError:
                    pass
            self.r.call("DEL", child)
        self.r.call("DEL", self._dir_key(path))

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> list[Entry]:
        dir_path = normalize_path(dir_path)
        if start_file:
            lo = ("[" if include_start else "(") + start_file
        elif prefix:
            lo = "[" + prefix
        else:
            lo = "-"
        hi = "[" + prefix + "\xff" if prefix else "+"
        names = self.r.call("ZRANGEBYLEX", self._dir_key(dir_path),
                            lo, hi, "LIMIT", 0, limit) or []
        out: list[Entry] = []
        for raw in names:
            name = raw.decode() if isinstance(raw, bytes) else raw
            if prefix and not name.startswith(prefix):
                continue
            raw_e = self.r.call(
                "GET", dir_path.rstrip("/") + "/" + name)
            if raw_e is None:
                continue    # listing/entry raced a delete
            out.append(Entry.from_json(json.loads(raw_e)))
        return out

    def close(self) -> None:
        self.r.close()
