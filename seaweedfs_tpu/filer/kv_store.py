"""Remote ordered-KV FilerStore archetype (the etcd/tikv/redis shape
among the reference's 24 pluggable stores — weed/filer/etcd/,
redis2/, tikv/; interface weed/filer/filerstore.go).

Key scheme (the ordered-KV idiom the reference's etcd store uses):

    <parent-dir> \\x00 <name>   ->   entry JSON

so one range scan over the prefix `<dir>\\x00` yields a directory's
children in lexicographic name order — no SQL, no local file, just
get/put/delete/scan against a remote server.  `KVClient` is the
transport contract; `HttpKVClient`/`HttpKVServer` provide a real
remote (JSON-over-HTTP) implementation used by tests and as the
template for binding an actual etcd/redis.
"""

from __future__ import annotations

import json
import threading
import urllib.parse

from ..server.httpd import HttpServer, Request, http_json
from .entry import Entry, normalize_path
from .filer_store import FilerStore

SEP = "\x00"


def _key(path: str) -> str:
    path = normalize_path(path)
    parent, _, name = path.rpartition("/")
    return f"{parent or '/'}{SEP}{name}"


def _dir_prefix(dir_path: str) -> str:
    return f"{normalize_path(dir_path)}{SEP}"


class KVClient:
    """Transport contract: an ordered key-value store."""

    def get(self, key: str) -> "bytes | None":
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def scan(self, prefix: str, start_after: str = "",
             limit: int = 1000) -> "list[tuple[str, bytes]]":
        """Keys with `prefix`, strictly greater than `start_after`
        (full key), ascending, at most `limit`."""
        raise NotImplementedError


class KVFilerStore(FilerStore):
    """filerstore.go over any KVClient."""

    def __init__(self, kv: KVClient):
        self.kv = kv

    def insert_entry(self, entry: Entry) -> None:
        self.kv.put(_key(entry.full_path),
                    json.dumps(entry.to_json()).encode())

    update_entry = insert_entry

    def find_entry(self, path: str) -> "Entry | None":
        if normalize_path(path) == "/":
            # root always exists (matches MemoryStore/SqliteStore —
            # clients PROPFIND the share root before anything else)
            return Entry("/", is_directory=True)
        raw = self.kv.get(_key(path))
        return Entry.from_json(json.loads(raw)) if raw else None

    def delete_entry(self, path: str) -> None:
        self.kv.delete(_key(path))

    def delete_folder_children(self, path: str) -> None:
        """Whole-SUBTREE delete, like the other stores — removing only
        direct children would orphan grandchildren keys, and a later
        mkdir of the same subdir would resurrect them with dangling
        chunk references."""
        prefix = _dir_prefix(path)
        while True:
            batch = self.kv.scan(prefix, limit=1000)
            if not batch:
                return
            for k, raw in batch:
                try:
                    child = Entry.from_json(json.loads(raw))
                    if child.is_directory:
                        self.delete_folder_children(child.full_path)
                except ValueError:
                    pass
                self.kv.delete(k)

    def list_directory_entries(self, dir_path: str,
                               start_file: str = "",
                               include_start: bool = False,
                               limit: int = 1000,
                               prefix: str = "") -> "list[Entry]":
        kp = _dir_prefix(dir_path)
        out: list[Entry] = []
        # start_after is exclusive; include_start re-reads the exact key
        start_after = kp + start_file if start_file else ""
        if start_file and include_start:
            raw = self.kv.get(kp + start_file)
            if raw:
                e = Entry.from_json(json.loads(raw))
                if e.name.startswith(prefix):
                    out.append(e)
        while len(out) < limit:
            batch = self.kv.scan(kp, start_after,
                                 min(1000, limit - len(out) + 64))
            if not batch:
                break
            for k, raw in batch:
                name = k[len(kp):]
                start_after = k
                if prefix and not name.startswith(prefix):
                    continue
                out.append(Entry.from_json(json.loads(raw)))
                if len(out) >= limit:
                    break
        return out

    def close(self) -> None:
        pass


# --- a real remote KV (JSON-over-HTTP) for tests & as a template ---------

class HttpKVServer:
    """Minimal ordered-KV server: the stand-in for etcd/redis in tests
    (the reference's stores are exercised against real containers in
    CI; this keeps the same client/server split in-process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._data: dict[str, bytes] = {}
        self._lock = threading.Lock()
        self.http = HttpServer(host, port)
        self.http.route("POST", "/kv/get", self._get)
        self.http.route("POST", "/kv/put", self._put)
        self.http.route("POST", "/kv/delete", self._delete)
        self.http.route("POST", "/kv/scan", self._scan)

    def start(self) -> "HttpKVServer":
        self.http.start()
        return self

    def stop(self) -> None:
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    def _get(self, req: Request):
        with self._lock:
            v = self._data.get(req.json()["key"])
        if v is None:
            return 200, {"found": False}
        return 200, {"found": True, "value": v.decode("latin-1")}

    def _put(self, req: Request):
        b = req.json()
        with self._lock:
            self._data[b["key"]] = b["value"].encode("latin-1")
        return 200, {}

    def _delete(self, req: Request):
        with self._lock:
            self._data.pop(req.json()["key"], None)
        return 200, {}

    def _scan(self, req: Request):
        b = req.json()
        prefix = b["prefix"]
        start_after = b.get("startAfter", "")
        limit = int(b.get("limit", 1000))
        with self._lock:
            keys = sorted(k for k in self._data
                          if k.startswith(prefix) and k > start_after)
            items = [{"key": k,
                      "value": self._data[k].decode("latin-1")}
                     for k in keys[:limit]]
        return 200, {"items": items}


class HttpKVClient(KVClient):
    def __init__(self, server: str):
        self.server = server

    def get(self, key: str) -> "bytes | None":
        r = http_json("POST", f"{self.server}/kv/get", {"key": key})
        return r["value"].encode("latin-1") if r.get("found") else None

    def put(self, key: str, value: bytes) -> None:
        http_json("POST", f"{self.server}/kv/put",
                  {"key": key, "value": value.decode("latin-1")})

    def delete(self, key: str) -> None:
        http_json("POST", f"{self.server}/kv/delete", {"key": key})

    def scan(self, prefix: str, start_after: str = "",
             limit: int = 1000) -> "list[tuple[str, bytes]]":
        r = http_json("POST", f"{self.server}/kv/scan",
                      {"prefix": prefix, "startAfter": start_after,
                       "limit": limit})
        return [(i["key"], i["value"].encode("latin-1"))
                for i in r.get("items", [])]
