"""`filer.backup` S3 sink — continuously mirror a filer's namespace
into an S3-compatible bucket (weed/replication/sink/s3sink/s3_sink.go;
the reference also ships gcs/azure sinks on the same interface, which
reduce to the same PUT/DELETE verbs against a different endpoint).

Same engine as filer.sync/backup (poll the persistent metadata stream,
apply each event, checkpoint the offset after it fully applies), with
an S3 applier: create/update PUTs the object at the filer path,
delete DELETEs it, rename re-PUTs under the new key and deletes the
old (S3 has no rename).  Restart-resumable via the shared offset
checkpoint."""

from __future__ import annotations

from ..server.httpd import http_bytes
from ..storage.backend import S3BackendStorage
from .filer_sync import FilerSync, _quote


class S3Sink(FilerSync):
    def __init__(self, source: str, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 key_prefix: str = "", state_path: str | None = None,
                 poll_interval: float = 0.2):
        super().__init__(source, f"s3:{endpoint}/{bucket}/{key_prefix}",
                         state_path, poll_interval)
        self.s3 = S3BackendStorage("s3sink", endpoint, bucket,
                                   access_key, secret_key)
        self.key_prefix = key_prefix.strip("/")
        self.s3.ensure_bucket()

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.key_prefix}/{key}" if self.key_prefix else key

    # -- applier (s3sink) ----------------------------------------------

    def _apply(self, ev: dict) -> None:
        op = ev.get("op")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        if op in ("create", "update") and new:
            self._put_entry(new)
        elif op == "delete" and old:
            if not old.get("isDirectory"):
                self.s3.delete(self._key(old["fullPath"]))
        elif op == "rename" and new and old:
            if not old.get("isDirectory"):
                self.s3.delete(self._key(old["fullPath"]))
            self._put_entry(new)

    def _put_entry(self, entry: dict) -> None:
        if entry.get("isDirectory"):
            return  # S3 has no directories; objects carry full keys
        st, body, _ = http_bytes(
            "GET", self.source + _quote(entry["fullPath"]))
        if st == 404:
            return  # deleted since; the delete event follows
        if st >= 300:
            raise RuntimeError(
                f"s3 sink: read {entry['fullPath']}: {st}")
        self.s3.put_bytes(self._key(entry["fullPath"]), body)
