"""`filer.backup` S3 sink — continuously mirror a filer's namespace
into an S3-compatible bucket (weed/replication/sink/s3sink/s3_sink.go).

Rides the shared cloud-sink applier (filer/cloud_sinks.py _CloudSink:
create/update uploads the object at the filer path, delete removes
it, rename re-uploads under the new key and deletes the old — S3 has
no rename) with S3 PUT/DELETE verbs via the tiering backend's client.
Restart-resumable via the shared offset checkpoint."""

from __future__ import annotations

from ..storage.backend import S3BackendStorage
from .cloud_sinks import _CloudSink


class S3Sink(_CloudSink):
    def __init__(self, source: str, endpoint: str, bucket: str,
                 access_key: str = "", secret_key: str = "",
                 key_prefix: str = "", state_path: str | None = None,
                 poll_interval: float = 0.2):
        super().__init__(source,
                         f"s3:{endpoint}/{bucket}/{key_prefix}",
                         key_prefix, state_path, poll_interval)
        self.s3 = S3BackendStorage("s3sink", endpoint, bucket,
                                   access_key, secret_key)
        self.s3.ensure_bucket()

    def _upload(self, key: str, data: bytes) -> None:
        self.s3.put_bytes(key, data)

    def _delete(self, key: str) -> None:
        self.s3.delete(key)
