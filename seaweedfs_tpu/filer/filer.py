"""The Filer: namespace operations over a FilerStore + chunked content
on the volume cluster (weed/filer/filer.go).

Mutations emit metadata events to a persistent, timestamp-replayable
log (filer/filer_notify.go, meta_log.MetaLog) — the backbone for
filer.sync / mount cache invalidation / S3 events.  Subscribers resume
from their last-seen tsNs and never silently skip events.
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Callable

from .. import operation
from .entry import Attributes, Entry, FileChunk, normalize_path
from .filechunks import total_size, view_from_chunks
from .filer_store import FilerStore, MemoryStore
from .meta_log import MetaLog

CHUNK_SIZE = 4 * 1024 * 1024  # filer auto-chunk default (8MB in ref CLI)


class _ViewStream:
    """Lazy file-like over a chunk-view list (Filer.open_read_stream):
    each `read` drains an internal buffer refilled one view at a time
    — gaps between views and short volume reads are zero-filled so the
    stream always yields exactly `size` bytes (the buffered read_file
    contract, without the whole-body bytearray)."""

    def __init__(self, filer: "Filer", views, offset: int, size: int,
                 on_close=None):
        self._filer = filer
        self._views = list(views)
        self._vi = 0
        self._pos = offset            # logical file position
        self._end = offset + size
        self._buf = memoryview(b"")
        self._on_close = on_close

    def _refill(self) -> bool:
        """Load the next segment (zero gap or one view's bytes) into
        the buffer.  False at end of range."""
        if self._pos >= self._end:
            return False
        if self._vi < len(self._views):
            v = self._views[self._vi]
            if self._pos < v.logical_offset:
                # gap before the next view: bounded zero block
                n = min(v.logical_offset - self._pos,
                        self._end - self._pos, 1 << 20)
                self._buf = memoryview(bytes(n))
                self._pos += n
                return True
            piece = self._filer._read_view(v)
            if len(piece) < v.size:
                # short volume read: pad to the view's extent so later
                # views stay aligned (read_file leaves zeros the same
                # way)
                piece = piece + bytes(v.size - len(piece))
            self._buf = memoryview(piece)
            self._pos += len(piece)
            self._vi += 1
            return True
        # trailing gap (sparse tail): zeros to the end of the range
        n = min(self._end - self._pos, 1 << 20)
        self._buf = memoryview(bytes(n))
        self._pos += n
        return True

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            # read-all: still bounded consumers only (tests); the
            # server path always passes a window size
            parts = []
            while self._buf or self._refill():
                parts.append(bytes(self._buf))
                self._buf = memoryview(b"")
            return b"".join(parts)
        if not self._buf and not self._refill():
            return b""
        if n >= len(self._buf):
            out, self._buf = bytes(self._buf), memoryview(b"")
            return out
        out = bytes(self._buf[:n])
        self._buf = self._buf[n:]
        return out

    def close(self) -> None:
        self._views = []
        self._buf = memoryview(b"")
        cb, self._on_close = self._on_close, None
        if cb is not None:
            cb()


class Filer:
    def __init__(self, master: str, store: FilerStore | None = None,
                 collection: str = "", replication: str = "",
                 meta_log_dir: str | None = None,
                 meta_cache: "bool | None" = None,
                 chunk_cache_dir: "str | None" = None,
                 meta_plane: "bool | None" = None):
        self.master = master
        self.store = store or MemoryStore()
        self.collection = collection
        self.replication = replication
        self._log_lock = threading.Lock()
        # persisted when meta_log_dir is set (filer_notify_append.go);
        # memory-tail-only otherwise (tests / ephemeral filers)
        self.meta_log = MetaLog(meta_log_dir)
        self._listeners: list[Callable[[dict], None]] = []
        # meta plane (filer/meta_plane.py, ISSUE 13): the metalog is
        # this filer's WAL — a mutation acks at the metalog barrier,
        # the store is checkpointed asynchronously, reads merge the
        # unapplied-tail overlay over the store.  Auto-on for durable
        # local stores with a metalog dir; SEAWEEDFS_TPU_FILER_META_
        # PLANE=0 restores the synchronous commit (and its boot path
        # still replays any unapplied tail a planed run left behind).
        from .meta_plane import (MetaPlane, meta_plane_enabled,
                                 recover_sync)
        supported = bool(self.meta_log.dir) and \
            getattr(self.store, "supports_meta_plane", False)
        env = meta_plane_enabled()
        if env is False:
            meta_plane = False
        elif meta_plane is None:
            meta_plane = env is True or env is None
        self.meta_plane = MetaPlane(self.store, self.meta_log) \
            if (meta_plane and supported) else None
        if self.meta_plane is None and supported:
            recover_sync(self.meta_log, self.store)
        # metadata cache (meta_cache.py): find/list served from memory,
        # invalidated by this filer's own event stream synchronously
        # and by sibling filers' metalog watermark.  FilerServer passes
        # meta_cache=False for stores whose co-located siblings keep
        # separate metalog dirs (redis/elastic).  Left UNSPECIFIED
        # (None), the cache enables only when a coherence channel
        # exists: a metalog dir (the watermark files live there) or a
        # MemoryStore (unsharable by construction, own events
        # suffice).  A dir-less Filer over a sqlite FILE — the
        # embedded S3-gateway shape — could be sharing that file with
        # another process it has no way to hear from, so it stays
        # uncached unless the caller opts in explicitly.
        from .meta_cache import FilerMetaCache, meta_cache_entries
        cap = meta_cache_entries()
        if meta_cache is None:
            meta_cache = bool(meta_log_dir) or \
                isinstance(self.store, MemoryStore)
        # plane mode drops the foreign-watermark serve rule: sibling
        # commits arrive as point invalidations through the plane's
        # log follower instead (worker-scalable coherence)
        self.meta_cache = FilerMetaCache(
            self.meta_log, cap, watermark=self.meta_plane is None) \
            if (meta_cache and cap > 0) else None
        if self.meta_cache is not None:
            self._listeners.append(self.meta_cache.on_event)
        if self.meta_plane is not None:
            self.meta_plane.cache = self.meta_cache
        # hot chunk-body cache on the proxy read path (the server-side
        # sibling of the mount's TieredChunkCache): chunk blobs are
        # immutable per fid — an overwrite mints new fids — so this
        # tier needs NO invalidation, only byte-bounded LRU
        from ..util.chunk_cache import (TieredChunkCache, read_cache_mb,
                                        read_cache_disk)
        mb = read_cache_mb(64)
        self.chunk_cache = TieredChunkCache(
            mem_limit=mb << 20, disk_dir=chunk_cache_dir,
            disk_limit=read_cache_disk()[1] << 20,
            name="filer_chunk") if mb > 0 else None
        # striped per-path locks for chunk-list read-modify-write
        # cycles (append_chunks/truncate_file): two concurrent
        # /__chunk__/ posts must not lose each other's chunks
        self._chunk_stripes = [threading.Lock() for _ in range(64)]
        # known-directory cache (the reference filer caches directory
        # existence the same way): _ensure_parents was issuing one
        # store SELECT per ancestor per write — for a flat bench tree
        # that is 2 extra round-trips on every single write.  Bounded;
        # cleared wholesale on any directory delete/rename (rare), so
        # staleness can only re-create a directory entry, never lose
        # one.
        self._known_dirs: set[str] = set()
        self._known_dirs_cap = 4096

    def _note_dir(self, path: str) -> None:
        if len(self._known_dirs) >= self._known_dirs_cap:
            self._known_dirs.clear()
        self._known_dirs.add(path)

    def _chunk_lock(self, path: str) -> "threading.Lock":
        return self._chunk_stripes[hash(path) % 64]

    # -- namespace ops ----------------------------------------------------

    _UNKNOWN = object()   # create_entry: "caller didn't pre-fetch"

    def _store_find(self, path: str) -> Entry | None:
        """Overlay-over-store point lookup WITHOUT the meta cache —
        the internal read every mutation path uses.  Overlay hits are
        cloned: internal callers mutate entries in place (rename)."""
        mp = self.meta_plane
        if mp is not None:
            from .meta_plane import _OMISS
            hit = mp.lookup(path)
            if hit is not _OMISS:
                return hit.clone() if hit is not None else None
        return self.store.find_entry(path)

    _OV_UNKNOWN = object()

    def _store_list(self, dir_path: str, start_file: str = "",
                    include_start: bool = False, limit: int = 1000,
                    prefix: str = "", overlay=_OV_UNKNOWN) -> list[Entry]:
        """Overlay-merged directory listing WITHOUT the meta cache:
        unapplied creates appear, tombstones hide the store's stale
        rows.  The store is asked for `limit + |overlay(dir)|` rows so
        tombstoned rows cannot shrink a full page.  `overlay` lets a
        caller that already snapshotted the dir's overlay pass it in
        instead of rebuilding it under the overlay lock."""
        mp = self.meta_plane
        ov = overlay if overlay is not self._OV_UNKNOWN else (
            mp.overlay_dir(dir_path) if mp is not None else None)
        if not ov:
            return self.store.list_directory_entries(
                dir_path, start_file, include_start, limit, prefix)
        rows = self.store.list_directory_entries(
            dir_path, start_file, include_start, limit + len(ov),
            prefix)
        merged = {e.name: e for e in rows}
        for name, ent in ov.items():
            if prefix and not name.startswith(prefix):
                continue
            if start_file and (name < start_file or (
                    name == start_file and not include_start)):
                continue
            if ent is None:
                merged.pop(name, None)
            else:
                merged[name] = ent.clone()
        return [merged[n] for n in sorted(merged)][:limit]

    def create_entry(self, entry: Entry, create_parents: bool = True,
                     old_entry=_UNKNOWN) -> None:
        """`old_entry` lets a caller that already looked the path up
        (write_file's overwrite check) pass its result through instead
        of paying a second store read for the update-vs-create event
        verdict."""
        entry.full_path = normalize_path(entry.full_path)
        if create_parents:
            self._ensure_parents(entry.full_path)
        old = self._store_find(entry.full_path) \
            if old_entry is self._UNKNOWN else old_entry
        if self.meta_plane is None:
            # synchronous commit path (kill switch / unsupported
            # store); with the plane on, durability is the metalog
            # barrier inside _notify and the store is applied async
            self.store.insert_entry(entry)
        if entry.is_directory:
            self._note_dir(entry.full_path)
        self._notify("update" if old else "create", entry, old)

    def _ensure_parents(self, path: str) -> None:
        parent = path.rsplit("/", 1)[0]
        if not parent or parent == "/":
            return
        if parent in self._known_dirs:
            return
        if self._store_find(parent) is None:
            e = Entry(parent, is_directory=True,
                      attributes=Attributes(mode=0o770))
            self._ensure_parents(parent)
            if self.meta_plane is None:
                self.store.insert_entry(e)
            self._notify("create", e, None)
        self._note_dir(parent)

    @staticmethod
    def _count_negative(result: str) -> None:
        """filer_read_negative_total{result}: the read-edge negative
        outcome split — "hit" = absence proven WITHOUT a store SELECT
        (overlay / cached-None / negative-directory), "miss" = the
        SELECT was paid and came back empty.  Only emitted from
        count_negative=True call sites (the filer/S3 GET edge), so
        internal probes (write_file's old-entry check, mkdir scans)
        don't pollute the read-shape signal."""
        from ..stats import PROCESS
        PROCESS.counter_add(
            "filer_read_negative_total", 1.0,
            help_text="read-edge lookups that found no entry, by "
                      "whether absence was proven without a store "
                      "SELECT",
            result=result)

    def find_entry(self, path: str,
                   count_negative: bool = False) -> Entry | None:
        path = normalize_path(path)
        mp = self.meta_plane
        if mp is not None:
            # coherence point: ingest any sibling event durably
            # appended before this read began (one stat), then let
            # the overlay override cache and store
            mp.catch_up()
            from .meta_plane import _OMISS
            hit = mp.lookup(path)
            if hit is not _OMISS:
                if hit is None and count_negative:
                    self._count_negative("hit")
                return hit.clone() if hit is not None else None
        mc = self.meta_cache
        if mc is None:
            entry = self.store.find_entry(path)
            if entry is None and count_negative:
                self._count_negative("miss")
            return entry
        if mc.known_absent(path):
            # negative-directory fast path (ROADMAP 1b): the parent is
            # a tracked fresh directory and this name was never
            # touched — provably no entry, skip the store SELECT that
            # every create otherwise pays to prove old_entry is None.
            # (Runs AFTER the plane overlay above: anything a sibling
            # durably committed before this read began was either
            # served from the overlay or has point-invalidated the
            # name into the parent's poison set via the follower.)
            if count_negative:
                self._count_negative("hit")
            return None
        from .meta_cache import _MISS
        hit = mc.lookup_entry(path)
        if hit is not _MISS:
            if hit is None and count_negative:
                # cached-None: a prior miss's fill short-circuits the
                # SELECT until an event invalidates the name
                self._count_negative("hit")
            # clone: callers mutate the returned entry in place
            # (update_attrs, append_chunks) — the cached copy must
            # stay pristine until an event invalidates it
            return hit.clone() if hit is not None else None
        token = mc.begin_fill()
        entry = self.store.find_entry(path)
        mc.fill_entry(path,
                      entry.clone() if entry is not None else None,
                      token)
        if entry is None and count_negative:
            self._count_negative("miss")
        return entry

    def delete_entry(self, path: str, recursive: bool = False,
                     delete_chunks: bool = True) -> None:
        path = normalize_path(path)
        entry = self._store_find(path)
        if entry is None:
            return
        if entry.is_directory:
            children = self._store_list(path, limit=2)
            if children and not recursive:
                raise IsADirectoryError(f"{path} not empty")
            self._delete_tree(path, delete_chunks)
        elif delete_chunks:
            self._delete_chunks(entry)
        if self.meta_plane is None:
            self.store.delete_entry(path)
        if entry.is_directory:
            # wholesale, and AFTER the store delete: clearing before
            # it would let a concurrent _note_dir re-cache the doomed
            # path and suppress its re-creation forever.  (A racing
            # write can still land an entry under a just-deleted
            # parent — the same check-then-insert window the store
            # always had; the cache only matches that window, never
            # widens it past this clear.)
            self._known_dirs.clear()
        self._notify("delete", None, entry)

    def _delete_tree(self, path: str, delete_chunks: bool) -> None:
        while True:
            children = self._store_list(path, limit=1000)
            if not children:
                break
            for child in children:
                if child.is_directory:
                    self._delete_tree(child.full_path, delete_chunks)
                elif delete_chunks:
                    self._delete_chunks(child)
                if self.meta_plane is None:
                    self.store.delete_entry(child.full_path)
                self._notify("delete", None, child)

    def _delete_chunks(self, entry: Entry) -> None:
        for c in entry.chunks:
            try:
                operation.delete(self.master, c.file_id)
            except (OSError, LookupError, RuntimeError):
                pass  # orphan cleanup is a maintenance job

    def list_directory(self, path: str, start_file: str = "",
                       include_start: bool = False, limit: int = 1000,
                       prefix: str = "") -> list[Entry]:
        path = normalize_path(path)
        mp = self.meta_plane
        if mp is not None:
            mp.catch_up()
            ov = mp.overlay_dir(path)
            if ov:
                # unapplied tail touches this directory: serve the
                # overlay-merged listing and skip the cache (while the
                # overlay masks the dir the cache cannot acquire a
                # stale fill, and the event-time epoch bump killed
                # any fill that raced the events)
                return self._store_list(path, start_file,
                                        include_start, limit, prefix,
                                        overlay=ov)
        mc = self.meta_cache
        if mc is None:
            return self.store.list_directory_entries(
                path, start_file, include_start, limit, prefix)
        from .meta_cache import _MISS
        key = (path, start_file, include_start, limit, prefix)
        hit = mc.lookup_list(key)
        if hit is not _MISS:
            return [e.clone() for e in hit]
        token = mc.begin_fill()
        entries = self.store.list_directory_entries(
            path, start_file, include_start, limit, prefix)
        mc.fill_list(key, [e.clone() for e in entries], token)
        return entries

    def update_attrs(self, path: str, **kw) -> None:
        """Attribute-only UpdateEntry (filer.proto UpdateEntry with
        unchanged chunks): mode/uid/gid/mtime patches from gateways
        (SFTP setstat, mount chmod) that content writes can't carry."""
        entry = self.find_entry(path)
        if entry is None:
            raise FileNotFoundError(path)
        for k, v in kw.items():
            setattr(entry.attributes, k, v)
        self.create_entry(entry, create_parents=False)

    def rename(self, old_path: str, new_path: str) -> None:
        """Atomic within the store (filer.proto AtomicRenameEntry);
        directories move their whole subtree."""
        old_path = normalize_path(old_path)
        new_path = normalize_path(new_path)
        entry = self._store_find(old_path)
        if entry is None:
            raise FileNotFoundError(old_path)
        self._ensure_parents(new_path)
        if entry.is_directory:
            for child in self._store_list(old_path, limit=1_000_000):
                self.rename(child.full_path,
                            new_path + "/" + child.name)
        old_entry = copy.copy(entry)  # event must carry the OLD path
        entry.full_path = new_path
        if self.meta_plane is None:
            self.store.insert_entry(entry)
            self.store.delete_entry(old_path)
        if entry.is_directory:
            self._known_dirs.clear()   # the old path left the tree
        self._notify("rename", entry, old_entry)

    # -- content IO -------------------------------------------------------

    def write_file(self, path: str, data: bytes, mime: str = "",
                   mode: int = 0o660) -> Entry:
        """Auto-chunking upload
        (server/filer_server_handlers_write_autochunk.go:25).
        Chunks upload through a bounded parallel pool
        (util/limiter, limited_executor.go role): a multi-chunk
        write overlaps its volume-server round trips instead of
        serializing them, with backpressure at the bound."""
        from .. import profiling
        from ..util.limiter import bounded_parallel

        # capture the handler thread's stage track AND deadline BEFORE
        # fanning out: contextvars do not follow the limiter pool's
        # threads, so each piece re-binds both (operation.assign/
        # upload then report their stages into this request's
        # decomposition, and their outbound hops keep deriving
        # timeouts from THIS request's shrinking budget)
        trk = profiling.current_track()
        from ..util import deadline as _dl
        dl = _dl.get()

        def upload_piece(off: int) -> FileChunk:
            piece = data[off:off + CHUNK_SIZE]
            # fresh-assign retry on volume-state races (a background
            # ec.encode marking the assigned volume readonly mid-write
            # must cost a retry, not surface a 500 to the tenant)
            with _dl.use(dl), profiling.use_track(trk):
                a, r = operation.assign_and_upload(
                    self.master, piece, collection=self.collection,
                    replication=self.replication)
            return FileChunk(a.fid, off, len(piece),
                             r.get("eTag", ""), time.time_ns())

        # persistent=True: the fan-out runs on the process-wide worker
        # pool, so each worker's thread-local keep-alive sockets (the
        # pooled client funnel) survive across requests — a fresh
        # executor per write was re-dialing every volume server on
        # every multi-chunk upload.  Single-chunk writes stay inline
        # on the handler thread: zero per-request thread hand-offs.
        chunks = bounded_parallel(
            upload_piece, range(0, len(data), CHUNK_SIZE), limit=4,
            persistent=True)
        if len(chunks) > 1:
            # flight-recorder note: a slow write that fanned out N
            # chunks reads differently from a slow single-chunk one
            profiling.flight_note("chunks", len(chunks))
        entry = Entry(normalize_path(path), is_directory=False,
                      attributes=Attributes(mime=mime, mode=mode),
                      chunks=chunks)
        with profiling.stage("meta"):
            old = self.find_entry(path)
            self.create_entry(entry, old_entry=old)
        if old is not None and not old.is_directory:
            # separate stage: these are volume-server DELETE round
            # trips, not metadata-store work — folding them into
            # "meta" would misattribute overwrite workloads
            with profiling.stage("gc"):
                self._delete_chunks(old)
        return entry

    def append_chunks(self, path: str, offset: int, data: bytes,
                      truncate_to: int | None = None) -> Entry:
        """Interval write: upload `data` as chunks at logical
        `offset` and merge them into the entry's chunk list, relying
        on later-wins overlap resolution (filechunks.py) — the
        server half of the reference's chunked dirty-page writeback
        (mount/dirty_pages_chunked.go + UpdateEntry).  Creates the
        entry when absent.  `truncate_to` clips the visible length
        afterwards (see truncate_file)."""
        # upload blobs OUTSIDE the path lock (slow), merge under it:
        # concurrent posts to one path must not lose each other's
        # chunk-list updates (read-modify-write race)
        new_chunks = []
        for off in range(0, len(data), CHUNK_SIZE):
            piece = data[off:off + CHUNK_SIZE]
            a, r = operation.assign_and_upload(
                self.master, piece, collection=self.collection,
                replication=self.replication)
            new_chunks.append(
                FileChunk(a.fid, offset + off, len(piece),
                          r.get("eTag", ""), time.time_ns()))
        with self._chunk_lock(path):
            entry = self.find_entry(path)
            if entry is None:
                entry = Entry(normalize_path(path),
                              is_directory=False,
                              attributes=Attributes())
            elif entry.is_directory:
                raise IsADirectoryError(path)
            entry.chunks.extend(new_chunks)
            if truncate_to is not None:
                self._clip_chunks(entry, truncate_to)
            entry.attributes.mtime = time.time()
            self.create_entry(entry)
            return entry

    @staticmethod
    def _clip_chunks(entry: Entry, length: int) -> None:
        """Drop/clip chunk extents beyond `length` (a FileChunk's
        visible size can shrink without rewriting its blob)."""
        kept = []
        for c in entry.chunks:
            if c.offset >= length:
                continue
            if c.offset + c.size > length:
                c.size = length - c.offset
            kept.append(c)
        entry.chunks = kept

    def truncate_file(self, path: str, length: int) -> Entry:
        """Set the visible file length: clip beyond, zero-extend by a
        one-byte sentinel chunk when growing (reads zero-fill gaps,
        but total size is the max chunk extent)."""
        with self._chunk_lock(path):
            entry = self.find_entry(path)
            if entry is None or entry.is_directory:
                raise FileNotFoundError(path)
            current = total_size(entry.chunks)
            if length >= current:
                grow = length > current
            else:
                self._clip_chunks(entry, length)
                entry.attributes.mtime = time.time()
                self.create_entry(entry)
                return entry
        if grow:
            # append_chunks retakes the lock (upload happens outside)
            return self.append_chunks(path, length - 1, b"\x00")
        return entry

    # chunk bodies over this size are never cached whole (a tiny view
    # into a huge chunk must not stage the whole blob through memory
    # to warm the cache) — the filer's own chunks are CHUNK_SIZE, so
    # the default covers everything this filer wrote itself
    CHUNK_CACHE_ITEM_MAX = CHUNK_SIZE

    def _read_view(self, view) -> bytes:
        """One ChunkView's bytes, through the hot chunk-body cache
        when the blob is cache-worthy.  Chunk fids are immutable —
        overwrites mint new fids — so cached bodies never need
        invalidation, and serving a slice of a cached body replaces a
        filer->volume network round trip with a memory copy."""
        # armed `filer.chunk.fetch` faults (delay/error) fire before
        # the cache answers — chaos coverage for the filer->volume
        # read leg of the deadline plane; keyed by the chunk fid
        from .. import faults
        faults.fire("filer.chunk.fetch", key=view.file_id)
        cc = self.chunk_cache
        if cc is not None and 0 < view.chunk_size <= \
                self.CHUNK_CACHE_ITEM_MAX:
            body = cc.get(view.file_id)
            if body is None:
                # fetch the WHOLE chunk once (the reference mount
                # caches whole chunks for the same reason: the next
                # zipfian read wants a different slice of the same
                # hot blob)
                body = operation.read(self.master, view.file_id)
                cc.set(view.file_id, body)
            return body[view.chunk_offset:view.chunk_offset
                        + view.size]
        # ranged read: fetch only the view's bytes, not the chunk
        return operation.read(self.master, view.file_id,
                              view.chunk_offset, view.size)

    def read_file(self, path: str, offset: int = 0,
                  size: int | None = None) -> bytes:
        """Chunk-resolved ranged read (filer/stream.go:99)."""
        entry = self.find_entry(path)
        if entry is None or entry.is_directory:
            raise FileNotFoundError(path)
        file_size = total_size(entry.chunks)
        if size is None:
            size = file_size - offset
        size = max(0, min(size, file_size - offset))
        if size == 0:
            return b""
        out = bytearray(size)
        for view in view_from_chunks(entry.chunks, offset, size):
            piece = self._read_view(view)
            lo = view.logical_offset - offset
            out[lo:lo + len(piece)] = piece
        return bytes(out)

    def open_read_stream(self, entry: Entry, offset: int, size: int,
                         on_close=None) -> "_ViewStream":
        """File-like over [offset, offset+size) of `entry`'s content:
        views are fetched lazily one at a time as httpd drains the
        response, so a multi-GB filer GET holds at most ONE chunk in
        memory instead of the whole body (the zero-copy audit's filer
        fix; gaps read as zeros exactly like read_file).  `on_close`
        runs when the server finishes the response (QoS byte
        release)."""
        views = view_from_chunks(entry.chunks, offset, size)
        return _ViewStream(self, views, offset, size,
                           on_close=on_close)

    # -- metadata subscription (filer/filer_notify.go) --------------------

    def _notify(self, op: str, new_entry: Entry | None,
                old_entry: Entry | None) -> None:
        if self.meta_plane is not None:
            # WAL path: ONE serialization, durable at the metalog
            # barrier (this is the write's ack point), overlay
            # ingested before any listener runs
            event = self.meta_plane.commit(op, new_entry, old_entry)
        else:
            event = {
                "op": op,
                "tsNs": time.time_ns(),
                "newEntry": new_entry.to_json() if new_entry else None,
                "oldEntry": old_entry.to_json() if old_entry else None,
            }
            # MetaLog stamps (strictly monotonic) and persists BEFORE
            # live listeners see the event, so a listener's recorded
            # tsNs is always replayable after a disconnect
            event = self.meta_log.append(event)
        with self._log_lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(event)
            except Exception as e:  # noqa: BLE001 — listeners are
                from ..util import wlog         # isolated
                wlog.warning("meta listener raised: %s", e,
                             component="filer")

    def subscribe(self, fn: Callable[[dict], None]) -> None:
        with self._log_lock:
            self._listeners.append(fn)

    def events_since(self, ts_ns: int, limit: int = 0) -> list[dict]:
        return self.meta_log.events_since(ts_ns, limit)

    def close(self) -> None:
        """Teardown: the meta plane first (its final apply leaves the
        store a complete checkpoint on clean shutdown), then store and
        log."""
        if self.meta_plane is not None:
            self.meta_plane.close()
        self.store.close()
        self.meta_log.close()
