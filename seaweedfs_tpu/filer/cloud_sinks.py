"""`filer.backup` cloud sinks — gcs / azure / backblaze
(weed/replication/sink/{gcssink,azuresink,b2sink}).

Same engine as every other sink (FilerSync: poll the persistent
metadata stream, apply, checkpoint), with wire-faithful appliers:

  GcsSink    Google Cloud Storage JSON API (media upload + object
             delete), Bearer auth; `endpoint` override targets the
             standard GCS emulator wire (fake-gcs-server shape).
  AzureSink  Azure Blob REST with hand-rolled SharedKey signing
             (Put Blob / Delete Blob), api-version 2020-10-02.
  B2Sink     Backblaze native B2 API: b2_authorize_account ->
             b2_get_upload_url -> b2_upload_file, versions listed and
             deleted on delete events.

No cloud SDKs exist in this environment (and the reference links the
official ones); these speak the documented REST surfaces directly, so
they are unit-testable against local mock servers and work against
the real services when credentials + egress exist.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import urllib.parse
from email.utils import formatdate

from ..server.httpd import http_bytes
from .filer_sync import FilerSync, _quote


class _CloudSink(FilerSync):
    """Shared applier: create/update uploads the file's bytes at its
    filer path, delete removes it, rename is delete+upload (object
    stores have no rename) — the s3sink event mapping."""

    def __init__(self, source: str, target: str, key_prefix: str = "",
                 state_path: "str | None" = None,
                 poll_interval: float = 0.2):
        super().__init__(source, target, state_path, poll_interval)
        self.key_prefix = key_prefix.strip("/")

    def _key(self, path: str) -> str:
        key = path.lstrip("/")
        return f"{self.key_prefix}/{key}" if self.key_prefix else key

    def _apply(self, ev: dict) -> None:
        op = ev.get("op")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        if op in ("create", "update") and new:
            self._put_entry(new)
        elif op == "delete" and old:
            if not old.get("isDirectory"):
                self._delete(self._key(old["fullPath"]))
        elif op == "rename" and new and old:
            if not old.get("isDirectory"):
                self._delete(self._key(old["fullPath"]))
            self._put_entry(new)

    def _put_entry(self, entry: dict) -> None:
        if entry.get("isDirectory"):
            return
        st, body, _ = http_bytes(
            "GET", self.source + _quote(entry["fullPath"]))
        if st == 404:
            return  # deleted since; the delete event follows
        if st >= 300:
            raise RuntimeError(
                f"{self.target}: read {entry['fullPath']}: {st}")
        self._upload(self._key(entry["fullPath"]), body)

    # subclasses implement the wire verbs
    def _upload(self, key: str, data: bytes) -> None:
        raise NotImplementedError

    def _delete(self, key: str) -> None:
        raise NotImplementedError


class GcsSink(_CloudSink):
    """gcssink: JSON API media upload / delete.
    Auth: Bearer `token` (or env GOOGLE_BEARER_TOKEN); GCS emulators
    accept anonymous requests."""

    def __init__(self, source: str, bucket: str,
                 endpoint: str = "https://storage.googleapis.com",
                 token: str = "", key_prefix: str = "",
                 state_path: "str | None" = None,
                 poll_interval: float = 0.2):
        super().__init__(source, f"gcs:{endpoint}/{bucket}/{key_prefix}",
                         key_prefix, state_path, poll_interval)
        self.endpoint = endpoint.rstrip("/")
        self.bucket = bucket
        self.token = token or os.environ.get("GOOGLE_BEARER_TOKEN", "")

    def _headers(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} \
            if self.token else {}

    def _upload(self, key: str, data: bytes) -> None:
        q = urllib.parse.urlencode({"uploadType": "media",
                                    "name": key})
        st, body, _ = http_bytes(
            "POST",
            f"{self.endpoint}/upload/storage/v1/b/{self.bucket}/o?{q}",
            data, {"Content-Type": "application/octet-stream",
                   **self._headers()})
        if st >= 300:
            raise RuntimeError(f"gcs upload {key}: {st} {body[:200]}")

    def _delete(self, key: str) -> None:
        obj = urllib.parse.quote(key, safe="")
        st, body, _ = http_bytes(
            "DELETE",
            f"{self.endpoint}/storage/v1/b/{self.bucket}/o/{obj}",
            None, self._headers())
        if st >= 300 and st != 404:
            raise RuntimeError(f"gcs delete {key}: {st}")


class AzureSink(_CloudSink):
    """azuresink: Blob REST with SharedKey authorization
    (Put Blob / Delete Blob).  The signature is the documented
    HMAC-SHA256 over the canonicalized headers + resource."""

    API_VERSION = "2020-10-02"

    def __init__(self, source: str, account: str, account_key: str,
                 container: str, endpoint: str = "",
                 key_prefix: str = "",
                 state_path: "str | None" = None,
                 poll_interval: float = 0.2):
        endpoint = (endpoint or
                    f"https://{account}.blob.core.windows.net").rstrip("/")
        super().__init__(
            source, f"azure:{endpoint}/{container}/{key_prefix}",
            key_prefix, state_path, poll_interval)
        self.endpoint = endpoint
        self.account = account
        self.key = base64.b64decode(account_key)
        self.container = container

    def _auth(self, method: str, path: str, headers: dict,
              content_length: int) -> str:
        """SharedKey string-to-sign (Storage services REST docs):
        VERB, 12 standard headers, canonicalized x-ms-* headers,
        canonicalized resource."""
        xms = "".join(
            f"{k.lower()}:{v}\n" for k, v in
            sorted(headers.items()) if k.lower().startswith("x-ms-"))
        sts = (f"{method}\n\n\n"
               f"{content_length if content_length else ''}\n\n"
               f"{headers.get('Content-Type', '')}\n\n\n\n\n\n\n"
               f"{xms}"
               f"/{self.account}{path}")
        sig = base64.b64encode(hmac.new(
            self.key, sts.encode(), hashlib.sha256).digest()).decode()
        return f"SharedKey {self.account}:{sig}"

    def _request(self, method: str, blob: str, data: "bytes | None",
                 extra: "dict | None" = None) -> "tuple[int, bytes]":
        path = f"/{self.container}/" + urllib.parse.quote(blob)
        headers = {"x-ms-date": formatdate(usegmt=True),
                   "x-ms-version": self.API_VERSION, **(extra or {})}
        headers["Authorization"] = self._auth(
            method, path, headers, len(data) if data else 0)
        st, body, _ = http_bytes(method, self.endpoint + path, data,
                                 headers)
        return st, body

    def _upload(self, key: str, data: bytes) -> None:
        st, body = self._request(
            "PUT", key, data,
            {"x-ms-blob-type": "BlockBlob",
             "Content-Type": "application/octet-stream"})
        if st >= 300:
            raise RuntimeError(f"azure put {key}: {st} {body[:200]}")

    def _delete(self, key: str) -> None:
        st, _body = self._request("DELETE", key, None)
        if st >= 300 and st != 404:
            raise RuntimeError(f"azure delete {key}: {st}")


class B2Sink(_CloudSink):
    """b2sink: native B2 API (authorize -> get_upload_url -> upload;
    delete removes every version, b2_sink.go deleteEntry)."""

    def __init__(self, source: str, key_id: str, app_key: str,
                 bucket: str, bucket_id: str = "",
                 endpoint: str = "https://api.backblazeb2.com",
                 key_prefix: str = "",
                 state_path: "str | None" = None,
                 poll_interval: float = 0.2):
        super().__init__(source, f"b2:{bucket}/{key_prefix}",
                         key_prefix, state_path, poll_interval)
        self.key_id = key_id
        self.app_key = app_key
        self.bucket = bucket
        self.bucket_id = bucket_id
        self.auth_endpoint = endpoint.rstrip("/")
        self._api: "dict | None" = None      # authorize_account result
        self._upload_info: "dict | None" = None  # get_upload_url result

    # -- b2 session -------------------------------------------------------

    def _authorize(self) -> dict:
        if self._api is None:
            basic = base64.b64encode(
                f"{self.key_id}:{self.app_key}".encode()).decode()
            st, body, _ = http_bytes(
                "GET", f"{self.auth_endpoint}/b2api/v2/"
                       f"b2_authorize_account",
                None, {"Authorization": f"Basic {basic}"})
            if st != 200:
                raise RuntimeError(f"b2 authorize: {st}")
            self._api = json.loads(body)
            if not self.bucket_id:
                self.bucket_id = self._find_bucket_id()
        return self._api

    def _find_bucket_id(self) -> str:
        api = self._api
        st, body, _ = http_bytes(
            "POST", f"{api['apiUrl']}/b2api/v2/b2_list_buckets",
            json.dumps({"accountId": api["accountId"],
                        "bucketName": self.bucket}).encode(),
            {"Authorization": api["authorizationToken"]})
        if st != 200:
            raise RuntimeError(f"b2 list_buckets: {st}")
        for b in json.loads(body).get("buckets", []):
            if b["bucketName"] == self.bucket:
                return b["bucketId"]
        raise RuntimeError(f"b2 bucket {self.bucket!r} not found")

    def _upload_target(self) -> dict:
        if self._upload_info is None:
            api = self._authorize()
            st, body, _ = http_bytes(
                "POST", f"{api['apiUrl']}/b2api/v2/b2_get_upload_url",
                json.dumps({"bucketId": self.bucket_id}).encode(),
                {"Authorization": api["authorizationToken"]})
            if st != 200:
                raise RuntimeError(f"b2 get_upload_url: {st}")
            self._upload_info = json.loads(body)
        return self._upload_info

    def _reset(self) -> None:
        """B2 upload URLs are single-writer and expire; on failure a
        fresh authorize + upload URL is the documented retry."""
        self._api = None
        self._upload_info = None

    # -- verbs ------------------------------------------------------------

    def _upload(self, key: str, data: bytes) -> None:
        tgt = self._upload_target()
        st, body, _ = http_bytes(
            "POST", tgt["uploadUrl"], data, {
                "Authorization": tgt["authorizationToken"],
                "X-Bz-File-Name": urllib.parse.quote(key),
                "Content-Type": "b2/x-auto",
                "X-Bz-Content-Sha1":
                    hashlib.sha1(data).hexdigest()})
        if st != 200:
            self._reset()
            raise RuntimeError(f"b2 upload {key}: {st} {body[:200]}")

    def _delete(self, key: str) -> None:
        api = self._authorize()
        # every version must go (b2_sink.go deleteEntry); the listing
        # is paginated — follow nextFileName/nextFileId or a file with
        # more versions than one page leaves orphans behind
        cursor = {"startFileName": key}
        while True:
            st, body, _ = http_bytes(
                "POST",
                f"{api['apiUrl']}/b2api/v2/b2_list_file_versions",
                json.dumps({"bucketId": self.bucket_id,
                            "prefix": key, **cursor}).encode(),
                {"Authorization": api["authorizationToken"]})
            if st != 200:
                self._reset()
                raise RuntimeError(f"b2 list_file_versions: {st}")
            page = json.loads(body)
            for f in page.get("files", []):
                if f["fileName"] != key:
                    continue
                st, _, _ = http_bytes(
                    "POST",
                    f"{api['apiUrl']}/b2api/v2/b2_delete_file_version",
                    json.dumps({"fileName": f["fileName"],
                                "fileId": f["fileId"]}).encode(),
                    {"Authorization": api["authorizationToken"]})
                if st != 200:
                    self._reset()
                    raise RuntimeError(f"b2 delete {key}: {st}")
            nxt = page.get("nextFileName")
            if not nxt or nxt != key:
                return
            cursor = {"startFileName": nxt,
                      "startFileId": page.get("nextFileId")}
