"""`filer.backup` — continuously mirror a filer's namespace into a
LOCAL directory (weed/command/filer_backup.go; the localsink of
weed/replication/sink/).

Same engine as filer.sync (poll the persistent metadata stream, apply
each event, checkpoint the offset after it fully applies) with a
local-filesystem applier instead of a second filer: create/update
writes the file bytes under the backup root, delete removes, rename
moves.  A restarted backup resumes from its offset; a fresh one
replays the full history (the metadata log is persistent)."""

from __future__ import annotations

import os
import shutil

from ..server.httpd import http_bytes
from .filer_sync import FilerSync, _quote


class FilerBackup(FilerSync):
    def __init__(self, source: str, backup_dir: str,
                 state_path: str | None = None,
                 poll_interval: float = 0.2):
        super().__init__(source, f"localdir:{backup_dir}",
                         state_path, poll_interval)
        self.backup_dir = os.path.abspath(backup_dir)
        os.makedirs(self.backup_dir, exist_ok=True)

    def _local(self, path: str) -> str:
        """Map a filer path into the backup root, refusing traversal
        out of it."""
        local = os.path.abspath(
            os.path.join(self.backup_dir, path.lstrip("/")))
        if not local.startswith(self.backup_dir + os.sep) and \
                local != self.backup_dir:
            raise RuntimeError(f"backup path escapes root: {path}")
        return local

    # -- applier (localsink) ----------------------------------------------

    def _apply(self, ev: dict) -> None:
        op = ev.get("op")
        new = ev.get("newEntry")
        old = ev.get("oldEntry")
        if op in ("create", "update") and new:
            self._copy_entry(new)
        elif op == "delete" and old:
            local = self._local(old["fullPath"])
            if os.path.isdir(local):
                shutil.rmtree(local, ignore_errors=True)
            elif os.path.exists(local):
                os.remove(local)
        elif op == "rename" and new and old:
            src = self._local(old["fullPath"])
            dst = self._local(new["fullPath"])
            if os.path.exists(src):
                os.makedirs(os.path.dirname(dst), exist_ok=True)
                if os.path.isdir(src):
                    # replace, never nest: a re-applied rename (crash
                    # between apply and offset checkpoint) must stay
                    # idempotent, and shutil.move into an existing dir
                    # would produce dst/basename(src)
                    if os.path.isdir(dst):
                        shutil.rmtree(dst, ignore_errors=True)
                    shutil.move(src, dst)
                else:
                    os.replace(src, dst)
            else:
                self._copy_entry(new)

    def _copy_entry(self, entry: dict) -> None:
        local = self._local(entry["fullPath"])
        if entry.get("isDirectory"):
            os.makedirs(local, exist_ok=True)
            return
        st, body, _ = http_bytes(
            "GET", self.source + _quote(entry["fullPath"]))
        if st == 404:
            return  # deleted since; the delete event follows
        if st >= 300:
            raise RuntimeError(
                f"filer.backup: read {entry['fullPath']}: {st}")
        os.makedirs(os.path.dirname(local), exist_ok=True)
        tmp = local + ".tmp"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, local)
        mode = (entry.get("attributes") or {}).get("mode")
        if mode:
            os.chmod(local, mode & 0o7777)
