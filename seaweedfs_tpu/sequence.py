"""File-id sequencers (weed/sequence/memory_sequencer.go,
snowflake_sequencer.go): monotonically increasing needle keys."""

from __future__ import annotations

import threading
import time


class MemorySequencer:
    """In-memory counter; the master checkpoints/raft-replicates it in
    the reference — here the master persists it with its state."""

    # next_file_id(count) reserves [start, start+count): assign with
    # count=N may hand clients the base fid and let them DERIVE the
    # other N-1 keys (the reference's count-assign contract, the
    # filer funnel's assign batching)
    reserves_ranges = True

    def __init__(self, start: int = 1):
        self._counter = start
        self._lock = threading.Lock()

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            start = self._counter
            self._counter += count
            return start

    def set_max(self, seen: int) -> None:
        """Floor the counter ABOVE `seen`: next_file_id hands out
        `_counter` itself, so seen == _counter must also bump (the
        boundary where a heartbeat-reported max key would otherwise be
        reissued; memory_sequencer.go uses the same <= rule)."""
        with self._lock:
            if seen >= self._counter:
                self._counter = seen + 1

    def peek(self) -> int:
        return self._counter


class SnowflakeSequencer:
    """41-bit ms timestamp | 10-bit machine id | 12-bit sequence
    (weed/sequence/snowflake_sequencer.go via sony/sonyflake layout)."""

    EPOCH_MS = 1_577_836_800_000  # 2020-01-01

    # snowflake ids are clock-derived: count>1 does NOT reserve a
    # contiguous range, so derived key+i would collide with the next
    # issued id — the master caps the granted count at 1
    reserves_ranges = False

    def __init__(self, machine_id: int = 1):
        if not 0 <= machine_id < 1024:
            raise ValueError("machine id must fit in 10 bits")
        self.machine_id = machine_id
        self._lock = threading.Lock()
        self._last_ms = 0
        self._seq = 0

    def next_file_id(self, count: int = 1) -> int:
        with self._lock:
            now = int(time.time() * 1000)
            if now == self._last_ms:
                self._seq += 1
                if self._seq >= 4096:
                    while now <= self._last_ms:
                        now = int(time.time() * 1000)
                    self._seq = 0
            else:
                self._seq = 0
            self._last_ms = now
            return (((now - self.EPOCH_MS) & ((1 << 41) - 1)) << 22) | \
                (self.machine_id << 12) | self._seq

    def set_max(self, seen: int) -> None:
        pass  # time-ordered by construction
