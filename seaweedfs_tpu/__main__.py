"""CLI entry: `python -m seaweedfs_tpu <command>` — the analog of the
reference's single multi-command `weed` binary (weed/weed.go:50,
weed/command/command.go:11-51).

Commands: master, volume, server (all-in-one), shell, upload, download,
bench.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv: list[str] | None = None) -> int:
    # WEED_LOCKGRAPH=1 race harness: must patch lock factories before
    # any server object is constructed (devtools/lockgraph.py)
    from .devtools.lockgraph import maybe_instrument
    maybe_instrument()
    # every role is an IO-chained thread server (handler threads block
    # on sockets between short CPU bursts); CPython's default 5ms GIL
    # switch interval adds a convoy delay to EVERY hop's response
    # wakeup, which multiplies across the client->filer->master->
    # volume chain.  1ms costs negligible context-switch overhead at
    # our thread counts and measurably compresses per-hop latency.
    import sys as _sys
    _sys.setswitchinterval(0.001)
    p = argparse.ArgumentParser(prog="seaweedfs-tpu")
    # security.toml discovery (util/config.go:34
    # LoadSecurityConfiguration; scaffold command/scaffold/security.toml)
    p.add_argument("-securityToml", default="",
                   help="path to security.toml (jwt signing keys, "
                        "admin key, ip whitelist)")
    # glog-analog logging flags (util/wlog; weed/glog -v/-logdir)
    p.add_argument("-v", type=int, default=None, metavar="LEVEL",
                   help="verbose log level (wlog.V gates; also "
                        "WEED_V)")
    p.add_argument("-logdir", default="",
                   help="also write logs to <logdir>/weed.log with "
                        "size rotation (glog_file.go role)")
    p.add_argument("-logJson", dest="log_json", action="store_true",
                   help="one JSON object per log line "
                        "(glog_json.go role)")
    sub = p.add_subparsers(dest="cmd", required=True)

    m = sub.add_parser("master", help="start a master server")
    m.add_argument("-ip", default="127.0.0.1")
    m.add_argument("-port", type=int, default=9333)
    m.add_argument("-volumeSizeLimitMB", type=int, default=1024)
    m.add_argument("-defaultReplication", default="000")
    m.add_argument("-peers", default="",
                   help="comma-separated master peers for HA "
                        "(raft leader election + log replication)")
    m.add_argument("-mdir", default="",
                   help="meta dir: persists the raft log/snapshot so "
                        "topology id + fid sequence survive restarts")
    m.add_argument("-metricsAddress", dest="metrics_address",
                   default="", help="Prometheus pushgateway "
                   "host:port (stats/metrics.go LoopPushingMetric)")
    m.add_argument("-metricsIntervalSec", dest="metrics_interval",
                   type=int, default=15)
    m.add_argument("-telemetry", action="store_true",
                   help="OPT-IN anonymous usage reports "
                        "(weed/telemetry; default off)")
    m.add_argument("-telemetryUrl", dest="telemetry_url",
                   default="", help="collector URL for -telemetry")

    v = sub.add_parser("volume", help="start a volume server")
    v.add_argument("-ip", default="127.0.0.1")
    v.add_argument("-port", type=int, default=8080)
    v.add_argument("-dir", default=".", help="comma-separated data dirs")
    v.add_argument("-mserver", default="127.0.0.1:9333")
    v.add_argument("-max", type=int, default=8)
    v.add_argument("-dataCenter", default="")
    v.add_argument("-rack", default="")
    v.add_argument("-tierBackend", default="",
                   help="S3 tier backend: endpoint,bucket[,accessKey,"
                        "secretKey] — lets this server reopen tiered "
                        "volumes after restart (master.toml "
                        "[storage.backend.s3] analog)")
    v.add_argument("-metricsAddress", dest="metrics_address",
                   default="", help="Prometheus pushgateway host:port")
    v.add_argument("-metricsIntervalSec", dest="metrics_interval",
                   type=int, default=15)
    v.add_argument("-memoryMapMaxSizeMb", dest="mmap_mb", type=int,
                   default=0,
                   help="mmap the .dat read path for volumes up to "
                        "this size (backend/memory_map role; 0 off)")
    v.add_argument("-fsync", action="store_true",
                   help="fsync acked writes (power-loss durability "
                        "tier; one fsync per group-commit window, "
                        "amortized across concurrent writers)")

    s = sub.add_parser(
        "server", help="all-in-one: master + volume (+ filer + s3), the "
        "weed server / weed mini analog (command/mini.go:894 "
        "dependency-ordered startup)")
    s.add_argument("-ip", default="127.0.0.1")
    s.add_argument("-master.port", dest="master_port", type=int,
                   default=9333)
    s.add_argument("-volume.port", dest="volume_port", type=int,
                   default=8080)
    s.add_argument("-filer", action="store_true")
    s.add_argument("-filer.port", dest="filer_port", type=int,
                   default=8888)
    s.add_argument("-s3", action="store_true")
    s.add_argument("-s3.port", dest="s3_port", type=int, default=8333)
    s.add_argument("-s3.accessKey", dest="s3_access", default="")
    s.add_argument("-s3.secretKey", dest="s3_secret", default="")
    s.add_argument("-dir", default=".")
    s.add_argument("-tierBackend", default="",
                   help="S3 tier backend: endpoint,bucket[,accessKey,"
                        "secretKey]")

    fl = sub.add_parser("filer", help="start a filer server")
    fl.add_argument("-ip", default="127.0.0.1")
    fl.add_argument("-port", type=int, default=8888)
    fl.add_argument("-master", default="127.0.0.1:9333")
    fl.add_argument("-store", default="filer.db",
                    help="store path (sqlite file / lsm dir), or "
                         ":memory:")
    fl.add_argument("-storeType", dest="store_type",
                    default="sqlite",
                    choices=["sqlite", "lsm", "redis", "elastic"],
                    help="metadata store archetype (filerstore.go: "
                         "sqlite=SQL, lsm=embedded ordered-KV — the "
                         "reference's leveldb default — redis=RESP "
                         "server at -store host:port); a filer.toml "
                         "on the config search path overrides these "
                         "defaults (util/config)")
    fl.add_argument("-collection", default="")
    fl.add_argument("-replication", default="")
    fl.add_argument("-notification", default="",
                    help="metadata notification sink "
                         "(weed/notification): webhook:http://...,"
                         " mq:broker/ns/topic, kafka:host:port/topic"
                         " (real Kafka wire protocol, any broker),"
                         " or logfile:/path")
    fl.add_argument("-lockPeers", dest="lock_peers", default="",
                    help="comma-separated filer addresses forming the "
                         "distributed-lock ring (give every filer the "
                         "same list; cluster/lock_manager)")
    fl.add_argument("-workers", type=int, default=None,
                    help="pre-fork worker processes sharing this "
                         "port via SO_REUSEPORT (sqlite store only: "
                         "one WAL store + one metalog dir, watermark-"
                         "coherent — the funnel past one process's "
                         "GIL).  Default 1; env "
                         "SEAWEEDFS_TPU_FILER_WORKERS sets it "
                         "cluster-wide.  0 marks a spawned worker "
                         "(internal).")
    fl.add_argument("-metaPlane", dest="meta_plane", default="",
                    choices=["", "0", "1"],
                    help="filer meta plane (metalog-as-WAL ack + "
                         "async store checkpointing, filer/"
                         "meta_plane.py): 1 forces on, 0 forces the "
                         "synchronous store commit; default auto "
                         "(on for durable sqlite/lsm stores).  Sets "
                         "SEAWEEDFS_TPU_FILER_META_PLANE so pre-fork "
                         "workers inherit it.")
    fl.add_argument("-metricsAddress", dest="metrics_address",
                    default="", help="Prometheus pushgateway "
                    "host:port (stats/metrics.go LoopPushingMetric)")
    fl.add_argument("-metricsIntervalSec", dest="metrics_interval",
                    type=int, default=15)

    s3p = sub.add_parser("s3", help="start the S3 gateway (on a filer)")
    s3p.add_argument("-ip", default="127.0.0.1")
    s3p.add_argument("-port", type=int, default=8333)
    s3p.add_argument("-master", default="127.0.0.1:9333")
    s3p.add_argument("-filer", default="",
                     help="attach to a RUNNING filer's namespace "
                          "(the reference's weed s3 -filer mode); "
                          "overrides -master/-store")
    s3p.add_argument("-store", default="filer.db")
    s3p.add_argument("-accessKey", default="")
    s3p.add_argument("-secretKey", default="")
    s3p.add_argument("-iamConfig", dest="iam_config", default="",
                     help="identities JSON (auth_credentials.go "
                          "s3.json shape); supersedes -accessKey")
    s3p.add_argument("-metricsPort", dest="metrics_port", type=int,
                     default=None,
                     help="serve per-bucket Prometheus metrics on a "
                          "SEPARATE listener (the reference's "
                          "weed s3 -metricsPort)")
    s3p.add_argument("-metricsAddress", dest="metrics_address",
                     default="", help="Prometheus pushgateway "
                     "host:port (stats/metrics.go LoopPushingMetric)")
    s3p.add_argument("-metricsIntervalSec", dest="metrics_interval",
                     type=int, default=15)
    s3p.add_argument("-stsKey", dest="sts_key", default="",
                     help="STS signing key: accept temporary "
                          "credentials minted by the iam server")
    s3p.add_argument("-rolesFile", dest="roles_file", default="")
    s3p.add_argument("-kmsFile", dest="kms_file", default="",
                     help="local KMS keystore (enables SSE-KMS)")
    s3p.add_argument("-kmsEndpoint", dest="kms_endpoint", default="",
                     help="remote AWS-KMS-protocol endpoint "
                          "host:port[,accessKey,secretKey[,region]] "
                          "(kms/aws analog); overrides -kmsFile")
    s3p.add_argument("-kmsCloud", dest="kms_cloud", default="",
                     help="cloud KMS spec (kms/gcp|azure|openbao): "
                          "gcp:endpoint,keyName,token | "
                          "azure:vaultUrl,keyName,token | "
                          "openbao:addr,keyName,token; overrides "
                          "-kmsEndpoint/-kmsFile")

    iamp = sub.add_parser(
        "iam", help="IAM management API + STS AssumeRole "
        "(weed/iamapi, weed/iam/sts) sharing an identities JSON with "
        "the s3 gateway")
    iamp.add_argument("-ip", default="127.0.0.1")
    iamp.add_argument("-port", type=int, default=8111)
    iamp.add_argument("-iamConfig", dest="iam_config", required=True)
    iamp.add_argument("-stsKey", dest="sts_key", default="")
    iamp.add_argument("-rolesFile", dest="roles_file", default="")
    iamp.add_argument("-oidcConfig", dest="oidc_config", default="",
                      help="JSON list of OIDC providers: [{name, "
                           "issuer, audience?, hs256Secret? | "
                           "rsaPublicKeyFile?}] — enables "
                           "AssumeRoleWithWebIdentity")

    ad = sub.add_parser("admin", help="start the maintenance admin server")
    ad.add_argument("-ip", default="127.0.0.1")
    ad.add_argument("-port", type=int, default=23646)
    ad.add_argument("-master", default="127.0.0.1:9333")
    ad.add_argument("-detectionInterval", type=float, default=30.0)
    ad.add_argument("-dataDir", default="",
                    help="persist jobs/config/workers under "
                         "<dataDir>/plugin/ (survives restart)")

    wk = sub.add_parser(
        "worker", help="start a maintenance worker (tpu_ec sidecar: owns "
        "the accelerator and executes erasure-coding jobs)")
    wk.add_argument("-admin", default="127.0.0.1:23646")
    wk.add_argument("-master", default="127.0.0.1:9333")
    wk.add_argument("-dir", default="/tmp/seaweedfs_tpu_worker")
    wk.add_argument("-capabilities", default="erasure_coding,vacuum")
    wk.add_argument("-backend", default="",
                    help="EC codec backend: jax|cpu (default: auto)")

    wd = sub.add_parser("webdav", help="WebDAV gateway attached to a "
                        "running filer (server/webdav_server.go)")
    wd.add_argument("-ip", default="127.0.0.1")
    wd.add_argument("-port", type=int, default=7333)
    wd.add_argument("-filer", default="127.0.0.1:8888",
                    help="filer host:port whose namespace to serve")

    mnt = sub.add_parser(
        "mount", help="FUSE-mount a filer (read-only slice; "
        "weed/mount analog — see seaweedfs_tpu/mount/DESIGN.md)")
    mnt.add_argument("-filer", default="127.0.0.1:8888")
    mnt.add_argument("-dir", required=True, help="mountpoint")

    mqb = sub.add_parser(
        "mq.broker", help="start a message-queue broker "
        "(mq/broker/broker_server.go)")
    mqb.add_argument("-ip", default="127.0.0.1")
    mqb.add_argument("-port", type=int, default=17777)
    mqb.add_argument("-filer", default="127.0.0.1:8888")

    mqa = sub.add_parser(
        "mq.agent", help="MQ agent: session facade in front of the "
        "broker cluster (mq/agent/agent_server.go)")
    mqa.add_argument("-ip", default="127.0.0.1")
    mqa.add_argument("-port", type=int, default=16777)
    mqa.add_argument("-broker", default="127.0.0.1:17777")

    kgw = sub.add_parser(
        "mq.kafka", help="Kafka wire-protocol gateway over a running "
        "MQ broker (mq/kafka/gateway)")
    kgw.add_argument("-ip", default="127.0.0.1")
    kgw.add_argument("-port", type=int, default=9092)
    kgw.add_argument("-broker", default="127.0.0.1:17777")
    kgw.add_argument("-users", default="",
                     help="SASL/PLAIN credentials user:pass[,u2:p2] "
                          "— when set, clients must authenticate "
                          "before any data API")

    fsync = sub.add_parser(
        "filer.sync", help="continuously replicate one filer's "
        "namespace+content to another, resuming from a persisted "
        "offset (command/filer_sync.go)")
    fsync.add_argument("-from", dest="sync_from", required=True,
                       help="source filer host:port")
    fsync.add_argument("-to", dest="sync_to", required=True,
                       help="target filer host:port")
    fsync.add_argument("-state", default="",
                       help="offset checkpoint file (default: a "
                            "per-direction name derived from -from/-to)")
    fsync.add_argument("-interval", type=float, default=0.5,
                       help="poll interval seconds when idle")

    fbak = sub.add_parser(
        "filer.backup", help="continuously mirror a filer into a "
        "local directory (command/filer_backup.go)")
    fbak.add_argument("-filer", required=True,
                      help="source filer host:port")
    fbak.add_argument("-dir", required=True, help="backup root")
    fbak.add_argument("-state", default="",
                      help="offset checkpoint file")
    fbak.add_argument("-interval", type=float, default=0.5)

    fbs3 = sub.add_parser(
        "filer.backup.s3", help="continuously mirror a filer into an "
        "S3-compatible bucket (replication/sink/s3sink)")
    fbs3.add_argument("-filer", required=True,
                      help="source filer host:port")
    fbs3.add_argument("-endpoint", required=True,
                      help="S3 endpoint, e.g. http://host:8333")
    fbs3.add_argument("-bucket", required=True)
    fbs3.add_argument("-accessKey", dest="access_key", default="")
    fbs3.add_argument("-secretKey", dest="secret_key", default="")
    fbs3.add_argument("-prefix", default="",
                      help="key prefix inside the bucket")
    fbs3.add_argument("-state", default="",
                      help="offset checkpoint file")
    fbs3.add_argument("-interval", type=float, default=0.5)

    fbgcs = sub.add_parser(
        "filer.backup.gcs", help="continuously mirror a filer into a "
        "Google Cloud Storage bucket (replication/sink/gcssink)")
    fbgcs.add_argument("-filer", required=True)
    fbgcs.add_argument("-bucket", required=True)
    fbgcs.add_argument("-endpoint",
                       default="https://storage.googleapis.com",
                       help="override for emulators")
    fbgcs.add_argument("-token", default="",
                       help="OAuth bearer (or env GOOGLE_BEARER_TOKEN)")
    fbgcs.add_argument("-prefix", default="")
    fbgcs.add_argument("-state", default="")
    fbgcs.add_argument("-interval", type=float, default=0.5)

    fbaz = sub.add_parser(
        "filer.backup.azure", help="continuously mirror a filer into "
        "an Azure Blob container (replication/sink/azuresink)")
    fbaz.add_argument("-filer", required=True)
    fbaz.add_argument("-account", required=True)
    fbaz.add_argument("-accountKey", dest="account_key", required=True,
                      help="base64 shared key")
    fbaz.add_argument("-container", required=True)
    fbaz.add_argument("-endpoint", default="",
                      help="override for emulators (azurite)")
    fbaz.add_argument("-prefix", default="")
    fbaz.add_argument("-state", default="")
    fbaz.add_argument("-interval", type=float, default=0.5)

    fbb2 = sub.add_parser(
        "filer.backup.b2", help="continuously mirror a filer into a "
        "Backblaze B2 bucket (replication/sink/b2sink)")
    fbb2.add_argument("-filer", required=True)
    fbb2.add_argument("-keyId", dest="key_id", required=True)
    fbb2.add_argument("-appKey", dest="app_key", required=True)
    fbb2.add_argument("-bucket", required=True)
    fbb2.add_argument("-endpoint",
                      default="https://api.backblazeb2.com")
    fbb2.add_argument("-prefix", default="")
    fbb2.add_argument("-state", default="")
    fbb2.add_argument("-interval", type=float, default=0.5)

    sf = sub.add_parser(
        "sftp", help="SFTP gateway attached to a running filer "
        "(weed/sftpd; from-scratch SSH transport — no SSH lib in env)")
    sf.add_argument("-ip", default="127.0.0.1")
    sf.add_argument("-port", type=int, default=2022)
    sf.add_argument("-filer", default="127.0.0.1:8888")
    sf.add_argument("-userStoreFile", dest="user_store", required=True,
                    help="JSON user store (sftpd/user/filestore.go)")
    sf.add_argument("-hostKeyFile", dest="host_key", default="",
                    help="ed25519 host key PEM; generated+saved if "
                         "missing")
    sf.add_argument("-authMethods", dest="auth_methods",
                    default="password,publickey")
    sf.add_argument("-banner", default="")
    sf.add_argument("-ldapServer", dest="ldap_server", default="",
                    help="host:port of an LDAP server for password "
                         "auth (iam/ldap, ldap_provider.go analog)")
    sf.add_argument("-ldapUserDnTemplate", dest="ldap_dn_template",
                    default="",
                    help="user DN template, {} = username "
                         "(e.g. uid={},ou=people,dc=corp)")
    sf.add_argument("-ldapBaseDn", dest="ldap_base_dn", default="")
    sf.add_argument("-ldapBindDn", dest="ldap_bind_dn", default="")
    sf.add_argument("-ldapBindPassword", dest="ldap_bind_password",
                    default="")
    sf.add_argument("-ldapTls", dest="ldap_tls",
                    action="store_true",
                    help="reach the directory over TLS (ldaps) — "
                         "simple binds carry cleartext passwords, so "
                         "use this for any non-loopback server")

    sfu = sub.add_parser(
        "sftp.user", help="manage an SFTP user-store file")
    sfu.add_argument("-store", required=True)
    sfu.add_argument("action", choices=["add", "delete", "list"])
    sfu.add_argument("-name", default="")
    sfu.add_argument("-password", default="")
    sfu.add_argument("-home", default="")
    sfu.add_argument("-pubkey", default="",
                     help="authorized key line 'ssh-ed25519 <b64>'")
    sfu.add_argument("-perm", action="append", default=[],
                     help="path:perm1,perm2 (repeatable)")

    rsync = sub.add_parser(
        "filer.remote.sync", help="push local changes under a "
        "remote-mounted directory back to the foreign object store "
        "(command/filer_remote_sync.go)")
    rsync.add_argument("-filer", required=True)
    rsync.add_argument("-dir", required=True,
                       help="remote-mounted filer directory")
    rsync.add_argument("-state", default="",
                       help="offset checkpoint file")
    rsync.add_argument("-interval", type=float, default=0.5)

    sh = sub.add_parser("shell", help="interactive admin shell")
    sh.add_argument("-master", default="127.0.0.1:9333")
    sh.add_argument("-filer", default="",
                    help="filer host:port for the fs.* command family")
    sh.add_argument("command", nargs="*",
                    help="run one command and exit")

    bm = sub.add_parser("benchmark",
                        help="write/read load test (weed benchmark)")
    bm.add_argument("-master", default="127.0.0.1:9333")
    bm.add_argument("-n", type=int, default=1000)
    bm.add_argument("-size", type=int, default=1024)
    bm.add_argument("-c", type=int, default=16)

    crt = sub.add_parser("cert", help="mint a cluster PKI (CA + node "
                         "cert) for the TLS plane (security/tls.go)")
    crt.add_argument("-dir", default="certs")
    crt.add_argument("-hosts", default="127.0.0.1,localhost",
                     help="comma-separated SAN hosts/IPs")

    sc = sub.add_parser("scaffold", help="print a commented template "
                        "config (command/scaffold)")
    sc.add_argument("-config", default="security",
                    choices=["security", "filer", "notification",
                             "replication"],
                    help="which template to print")

    up = sub.add_parser("upload", help="upload a file")
    up.add_argument("-master", default="127.0.0.1:9333")
    up.add_argument("file")

    an = sub.add_parser(
        "analyze", help="project-native static analysis: SWFS rules + "
        "baseline (devtools/RULES.md)")
    an.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: the "
                         "seaweedfs_tpu package)")
    an.add_argument("-json", dest="json_out", action="store_true",
                    help="machine-readable findings")
    an.add_argument("-baseline", default="",
                    help="baseline file (default: "
                         "devtools/baseline.json)")
    an.add_argument("-writeBaseline", dest="write_baseline",
                    action="store_true",
                    help="accept all current findings into the "
                         "baseline and exit 0")
    an.add_argument("-noBaseline", dest="no_baseline",
                    action="store_true",
                    help="report every finding, baselined or not")
    an.add_argument("-rules", default="",
                    help="comma-separated rule ids to run "
                         "(default: all)")

    sub.add_parser("version", help="print the build version "
                   "(command/version.go)")

    mt = sub.add_parser(
        "filer.meta.tail", help="tail the filer metadata event "
        "stream as JSON lines (command/filer_meta_tail.go)")
    mt.add_argument("-filer", default="127.0.0.1:8888")
    mt.add_argument("-sinceNs", dest="since_ns", type=int, default=0,
                    help="replay from this event timestamp (0 = now)")
    mt.add_argument("-pathPrefix", dest="path_prefix", default="",
                    help="only events under this path")
    mt.add_argument("-interval", type=float, default=1.0)
    mt.add_argument("-once", action="store_true",
                    help="drain the backlog and exit (no follow)")

    # offline volume tools (weed fix / compact / export): run against
    # UNMOUNTED volume files — stop the volume server first
    fx = sub.add_parser("fix", help="recreate a volume's .idx by "
                        "scanning its .dat (command/fix.go; stop the "
                        "volume server first)")
    fx.add_argument("-dir", required=True)
    fx.add_argument("-volumeId", dest="volume_id", type=int,
                    required=True)
    fx.add_argument("-collection", default="")

    cp = sub.add_parser("compact", help="offline vacuum of a volume "
                        "file (command/compact.go; stop the volume "
                        "server first)")
    cp.add_argument("-dir", required=True)
    cp.add_argument("-volumeId", dest="volume_id", type=int,
                    required=True)
    cp.add_argument("-collection", default="")

    ex = sub.add_parser("export", help="list or tar the live files "
                        "of one volume (command/export.go)")
    ex.add_argument("-dir", required=True)
    ex.add_argument("-volumeId", dest="volume_id", type=int,
                    required=True)
    ex.add_argument("-collection", default="")
    ex.add_argument("-o", dest="out", default="",
                    help="output .tar path (omit to just list)")

    down = sub.add_parser("download", help="download a fid")
    down.add_argument("-master", default="127.0.0.1:9333")
    down.add_argument("fid")

    # WEED_<ROLE>_<FLAG> env-var override layer (util/config,
    # reference viper SetEnvPrefix("weed")): rewrites parser DEFAULTS,
    # so explicit command-line flags still win
    from .util.config import apply_env_defaults
    env_applied = apply_env_defaults(sub.choices)

    args = p.parse_args(argv)

    from .util import wlog
    if args.v is not None:
        wlog.set_verbosity(args.v)
    if args.log_json:
        wlog.json_format(True)
    if args.logdir:
        import os as _os
        _os.makedirs(args.logdir, exist_ok=True)
        wlog.set_output(_os.path.join(args.logdir, "weed.log"))
    for line in env_applied:
        wlog.info("env override: %s", line, component="config")

    if args.securityToml:
        from . import qos, security
        security.configure(security.load_security_toml(args.securityToml))
        # the same file may carry a [qos] section (qos.py): tenant
        # admission limits + the foreground SLO for the EC throttle
        qos_cfg = qos.load_qos_toml(args.securityToml)
        if qos_cfg is not None:
            qos.configure(qos_cfg)
            wlog.info("qos config loaded from %s", args.securityToml,
                      component="config")

    if args.cmd == "master":
        from .server.master_server import MasterServer
        ms = MasterServer(args.ip, args.port,
                          volume_size_limit_mb=args.volumeSizeLimitMB,
                          default_replication=args.defaultReplication,
                          peers=args.peers or None,
                          meta_dir=args.mdir or None)
        ms.start()
        if args.metrics_address:
            from .stats import MetricsPusher
            MetricsPusher(ms.metrics, "master", ms.url,
                          args.metrics_address,
                          args.metrics_interval).start()
            print(f"pushing metrics to {args.metrics_address} "
                  f"every {args.metrics_interval}s")
        if args.telemetry and args.telemetry_url:
            from .telemetry import TelemetryClient
            TelemetryClient(args.telemetry_url,
                            enabled=True).start(ms.url)
            print(f"telemetry enabled -> {args.telemetry_url}")
        print(f"master listening on {ms.url}")
        _wait()
    elif args.cmd == "volume":
        from .server.volume_server import VolumeServer
        if args.tierBackend:
            from .storage.backend import configure_s3_backend
            parts = args.tierBackend.split(",")
            configure_s3_backend("default", parts[0],
                                 parts[1] if len(parts) > 1 else "tier",
                                 parts[2] if len(parts) > 2 else "",
                                 parts[3] if len(parts) > 3 else "")
        if args.mmap_mb:
            from .storage import store as _store_mod
            _store_mod.MMAP_READ_MB = args.mmap_mb
        vs = VolumeServer(args.dir.split(","), args.mserver,
                          host=args.ip, port=args.port,
                          max_volume_count=args.max,
                          data_center=args.dataCenter, rack=args.rack,
                          fsync=args.fsync)
        vs.start()
        if args.metrics_address:
            from .stats import MetricsPusher
            MetricsPusher(vs.metrics, "volume_server", vs.url,
                          args.metrics_address,
                          args.metrics_interval).start()
            print(f"pushing metrics to {args.metrics_address}")
        print(f"volume server listening on {vs.url}")
        _wait()
    elif args.cmd == "server":
        import os as _os
        from .server.master_server import MasterServer
        from .server.volume_server import VolumeServer
        if args.tierBackend:
            from .storage.backend import configure_s3_backend
            parts = args.tierBackend.split(",")
            configure_s3_backend("default", parts[0],
                                 parts[1] if len(parts) > 1 else "tier",
                                 parts[2] if len(parts) > 2 else "",
                                 parts[3] if len(parts) > 3 else "")
        ms = MasterServer(args.ip, args.master_port).start()
        vs = VolumeServer([args.dir], ms.url, host=args.ip,
                          port=args.volume_port).start()
        print(f"master on {ms.url}, volume on {vs.url}")
        if args.filer or args.s3:
            from .server.filer_server import FilerServer
            fs = FilerServer(
                ms.url, args.ip, args.filer_port,
                store_path=_os.path.join(args.dir, "filer.db"))
            fs.start()
            print(f"filer on {fs.url}")
            if args.s3:
                from .s3 import S3ApiServer
                creds = {args.s3_access: args.s3_secret} \
                    if args.s3_access else None
                gw = S3ApiServer(fs.filer, args.ip, args.s3_port,
                                 credentials=creds).start()
                print(f"s3 on {gw.url}")
        _wait()
    elif args.cmd == "filer":
        from .server.filer_server import FilerServer
        from .util.config import (filer_store_from_toml, find_toml,
                                  notification_from_toml)
        store_type, store_path = args.store_type, args.store
        # scaffold TOMLs override FLAG DEFAULTS only: an explicit
        # -store/-storeType on the command line wins (viper layering)
        toml_path = find_toml("filer.toml")
        if toml_path and store_type == "sqlite" and \
                store_path == "filer.db":
            picked = filer_store_from_toml(toml_path)
            if picked:
                store_type, store_path = picked
                wlog.info("filer store from %s: %s %s", toml_path,
                          store_type, store_path, component="config")
        notification = args.notification
        ntoml = find_toml("notification.toml")
        if ntoml and not notification:
            notification = notification_from_toml(ntoml)
            if notification:
                wlog.info("notification from %s: %s", ntoml,
                          notification, component="config")
        if args.meta_plane:
            # via the environment so spawned -workers siblings (which
            # re-exec this argv minus -port/-workers) inherit the same
            # plane mode even when driven by the flag
            os.environ["SEAWEEDFS_TPU_FILER_META_PLANE"] = \
                args.meta_plane
        workers = args.workers
        if workers is None:
            try:
                workers = int(os.environ.get(
                    "SEAWEEDFS_TPU_FILER_WORKERS", "") or 1)
            except ValueError:
                workers = 1
        is_worker = workers == 0          # spawned sibling (internal)
        if workers > 1 and store_type != "sqlite":
            wlog.warning("filer -workers needs the sqlite store "
                         "(shared WAL + metalog); running 1 process",
                         component="filer")
            workers = 1
        fs = FilerServer(args.master, args.ip, args.port,
                         store_path=store_path,
                         collection=args.collection,
                         replication=args.replication,
                         store_type=store_type,
                         notification=notification,
                         lock_peers=[p.strip() for p in
                                     args.lock_peers.split(",")
                                     if p.strip()],
                         reuse_port=is_worker or workers > 1)
        fs.start()
        worker_procs: list = []
        if is_worker:
            # exit when orphaned: the parent (or the harness that
            # killed it) is gone, so this listener must die too
            import threading as _threading

            def _orphan_watch(ppid: int = os.getppid()):
                while True:
                    time.sleep(1.0)
                    if os.getppid() != ppid:
                        os._exit(0)
            _threading.Thread(target=_orphan_watch,
                              daemon=True).start()
        elif workers > 1:
            # pre-fork: N-1 sibling processes re-exec this command on
            # the RESOLVED port with SO_REUSEPORT; the kernel spreads
            # connections across the workers' accept queues
            import subprocess as _subprocess
            # any ONE worker's /metrics scrape must report the whole
            # fleet's process-tree CPU/RSS (stats._proc_tree_sample):
            # siblings inherit this env and root their /proc walk at
            # the pre-fork parent instead of themselves
            os.environ["SEAWEEDFS_TPU_TREE_ROOT"] = str(os.getpid())
            argv = []
            skip = False
            for a in sys.argv[1:]:
                if skip:
                    skip = False
                    continue
                if a in ("-port", "-workers"):
                    skip = True
                    continue
                argv.append(a)
            argv += ["-port", str(fs.http.port), "-workers", "0"]
            for _ in range(workers - 1):
                worker_procs.append(_subprocess.Popen(
                    [sys.executable, "-m", "seaweedfs_tpu"] + argv))
            print(f"filer pre-forked {workers - 1} sibling workers "
                  f"on port {fs.http.port}")
            # monitor: a crashed worker is reaped, logged, and
            # respawned (bounded — a worker that cannot stay up must
            # not become a fork loop); without this the filer would
            # silently serve with fewer processes than -workers asked
            import threading as _threading
            respawns = [0]
            drained: "set[int]" = set()   # pids the autopilot drained
            # on purpose — the monitor must not resurrect them

            def _worker_monitor():
                while True:
                    time.sleep(2.0)
                    for i, wp in enumerate(worker_procs):
                        rc = wp.poll()
                        if rc is None or wp.pid in drained:
                            continue
                        wlog.warning(
                            f"filer worker pid={wp.pid} exited "
                            f"rc={rc}", component="filer")
                        if respawns[0] >= 20:
                            continue
                        respawns[0] += 1
                        worker_procs[i] = _subprocess.Popen(
                            [sys.executable, "-m", "seaweedfs_tpu"]
                            + argv)
            _threading.Thread(target=_worker_monitor,
                              daemon=True).start()
            # SLO autopilot "workers" actuator (autopilot.py, ISSUE
            # 20): only the pre-fork PARENT registers it — it owns
            # the sibling fleet — so a single-process filer can never
            # have workers conjured by a control rule.  Fleet size
            # counts the parent; bounds [1, 2x the requested size].
            ap = getattr(fs, "autopilot", None)
            if ap is not None:
                from .autopilot import Actuator
                _wlock = _threading.Lock()

                def _fleet_size() -> float:
                    with _wlock:
                        return 1.0 + sum(
                            1 for wp in worker_procs
                            if wp.poll() is None
                            and wp.pid not in drained)

                def _scale_fleet(n: float) -> None:
                    want = max(0, int(round(n)) - 1)
                    with _wlock:
                        live = [wp for wp in worker_procs
                                if wp.poll() is None
                                and wp.pid not in drained]
                        while len(live) < want:
                            wp = _subprocess.Popen(
                                [sys.executable, "-m",
                                 "seaweedfs_tpu"] + argv)
                            worker_procs.append(wp)
                            live.append(wp)
                        while len(live) > want:
                            wp = live.pop()
                            drained.add(wp.pid)
                            wp.terminate()

                ap.register(Actuator(
                    "workers", get=_fleet_size, set=_scale_fleet,
                    lo=1.0, hi=float(max(workers * 2, 2)),
                    cooldown=30.0,
                    describe="SO_REUSEPORT pre-fork filer "
                             "processes (parent included)"))
        if args.metrics_address:
            from .stats import MetricsPusher
            MetricsPusher(fs.metrics, "filer", fs.url,
                          args.metrics_address,
                          args.metrics_interval).start()
            print(f"pushing metrics to {args.metrics_address} "
                  f"every {args.metrics_interval}s")
        print(f"filer listening on {fs.url}")
        _wait()
    elif args.cmd == "s3":
        from .s3 import S3ApiServer
        from .filer import Filer
        from .filer.filer_store import SqliteStore
        creds = {args.accessKey: args.secretKey} if args.accessKey \
            else None
        iam_store = sts = kms = None
        if args.iam_config:
            from .iam import IdentityStore
            iam_store = IdentityStore(args.iam_config)
        if args.sts_key:
            from .iam import StsService
            from .iam.sts import RoleStore
            sts = StsService(args.sts_key,
                             RoleStore(args.roles_file or None))
        if args.kms_cloud:
            from .iam import kms_cloud
            kind, _, rest = args.kms_cloud.partition(":")
            parts = rest.split(",")
            ctor = {"gcp": kms_cloud.GcpKms,
                    "azure": kms_cloud.AzureKms,
                    "openbao": kms_cloud.OpenBaoKms}.get(kind)
            if ctor is None:
                print(f"unknown -kmsCloud provider {kind!r}",
                      file=sys.stderr)
                return 2
            kms = ctor(parts[0],
                       parts[1] if len(parts) > 1 else "",
                       token=parts[2] if len(parts) > 2 else "")
        elif args.kms_endpoint:
            from .iam.kms_aws import AwsKms
            parts = args.kms_endpoint.split(",")
            kms = AwsKms(parts[0],
                         parts[1] if len(parts) > 1 else "",
                         parts[2] if len(parts) > 2 else "",
                         parts[3] if len(parts) > 3 else "us-east-1")
        elif args.kms_file:
            from .iam.kms import LocalKms
            kms = LocalKms(args.kms_file)
        if args.filer:
            from .filer.client import FilerClient
            backend = FilerClient(args.filer)
        else:
            backend = Filer(args.master, SqliteStore(args.store))
        gw = S3ApiServer(backend, args.ip, args.port,
                         credentials=creds,
                         iam=iam_store, sts=sts, kms=kms,
                         metrics_port=args.metrics_port)
        gw.start()
        if args.metrics_address:
            from .stats import MetricsPusher
            MetricsPusher(gw.metrics, "s3", gw.url,
                          args.metrics_address,
                          args.metrics_interval).start()
            print(f"pushing metrics to {args.metrics_address} "
                  f"every {args.metrics_interval}s")
        print(f"s3 gateway listening on {gw.url}" +
              (f" (filer {args.filer})" if args.filer else "") +
              (f" (metrics {gw.metrics_http.url}/metrics)"
               if gw.metrics_http is not None else ""))
        _wait()
    elif args.cmd == "iam":
        from .iam import IdentityStore, StsService
        from .iam.iamapi import IamApiServer
        from .iam.sts import RoleStore
        store = IdentityStore(args.iam_config)
        sts = StsService(args.sts_key,
                         RoleStore(args.roles_file or None)) \
            if args.sts_key else None
        if args.oidc_config and sts is None:
            p.error("-oidcConfig requires -stsKey (web identities "
                    "mint STS credentials)")
        if sts is not None and args.oidc_config:
            import json as _json
            from .iam.oidc import OidcProvider
            with open(args.oidc_config) as f:
                for cfg in _json.load(f):
                    pems = []
                    if cfg.get("rsaPublicKeyFile"):
                        with open(cfg["rsaPublicKeyFile"],
                                  "rb") as kf:
                            pems.append(kf.read())
                    sts.add_provider(OidcProvider(
                        cfg["name"], cfg["issuer"],
                        cfg.get("audience", ""),
                        rsa_public_keys_pem=pems,
                        hs256_secret=cfg.get("hs256Secret", "")))
                    print(f"oidc provider {cfg['name']} "
                          f"({cfg['issuer']})")
        srv = IamApiServer(store, sts, args.ip, args.port).start()
        print(f"iam api on {srv.url}")
        _wait()
    elif args.cmd == "admin":
        from .plugin.admin import AdminServer
        ad = AdminServer(args.master, args.ip, args.port,
                         detection_interval=args.detectionInterval,
                         data_dir=args.dataDir or None)
        ad.start()
        print(f"admin listening on {ad.url}")
        _wait()
    elif args.cmd == "worker":
        from .plugin.handlers import (EcBalanceHandler,
                                      EcEncodeHandler,
                                      EcRebuildHandler,
                                      VacuumHandler,
                                      VolumeBalanceHandler)
        from .plugin.worker import PluginWorker
        handlers = []
        caps = args.capabilities.split(",")
        if "erasure_coding" in caps or "ec" in caps:
            handlers.append(EcEncodeHandler(
                backend=args.backend or None))
        if "erasure_coding" in caps or "ec" in caps or \
                "ec_rebuild" in caps:
            handlers.append(EcRebuildHandler())
        if "vacuum" in caps:
            handlers.append(VacuumHandler())
        if "volume_balance" in caps or "balance" in caps:
            handlers.append(VolumeBalanceHandler())
        if "ec_balance" in caps:
            handlers.append(EcBalanceHandler())
        w = PluginWorker(args.admin, args.master, args.dir, handlers)
        w.start()
        print(f"worker {w.worker_id} polling {args.admin}")
        _wait()
    elif args.cmd == "webdav":
        # attach to the RUNNING filer's namespace (the reference's
        # weed webdav -filer), not a private store
        from .filer.client import FilerClient
        from .server.webdav_server import WebDavServer
        dav = WebDavServer("", FilerClient(args.filer), args.ip,
                           args.port).start()
        print(f"webdav on {dav.url} serving filer {args.filer}")
        _wait()
    elif args.cmd == "mount":
        from .mount.fuse_ctypes import mount as fuse_mount
        print(f"mounting filer {args.filer} at {args.dir} (read-only)")
        return fuse_mount(args.filer, args.dir)
    elif args.cmd == "mq.broker":
        import signal
        from .mq import BrokerServer
        br = BrokerServer(args.filer, args.ip, args.port).start()
        # graceful SIGTERM: drain hot buffers to the filer before exit
        signal.signal(signal.SIGTERM,
                      lambda *_: (br.stop(), sys.exit(0)))
        print(f"mq broker on {br.url} (filer {args.filer})")
        try:
            _wait()
        finally:
            br.stop()
    elif args.cmd == "mq.agent":
        from .mq.agent import AgentServer
        ag = AgentServer(args.broker, args.ip, args.port).start()
        print(f"mq agent on {ag.url} -> broker {args.broker}")
        _wait()
    elif args.cmd == "mq.kafka":
        from .mq.kafka_gateway import KafkaGateway
        users = None
        if args.users:
            entries = [u for u in args.users.split(",") if u]
            bad = [u for u in entries if ":" not in u]
            if bad or not entries:
                # an operator who ASKED for auth must never get an
                # open gateway because of a typo'd separator
                p.error(f"-users: malformed credential(s) "
                        f"{bad or args.users!r} (want user:pass"
                        f"[,user2:pass2])")
            users = dict(u.split(":", 1) for u in entries)
        gw = KafkaGateway(args.broker, args.ip, args.port,
                          users=users).start()
        print(f"kafka gateway on {args.ip}:{gw.port} over broker "
              f"{args.broker}" +
              (" (SASL/PLAIN required)" if users else ""))
        _wait()
    elif args.cmd == "filer.sync":
        from .filer.filer_sync import FilerSync
        syncer = FilerSync(args.sync_from, args.sync_to,
                           args.state or None,
                           poll_interval=args.interval)
        print(f"filer.sync {args.sync_from} -> {args.sync_to} "
              f"(offset state: {syncer.state_path})")
        try:
            syncer.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.backup.s3":
        from .filer.s3_sink import S3Sink
        sink = S3Sink(args.filer, args.endpoint, args.bucket,
                      args.access_key, args.secret_key, args.prefix,
                      args.state or None, poll_interval=args.interval)
        print(f"filer.backup.s3 {args.filer} -> "
              f"{args.endpoint}/{args.bucket}/{args.prefix} "
              f"(offset state: {sink.state_path})")
        try:
            sink.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.backup.gcs":
        from .filer.cloud_sinks import GcsSink
        sink = GcsSink(args.filer, args.bucket, args.endpoint,
                       args.token, args.prefix, args.state or None,
                       poll_interval=args.interval)
        print(f"filer.backup.gcs {args.filer} -> "
              f"{args.endpoint}/{args.bucket}/{args.prefix}")
        try:
            sink.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.backup.azure":
        from .filer.cloud_sinks import AzureSink
        sink = AzureSink(args.filer, args.account, args.account_key,
                         args.container, args.endpoint, args.prefix,
                         args.state or None,
                         poll_interval=args.interval)
        print(f"filer.backup.azure {args.filer} -> "
              f"{sink.endpoint}/{args.container}/{args.prefix}")
        try:
            sink.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.backup.b2":
        from .filer.cloud_sinks import B2Sink
        sink = B2Sink(args.filer, args.key_id, args.app_key,
                      args.bucket, endpoint=args.endpoint,
                      key_prefix=args.prefix,
                      state_path=args.state or None,
                      poll_interval=args.interval)
        print(f"filer.backup.b2 {args.filer} -> b2://{args.bucket}/"
              f"{args.prefix}")
        try:
            sink.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.backup":
        from .filer.filer_backup import FilerBackup
        bak = FilerBackup(args.filer, args.dir, args.state or None,
                          poll_interval=args.interval)
        print(f"filer.backup {args.filer} -> {args.dir} "
              f"(offset state: {bak.state_path})")
        try:
            bak.run()
        except KeyboardInterrupt:
            pass
    elif args.cmd == "filer.remote.sync":
        from .remote import RemoteSyncer
        syncer = RemoteSyncer(args.filer, args.dir,
                              args.state or None,
                              args.interval).start()
        print(f"remote-syncing {args.dir} on {args.filer}")
        try:
            _wait()
        finally:
            syncer.stop()
    elif args.cmd == "sftp":
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric.ed25519 import (
            Ed25519PrivateKey)
        from .filer.client import FilerClient
        from .sftp import SftpService, UserStore
        key = None
        if args.host_key:
            if os.path.exists(args.host_key):
                with open(args.host_key, "rb") as f:
                    key = serialization.load_pem_private_key(
                        f.read(), password=None)
            else:
                key = Ed25519PrivateKey.generate()
                with open(args.host_key, "wb") as f:
                    f.write(key.private_bytes(
                        serialization.Encoding.PEM,
                        serialization.PrivateFormat.PKCS8,
                        serialization.NoEncryption()))
        ldap = None
        if args.ldap_server:
            from .iam.ldap import LdapProvider
            host, _, port = args.ldap_server.partition(":")
            default_port = 636 if args.ldap_tls else 389
            ldap = LdapProvider(
                host, int(port or default_port),
                base_dn=args.ldap_base_dn,
                user_dn_template=args.ldap_dn_template,
                bind_dn=args.ldap_bind_dn,
                bind_password=args.ldap_bind_password,
                use_tls=args.ldap_tls)
        svc = SftpService(
            FilerClient(args.filer), UserStore(args.user_store),
            host_key=key, port=args.port,
            auth_methods=tuple(args.auth_methods.split(",")),
            banner=args.banner, ldap=ldap).start()
        print(f"sftp on {args.ip}:{svc.port} serving filer "
              f"{args.filer}")
        _wait()
    elif args.cmd == "sftp.user":
        from .sftp import User, UserStore
        store = UserStore(args.store)
        if args.action == "list":
            for u in store:
                print(f"{u.username} home={u.home_dir} "
                      f"keys={len(u.public_keys)} "
                      f"perms={u.permissions}")
        elif args.action == "delete":
            store.delete(args.name)
            print(f"deleted {args.name}")
        else:
            u = store.get(args.name) or User(args.name, args.home)
            if args.home:
                u.home_dir = args.home
            if args.password:
                u.set_password(args.password)
            if args.pubkey:
                u.add_public_key(args.pubkey)
            for spec in args.perm:
                path, _, perms = spec.partition(":")
                u.permissions[path] = perms.split(",")
            store.put(u)
            print(f"saved {u.username}")
    elif args.cmd == "shell":
        from .shell import CommandEnv, run_command
        env = CommandEnv(args.master, filer=args.filer)
        if args.command:
            # ';'-separated sequences share one env (so `lock;
            # volume.move ...; unlock` works as a one-shot)
            for one in " ".join(args.command).split(";"):
                if one.strip():
                    print(run_command(env, one.strip()))
            return 0
        _repl(env)
    elif args.cmd == "benchmark":
        import json as _json
        from .benchmark import run_benchmark
        for r in run_benchmark(args.master, args.n, args.size, args.c):
            print(_json.dumps(r))
    elif args.cmd == "cert":
        from .tls import generate_cluster_certs
        paths = generate_cluster_certs(
            args.dir, [h.strip() for h in args.hosts.split(",")
                       if h.strip()])
        print(f"wrote {paths['ca']}, {paths['cert']}, {paths['key']}")
        print("enable via security.toml:\n[tls]\n"
              f'ca = "{paths["ca"]}"\ncert = "{paths["cert"]}"\n'
              f'key = "{paths["key"]}"\nmtls = true')
    elif args.cmd == "scaffold" and args.config == "filer":
        # command/scaffold/filer.toml shape (util/config.py
        # filer_store_from_toml reads the enabled section)
        print("""\
# filer.toml — place in ./, ~/.seaweedfs/, or /etc/seaweedfs/
# the first ENABLED section picks the filer's metadata store
# (command/scaffold/filer.toml layout; archetype mapping in
# seaweedfs_tpu/util/config.py)

[sqlite]
enabled = true
dbFile = "filer.db"           # or ":memory:"

[leveldb2]
# embedded ordered-KV (our LSM store — the reference's default)
enabled = false
dir = "./filerldb2"

[redis2]
# any RESP2 server (hand-rolled client, filer/redis_store.py)
enabled = false
address = "localhost:6379"

[elastic7]
# any ES-wire JSON-HTTP server (filer/elastic_store.py)
enabled = false
servers = ["http://localhost:9200"]""")
    elif args.cmd == "scaffold" and args.config == "notification":
        print("""\
# notification.toml — metadata-event publishing
# (command/scaffold/notification.toml layout; the first enabled
# sink becomes the filer's -notification spec)

[notification.webhook]
enabled = false
url = "http://localhost:9000/events"

[notification.kafka]
enabled = false
hosts = ["localhost:9092"]
topic = "seaweedfs_meta"

[notification.log]
enabled = false
path = "filer_events.log"

[notification.mq]
enabled = false
broker = "localhost:17777"
namespace = "notifications"
topic = "filer_meta"\
""")
    elif args.cmd == "scaffold" and args.config == "replication":
        print("""\
# replication.toml — filer.backup sink selection
# (command/scaffold/replication.toml layout; the first enabled
# [sink.*] section drives filer.backup)

[sink.local]
enabled = false
directory = "/backup"

[sink.s3]
enabled = false
endpoint = "localhost:8333"
bucket = "backup"
aws_access_key_id = ""
aws_secret_access_key = ""

[sink.gcs]
enabled = false
bucket = "backup"

[sink.azure]
enabled = false
container = "backup"

[sink.backblaze]
enabled = false
bucket = "backup"\
""")
    elif args.cmd == "scaffold":
        # command/scaffold/security.toml layout (keys match
        # util/config.go:34 LoadSecurityConfiguration)
        print("""\
# security.toml — place beside the binary or pass -securityToml
# (command/scaffold/security.toml layout)

[jwt.signing]
# per-fid write tokens minted by the master on assign
key = ""
expires_after_seconds = 10

[jwt.signing.read]
# optional read-token gate on the volume data path
key = ""
expires_after_seconds = 10

[admin]
# admin-plane key: guards /admin/*, raft, heartbeat, grow, lock
key = ""

[access]
# CIDR whitelist for unauthenticated access (empty = no whitelist)
white_list = []

# [tls]
# cluster-wide TLS/mTLS (security/tls.go; mint a PKI with
# `python -m seaweedfs_tpu cert -dir certs`)
# ca = "certs/ca.crt"
# cert = "certs/node.crt"
# key = "certs/node.key"
# mtls = true

# [qos]
# per-tenant admission + background EC throttle (qos.py); runtime
# lever: POST /debug/qos on any role
# enabled = true
# slo_p99_ms = 200          # foreground p99 SLO for the EC throttle
# [qos.default]             # any tenant without an override
# rps = 200
# burst = 400
# inflight_mb = 64
# [qos.tenants.AKIDEXAMPLE] # per-access-key override
# rps = 10
# burst = 10""")
    elif args.cmd == "upload":
        from . import operation
        with open(args.file, "rb") as f:
            data = f.read()
        fid = operation.submit(args.master, data, name=args.file)
        print(fid)
    elif args.cmd == "analyze":
        from .devtools.analyze import run_cli
        return run_cli(args.paths, json_out=args.json_out,
                       baseline_path=args.baseline,
                       write_baseline=args.write_baseline,
                       no_baseline=args.no_baseline,
                       rule_ids=args.rules)
    elif args.cmd == "version":
        from . import __version__
        print(f"seaweedfs-tpu {__version__} "
              f"(python {sys.version.split()[0]})")
    elif args.cmd == "filer.meta.tail":
        # command/filer_meta_tail.go: follow the metadata log from a
        # timestamp, one JSON event per line; -once drains and exits
        import json as _json

        from .server.httpd import http_json
        since = args.since_ns
        if since == 0 and not args.once:
            import time as _t
            since = _t.time_ns()          # "now": only new events
        try:
            while True:
                try:
                    r = http_json(
                        "GET", f"{args.filer}/__meta__/events?"
                               f"sinceNs={since}&limit=1000")
                except OSError as e:
                    # follow mode must survive a filer restart /
                    # network blip (FilerSync retries the same way);
                    # -once surfaces the failure instead
                    if args.once:
                        print(f"filer.meta.tail: {e}",
                              file=sys.stderr)
                        return 1
                    print(f"filer.meta.tail: {e}; retrying",
                          file=sys.stderr)
                    time.sleep(args.interval)
                    continue
                if "error" in r:
                    # a 401/404 must not read as "log is empty"
                    print(f"filer.meta.tail: {r['error']}",
                          file=sys.stderr)
                    return 1
                for ev in r.get("events", []):
                    path = (ev.get("newEntry") or
                            ev.get("oldEntry") or {}).get(
                                "fullPath", "")
                    if args.path_prefix and \
                            not path.startswith(args.path_prefix):
                        since = max(since, int(ev.get("tsNs", 0)))
                        continue
                    print(_json.dumps(ev), flush=True)
                    since = max(since, int(ev.get("tsNs", 0)))
                if args.once and len(r.get("events", [])) < 1000:
                    break
                if len(r.get("events", [])) < 1000:
                    time.sleep(args.interval)
        except KeyboardInterrupt:
            pass
    elif args.cmd == "fix":
        # command/fix.go: replay the .dat sequentially into a fresh
        # .idx (writes -> put, tombstones -> delete-row), exactly the
        # recovery the reference runs on index corruption
        import os as _os

        from .storage import idx as idxmod
        from .storage import types as stypes
        from .storage.volume import walk_dat
        dat = _offline_vol_path(args, ".dat")
        idx_path = _offline_vol_path(args, ".idx")
        if not _os.path.exists(dat):
            print(f"no {dat}", file=sys.stderr)
            return 1
        tmp = idx_path + ".fix"
        n_writes = n_dels = 0
        with open(tmp, "wb") as f:
            for needle, off in walk_dat(dat):
                if needle.data:
                    f.write(idxmod.entry_bytes(
                        needle.id, stypes.to_stored_offset(off),
                        needle.size))
                    n_writes += 1
                else:
                    f.write(idxmod.entry_bytes(
                        needle.id, 0, stypes.TOMBSTONE_FILE_SIZE))
                    n_dels += 1
        _os.replace(tmp, idx_path)
        print(f"fixed {idx_path}: {n_writes} writes, "
              f"{n_dels} tombstones")
    elif args.cmd == "compact":
        # command/compact.go: offline shadow-compact + commit on an
        # unmounted volume
        import os as _os

        from .storage.volume import Volume
        if not _os.path.exists(_offline_vol_path(args, ".dat")):
            # Volume() would CREATE an empty volume here — a typo'd
            # id must fail, not mint stray files the server later
            # serves as a real volume
            print(f"no {_offline_vol_path(args, '.dat')}",
                  file=sys.stderr)
            return 1
        v = Volume(args.dir, args.volume_id,
                   collection=args.collection)
        before = v.dat_size()
        garbage = v.garbage_level()
        v.vacuum()
        after = v.dat_size()
        v.close()
        print(f"compacted volume {args.volume_id}: {before} -> "
              f"{after} bytes (garbage was {garbage:.0%})")
    elif args.cmd == "export":
        # command/export.go: list live needles, or tar their payloads
        # (member names <key-hex>[_<name>])
        import os as _os
        import tarfile

        from .storage.volume import Volume
        if not _os.path.exists(_offline_vol_path(args, ".dat")):
            print(f"no {_offline_vol_path(args, '.dat')}",
                  file=sys.stderr)
            return 1
        v = Volume(args.dir, args.volume_id,
                   collection=args.collection)
        entries = sorted(v.nm.items())
        tar = tarfile.open(args.out, "w") if args.out else None
        count = 0
        for key, stored_off, size in entries:
            n = v._read_at(stored_off, size)
            fname = f"{key:x}"
            if n.has_name():
                fname += "_" + n.name.decode("utf-8", "replace")
            if tar is None:
                mime = n.mime.decode("utf-8", "replace") \
                    if n.has_mime() else "-"
                print(f"{fname}\t{len(n.data)}\t{mime}")
            else:
                import io as _io
                info = tarfile.TarInfo(fname)
                info.size = len(n.data)
                info.mtime = n.last_modified or 0
                tar.addfile(info, _io.BytesIO(n.data))
            count += 1
        if tar is not None:
            tar.close()
            print(f"exported {count} files to {args.out}")
        else:
            print(f"{count} live files in volume {args.volume_id}")
        v.close()
    elif args.cmd == "download":
        from . import operation
        sys.stdout.buffer.write(operation.read(args.master, args.fid))
    return 0


def _offline_vol_path(args, ext: str) -> str:
    """<dir>/<collection_>_?<vid><ext> — the volume.file_name naming
    rule, shared by the offline fix/compact/export tools."""
    import os as _os
    name = (f"{args.collection}_" if args.collection else "") + \
        f"{args.volume_id}{ext}"
    return _os.path.join(args.dir, name)


def _repl(env) -> None:
    from .shell import run_command
    while True:
        try:
            line = input("> ").strip()
        except (EOFError, KeyboardInterrupt):
            break
        if line in ("exit", "quit"):
            break
        if not line:
            continue
        try:
            print(run_command(env, line))
        except Exception as e:  # noqa: BLE001 — REPL must survive
            print(f"error: {e}")


def _wait() -> None:
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    sys.exit(main())
