"""QoS plane: per-tenant admission control + foreground/background
priority.

The north star is sustained mixed traffic from many tenants, and
arXiv:1709.05365's core finding is that background EC maintenance
disproportionately hurts foreground tail latency in online-EC stores.
The SOSP Cake/Retro line gives the standard remedy shape: per-tenant
token buckets at the front door plus feedback throttling of background
work off a foreground latency signal.  This module is both halves:

* **AdmissionController** — per-tenant token buckets enforced as httpd
  middleware at the S3 gateway, the filer, and the volume admin plane.
  A tenant is the S3 access key (parsed from the SigV4 `Credential=`),
  the bearer principal on the admin plane, an explicit `X-Tenant` tag
  (internal load tools), or `anonymous`.  Two dimensions per tenant:
  request rate (req/s with a burst ceiling) and in-flight request
  bytes (Content-Length summed over admitted, unfinished requests).
  Over-limit requests are REJECTED with 503 + `Retry-After` — bounded
  backpressure at the edge, never an unbounded server-side queue.

* **FeedbackThrottle** — the background/foreground priority tier.  A
  watcher samples each registered role's `request_seconds` histogram
  (PR 3's uniform middleware metric), computes the p99 of the traffic
  that arrived since the last sample, and compares it to the
  configured SLO.  While foreground p99 is over the SLO the throttle
  doubles an inter-window pace (up to a cap) that the EC pipelines
  consult per window — `ShardSink` pushes and `ShardSource` slice
  fetches — so encode/rebuild degrade to a trickle instead of
  competing with user traffic; when p99 recovers the pace halves back
  to zero.

Configuration comes from a `[qos]` section in the same TOML file as
security.toml (see `load_qos_toml`) and can be changed at runtime via
`POST /debug/qos` on any role (server/debug.py).  Unconfigured, the
whole plane is inert: admission admits everything without touching a
bucket and `ec_pace` is a no-op.

Env knobs (all optional; TOML/runtime win over env):

  SEAWEEDFS_TPU_QOS_SLO_P99_MS        foreground p99 SLO (0 = off)
  SEAWEEDFS_TPU_QOS_CHECK_MS          throttle sample interval (1000)
  SEAWEEDFS_TPU_QOS_PACE_MIN_MS       first downshift pace (25)
  SEAWEEDFS_TPU_QOS_PACE_MAX_MS      pace ceiling / "paused" (2000)

Observability: `qos_admitted_total{tenant,role}`,
`qos_rejected_total{tenant,role,reason}`, `qos_inflight_bytes{tenant}`,
`qos_ec_pace_ms`, `qos_ec_paced_total{kind}` and
`qos_foreground_p99_seconds` in the shared stats.PROCESS registry every
role's /metrics appends.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field

from .util.hedge import LatencyTracker as _LatencyTracker


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


# -- configuration ---------------------------------------------------------

@dataclass
class TenantLimit:
    """Limits for one tenant (0 = unlimited on that dimension)."""

    rps: float = 0.0            # sustained request rate
    burst: float = 0.0          # bucket depth; defaults to max(rps, 1)
    inflight_mb: float = 0.0    # concurrent request payload bytes

    def to_json(self) -> dict:
        return {"rps": self.rps, "burst": self.burst,
                "inflightMb": self.inflight_mb}

    @classmethod
    def from_json(cls, d: dict) -> "TenantLimit":
        lim = cls(rps=float(d.get("rps", 0.0)),
                  burst=float(d.get("burst", 0.0)),
                  inflight_mb=float(d.get("inflightMb",
                                          d.get("inflight_mb", 0.0))))
        if lim.rps < 0 or lim.burst < 0 or lim.inflight_mb < 0:
            # same fail-loud contract as load_qos_toml: a sign slip in
            # a runtime lever call must 400, not silently run the
            # tenant unlimited (TokenBucket clamps negatives to the
            # unlimited dimension)
            raise ValueError("qos limits must be >= 0")
        return lim


@dataclass
class QosConfig:
    """The `[qos]` TOML surface + runtime lever state."""

    enabled: bool = False
    default: "TenantLimit | None" = None      # applies to any tenant
    tenants: dict = field(default_factory=dict)  # name -> TenantLimit
    slo_p99_ms: float = 0.0                   # 0 = throttle off
    check_interval_ms: float = 1000.0
    pace_min_ms: float = 25.0
    pace_max_ms: float = 2000.0

    def limit_for(self, tenant: str) -> "TenantLimit | None":
        return self.tenants.get(tenant) or self.default

    def to_json(self) -> dict:
        return {
            "enabled": self.enabled,
            "default": self.default.to_json() if self.default else None,
            "tenants": {t: lim.to_json()
                        for t, lim in sorted(self.tenants.items())},
            "sloP99Ms": self.slo_p99_ms,
            "checkIntervalMs": self.check_interval_ms,
            "paceMinMs": self.pace_min_ms,
            "paceMaxMs": self.pace_max_ms,
        }


def load_qos_toml(path: str) -> "QosConfig | None":
    """Parse the `[qos]` section of a security.toml-style file:

        [qos]
        enabled = true
        slo_p99_ms = 200          # foreground SLO for the EC throttle
        [qos.default]             # any tenant without an override
        rps = 200
        burst = 400
        inflight_mb = 64
        [qos.tenants.noisy]       # per-tenant override (access key /
        rps = 10                  # principal name)
        burst = 10

    Returns None when the file has no [qos] section (callers keep the
    process default).  Malformed limits raise ValueError — a typo'd
    QoS config must fail at boot, not silently run unlimited."""
    try:
        import tomllib
    except ModuleNotFoundError:      # py<3.11: the tomli backport
        import tomli as tomllib
    with open(path, "rb") as f:
        t = tomllib.load(f)
    q = t.get("qos")
    if not q:
        return None

    def _limit(d: dict, where: str) -> TenantLimit:
        lim = TenantLimit(rps=float(d.get("rps", 0.0)),
                          burst=float(d.get("burst", 0.0)),
                          inflight_mb=float(d.get("inflight_mb", 0.0)))
        if lim.rps < 0 or lim.burst < 0 or lim.inflight_mb < 0:
            raise ValueError(f"[qos] {where}: limits must be >= 0")
        return lim

    cfg = QosConfig(
        enabled=bool(q.get("enabled", True)),
        slo_p99_ms=float(q.get("slo_p99_ms", 0.0)),
        check_interval_ms=float(q.get("check_interval_ms", 1000.0)),
        pace_min_ms=float(q.get("pace_min_ms", 25.0)),
        pace_max_ms=float(q.get("pace_max_ms", 2000.0)),
    )
    if q.get("default"):
        cfg.default = _limit(q["default"], "default")
    for name, d in (q.get("tenants") or {}).items():
        cfg.tenants[str(name)] = _limit(d, f"tenants.{name}")
    return cfg


# -- token bucket ----------------------------------------------------------

class TokenBucket:
    """Monotonic-clock token bucket.  `try_take` never blocks: it
    returns 0.0 on success or the seconds until enough tokens refill —
    the `Retry-After` the rejection carries, so a well-behaved client
    retries exactly when a token exists instead of hammering."""

    def __init__(self, rate: float, burst: float):
        # configured values kept verbatim: the admission controller
        # compares THESE against the live TenantLimit to decide
        # whether the bucket is stale — comparing the clamped values
        # would recreate the bucket (full of tokens) on every admit
        # for any config the clamp rewrites, e.g. burst in (0, 1)
        self.cfg_rate = float(rate)
        self.cfg_burst = float(burst)
        self.rate = max(self.cfg_rate, 0.0)
        self.burst = max(self.cfg_burst or max(self.rate, 1.0), 1.0)
        self._tokens = self.burst
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> float:
        if self.rate <= 0:
            return 0.0               # unlimited dimension
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._stamp)
                               * self.rate)
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return 0.0
            return (n - self._tokens) / self.rate


# -- admission controller --------------------------------------------------

class RejectInfo:
    """One admission verdict: why + when to retry."""

    __slots__ = ("reason", "retry_after")

    def __init__(self, reason: str, retry_after: float):
        self.reason = reason
        self.retry_after = max(retry_after, 0.0)


class AdmissionController:
    """Per-tenant rate + in-flight-bytes admission.  One instance per
    process (module singleton below), shared by every role's listener
    — a tenant hammering the S3 gateway spends the same bucket its
    filer traffic does."""

    def __init__(self, config: "QosConfig | None" = None):
        self._lock = threading.Lock()
        self._config = config or QosConfig()
        self._buckets: dict[str, TokenBucket] = {}
        self._inflight: dict[str, int] = {}

    # -- config ------------------------------------------------------

    def configure(self, config: QosConfig) -> None:
        with self._lock:
            self._config = config
            self._buckets.clear()    # new rates take effect at once

    def config(self) -> QosConfig:
        with self._lock:
            return self._config

    def set_tenant(self, tenant: str,
                   limit: "TenantLimit | None") -> None:
        """Runtime lever: install/replace (or remove, with None) one
        tenant's limits.  `default` / `*` targets the default limit."""
        with self._lock:
            if tenant in ("default", "*"):
                self._config.default = limit
            elif limit is None:
                self._config.tenants.pop(tenant, None)
            else:
                self._config.tenants[tenant] = limit
            self._buckets.pop(tenant, None)
            if limit is not None:
                self._config.enabled = True

    def set_enabled(self, enabled: bool) -> None:
        with self._lock:
            self._config.enabled = bool(enabled)

    # -- admission ---------------------------------------------------

    def admit(self, tenant: str, nbytes: int = 0):
        """Returns (release, reject).  reject is None when admitted;
        release is a zero-arg callable the server runs when the
        request finishes (always callable, possibly a no-op)."""
        with self._lock:
            cfg = self._config
            if not cfg.enabled:
                return _NOOP, None
            limit = cfg.limit_for(tenant)
            if limit is None:
                return _NOOP, None
            bucket = self._buckets.get(tenant)
            if bucket is None or bucket.cfg_rate != limit.rps or \
                    bucket.cfg_burst != limit.burst:
                bucket = self._buckets[tenant] = TokenBucket(
                    limit.rps, limit.burst)
            max_bytes = int(limit.inflight_mb * (1 << 20))
            cur = self._inflight.get(tenant, 0)
            if max_bytes and nbytes > 0 and \
                    cur + nbytes > max_bytes:
                # in-flight bytes over the cap: Retry-After is a hint
                # (completion, not refill, frees bytes) — 1s keeps
                # well-behaved clients from busy-looping
                return _NOOP, RejectInfo("inflight_bytes", 1.0)
            wait = bucket.try_take(1.0)
            if wait > 0.0:
                return _NOOP, RejectInfo("rate", wait)
            if nbytes > 0:
                release = self._reserve_locked(tenant, nbytes)
                _gauge_inflight(tenant, cur + nbytes)
                return release, None
            return _NOOP, None

    def _reserve_locked(self, tenant: str, nbytes: int):
        """Record `nbytes` in flight (caller holds self._lock) and
        return the idempotent release closure — the ONE copy of the
        reservation bookkeeping shared by admit (request bodies) and
        admit_bytes (response bodies)."""
        self._inflight[tenant] = \
            self._inflight.get(tenant, 0) + nbytes
        released = [False]

        def release():
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                left = self._inflight.get(tenant, 0) - nbytes
                if left > 0:
                    self._inflight[tenant] = left
                else:
                    self._inflight.pop(tenant, None)
            _gauge_inflight(tenant, self.inflight_of(tenant))
        return release

    def admit_bytes(self, tenant: str, nbytes: int):
        """In-flight-bytes-only admission for RESPONSE payloads (the
        read path's half of the accounting: admission at the edge
        meters request bodies via Content-Length, but a GET carries
        its bytes in the RESPONSE — a hot-cache stampede would
        otherwise ride the rate bucket alone and evade the byte
        dimension entirely).  No rate token is spent: the request
        already paid one at admission.  Returns (release, reject)."""
        with self._lock:
            cfg = self._config
            if not cfg.enabled or nbytes <= 0:
                return _NOOP, None
            limit = cfg.limit_for(tenant)
            if limit is None or not limit.inflight_mb:
                return _NOOP, None
            max_bytes = int(limit.inflight_mb * (1 << 20))
            cur = self._inflight.get(tenant, 0)
            if cur + nbytes > max_bytes:
                return _NOOP, RejectInfo("inflight_bytes", 1.0)
            release = self._reserve_locked(tenant, nbytes)
        _gauge_inflight(tenant, cur + nbytes)
        return release, None

    def inflight_of(self, tenant: str) -> int:
        with self._lock:
            return self._inflight.get(tenant, 0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"config": self._config.to_json(),
                    "inflightBytes": dict(self._inflight)}


def _NOOP() -> None:
    return None


def _gauge_inflight(tenant: str, value: int) -> None:
    from . import stats
    stats.PROCESS.gauge_set(
        "qos_inflight_bytes", float(value),
        help_text="admitted request bytes still in flight",
        tenant=tenant)


# -- tenant extraction -----------------------------------------------------

def tenant_of(req) -> str:
    """Best-effort tenant identity for accounting/limiting — NOT an
    authentication verdict (the gateway's SigV4/JWT verification still
    decides access; a forged access key here only burns the forger's
    chosen bucket).  Order: SigV4 access key (header then presigned
    query), explicit X-Tenant tag, bearer-JWT principal, anonymous."""
    auth = req.headers.get("Authorization", "") or ""
    if auth.startswith("AWS4-HMAC-SHA256"):
        # "AWS4-HMAC-SHA256 Credential=AK/date/region/s3/aws4_request,
        #  SignedHeaders=..., Signature=..."
        i = auth.find("Credential=")
        if i >= 0:
            ak = auth[i + len("Credential="):].split("/", 1)[0]
            ak = ak.split(",", 1)[0].strip()
            if ak:
                return ak
    cred = req.query.get("X-Amz-Credential", "")
    if cred:
        ak = cred.split("/", 1)[0].strip()
        if ak:
            return ak
    tag = req.headers.get("X-Tenant", "")
    if tag:
        return tag[:64]
    if auth[:7].upper() == "BEARER ":
        # decode (NOT verify) the claims for an accounting identity;
        # signature checks stay with the role's guard
        try:
            import base64
            import json as _json
            payload = auth[7:].split(".")[1]
            claims = _json.loads(base64.urlsafe_b64decode(
                payload + "=" * (-len(payload) % 4)))
            if claims.get("admin"):
                return "admin"
            who = claims.get("principal") or claims.get("sub") or ""
            if who:
                return str(who)[:64]
        except (ValueError, IndexError, TypeError):
            pass
    return "anonymous"


# -- brownout shedding (the deadline plane's admission hook) ---------------
#
# A request that arrives with less budget than this server currently
# needs to serve anything is already lost: admitting it spends a
# handler thread, store reads and downstream hops on work the client
# will have abandoned by the time the response is written.  Admission
# therefore consults the arriving request's deadline (util/deadline)
# against the MEDIAN of recent request service latencies — the
# "current queue latency" signal, fed by the release callback
# admission already hands the server fronts — and sheds unmeetable
# work with 503 + Retry-After (reason "brownout") BEFORE a rate token
# or byte reservation is spent.  Only deadline-carrying requests can
# brown out; everything else is admitted exactly as before.
#
# A windowed median, not a mean/EWMA: the release samples cover the
# response write, so one front serves a MIX of millisecond point
# requests and multi-second bulk transfers, and a mean would let a
# minority of bulk samples shed fast deadline-carrying reads that
# would comfortably finish.  The median only moves once bulk traffic
# is the MAJORITY of the window — at which point a small-budget
# request genuinely faces that queue.  (A mostly-bulk front that also
# serves point reads is still mis-estimated; `_FACTOR` tunes the
# sensitivity down and `BROWNOUT=0` is the kill switch.)
#
#   SEAWEEDFS_TPU_BROWNOUT=0         kill switch (default on)
#   SEAWEEDFS_TPU_BROWNOUT_FACTOR    shed when remaining < median * f
#                                    (default 1.0)

# the same ring-quantile the hedge threshold runs on (one
# implementation to tune), window 64 / warmup 20 / q=0.5
_brownout_tracker = _LatencyTracker(size=64, min_samples=20)


def brownout_enabled() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_BROWNOUT", "1") \
        not in ("0", "false")


# runtime override (SLO autopilot, ISSUE 20): the env var stays the
# operator baseline; the autopilot steers around it.  An
# autopilot-controlled knob — mutate only through the actuator
# registry (devtools rule SWFS021).
_brownout_factor_override: "float | None" = None


def set_brownout_factor(f: "float | None") -> None:
    global _brownout_factor_override
    _brownout_factor_override = None if f is None else max(0.0,
                                                           float(f))


def effective_brownout_factor() -> float:
    return _brownout_factor()


def _brownout_factor() -> float:
    if _brownout_factor_override is not None:
        return _brownout_factor_override
    return max(0.0, _env_float("SEAWEEDFS_TPU_BROWNOUT_FACTOR", 1.0))


def note_latency(seconds: float) -> None:
    """Feed one completed request's service latency into the brownout
    estimator (called from the admission release path — covers
    handler + response write)."""
    _brownout_tracker.note(seconds)


def brownout_estimate() -> float:
    """Expected service latency for a request admitted NOW (windowed
    median; the sort costs 64 floats and only runs for
    deadline-carrying arrivals); 0.0 until enough traffic has been
    seen to estimate anything (a cold server must not shed its first
    requests on noise)."""
    return _brownout_tracker.quantile(0.5) or 0.0


def _brownout_reset() -> None:
    _brownout_tracker.reset()


# exempt from admission on every role: the observability/debug plane
# must stay reachable from a throttled cluster (the runtime QoS lever
# itself rides /debug), and /status is every poller's liveness probe
_EXEMPT_PREFIXES = ("/debug/", "/metrics", "/status", "/healthz")


def install(http, role: str, path_prefix: str = "") -> None:
    """Wire admission into one listener as httpd middleware (the
    `HttpServer.admission` hook).  `path_prefix` scopes enforcement
    (the volume server passes "/admin/" so the tenant plane governs
    its maintenance endpoints while foreground needle traffic is
    protected by the EC throttle instead)."""
    ctl = controller()

    def admission(req):
        path = req.path
        if path.startswith(_EXEMPT_PREFIXES):
            return None, None
        if path_prefix and not path.startswith(path_prefix):
            return None, None
        from . import stats
        from .util import deadline as _deadline
        tenant = tenant_of(req)
        # brownout: a deadline-carrying request whose remaining budget
        # cannot cover the current expected service latency is shed
        # BEFORE any token/byte accounting (already-expired budgets
        # belong to the fronts' 504 path, not this 503)
        d = _deadline.get()
        if d is not None and brownout_enabled():
            est = brownout_estimate() * _brownout_factor()
            rem = d.remaining()
            if est > 0.0 and 0.0 < rem < est:
                stats.PROCESS.counter_add(
                    "qos_rejected_total", 1.0,
                    help_text="requests rejected by QoS admission",
                    tenant=tenant, role=role, reason="brownout")
                # the flight recorder's record of a shed request must
                # say WHY it was shed (verdict "shed" alone names the
                # mechanism, not the cause)
                from . import profiling
                profiling.flight_note(
                    "qosReject",
                    {"reason": "brownout", "tenant": tenant,
                     "estimateMs": round(est * 1e3, 2),
                     "remainingMs": round(rem * 1e3, 2)})
                retry_after = max(1, int(est + 0.999))
                body = (b'{"error": "qos: request budget below '
                        b'current service latency (brownout)"}')
                return (503, (body,
                              {"Retry-After": str(retry_after),
                               "Content-Type": "application/json"})), \
                    None
        nbytes = int(req.headers.get("Content-Length") or 0)
        release, reject = ctl.admit(tenant, nbytes)
        if reject is None:
            stats.PROCESS.counter_add(
                "qos_admitted_total", 1.0,
                help_text="requests admitted by QoS",
                tenant=tenant, role=role)
            # the release callback doubles as the brownout
            # estimator's latency feed: it runs on the server fronts'
            # response finally path, so the sample covers handler
            # execution AND the response write
            t0 = time.monotonic()

            def _release_and_note(_inner=release):
                note_latency(time.monotonic() - t0)
                if _inner is not _NOOP:
                    _inner()
            return None, _release_and_note
        stats.PROCESS.counter_add(
            "qos_rejected_total", 1.0,
            help_text="requests rejected by QoS admission",
            tenant=tenant, role=role, reason=reject.reason)
        from . import profiling
        profiling.flight_note(
            "qosReject", {"reason": reject.reason, "tenant": tenant})
        retry_after = max(1, int(reject.retry_after + 0.999))
        body = (b'{"error": "qos: tenant over ' +
                reject.reason.encode() + b' limit"}')
        return (503, (body, {"Retry-After": str(retry_after),
                             "Content-Type": "application/json"})), \
            None

    http.admission = admission


class MeteredBody:
    """File-like response body that runs a release callback when the
    server finishes streaming it (httpd closes file-like payloads on
    the response-write finally path) — how charge_response's in-flight
    bytes stay held for exactly the duration of the response write."""

    def __init__(self, data: bytes, release):
        self._data = data
        self._pos = 0
        self._release = release

    def read(self, n: int = -1) -> bytes:
        if n < 0:
            n = len(self._data) - self._pos
        out = self._data[self._pos:self._pos + n]
        self._pos += len(out)
        return out

    def close(self) -> None:
        rel, self._release = self._release, None
        if rel is not None:
            rel()


def charge_response(req, nbytes: int, role: str):
    """Response-side in-flight-byte accounting for data-plane reads
    (volume needle GETs, filer file GETs): charge the tenant's
    in-flight-bytes bucket for the RESPONSE size, so a stampede of
    concurrent large reads — cache hits included — is bounded by the
    same dimension uploads are.  Returns (release, deny): deny is a
    ready 503 response tuple when the tenant is over budget; release
    must run when the response has been written (wrap the body in
    MeteredBody, or call it on the buffered path).  Zero-cost when QoS
    is unconfigured or the tenant has no byte limit."""
    ctl = controller()
    release, reject = ctl.admit_bytes(tenant_of(req), int(nbytes))
    if reject is None:
        # None release = unmetered (QoS off / no byte limit): callers
        # skip the MeteredBody wrap entirely
        return (None if release is _NOOP else release), None
    from . import stats
    stats.PROCESS.counter_add(
        "qos_rejected_total", 1.0,
        help_text="requests rejected by QoS admission",
        tenant=tenant_of(req), role=role, reason="read_bytes")
    retry_after = max(1, int(reject.retry_after + 0.999))
    body = b'{"error": "qos: tenant over inflight_bytes limit"}'
    return _NOOP, (503, (body, {"Retry-After": str(retry_after),
                                "Content-Type": "application/json"}))


# -- foreground p99 + feedback throttle ------------------------------------

def histogram_p99(buckets, counts, q: float = 0.99) -> float:
    """Quantile estimate from a cumulative-free histogram snapshot:
    `counts[i]` observations fell in (prev_le, buckets[i]]; the last
    slot is +Inf.  Linear interpolation inside the winning bucket; the
    +Inf bucket reports its lower edge (can't interpolate to
    infinity)."""
    total = sum(counts)
    if total <= 0:
        return 0.0
    target = q * total
    cum = 0.0
    lower = 0.0
    for le, n in zip(buckets, counts[:-1]):
        cum += n
        if cum >= target:
            frac = 1.0 - (cum - target) / n if n else 1.0
            return lower + (le - lower) * frac
        lower = le
    return float(buckets[-1]) if buckets else 0.0


class FeedbackThrottle:
    """Watches foreground `request_seconds` p99 across registered
    sources and turns SLO violations into an EC window pace.

    States: pace 0.0 (healthy) → pace_min on first violation →
    doubling per violating sample up to pace_max ("paused" — one
    window per pace_max interval) → halving per healthy sample back
    to 0.  Multiplicative both ways: recovery is fast but not
    instant, so an oscillating p99 doesn't square-wave the EC jobs."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sources: list = []     # (label, callable -> snap|None)
        self._last: dict[str, tuple] = {}   # label -> counts tuple
        self._pace = 0.0
        self._p99 = 0.0
        self._thread: "threading.Thread | None" = None
        self._stop = threading.Event()

    # -- sources -----------------------------------------------------

    def add_metrics(self, label: str, metrics) -> None:
        """A local role registry (stats.Metrics) as a foreground
        source."""
        with self._lock:
            self._sources = [s for s in self._sources
                             if s[0] != label] + \
                [(label,
                  lambda m=metrics: m.histogram_merged(
                      "request_seconds"))]

    def add_scrape(self, label: str, url: str) -> None:
        """A remote role's /metrics as a foreground source (the
        worker's EC jobs watch the volume servers they hammer)."""
        with self._lock:
            self._sources = [s for s in self._sources
                             if s[0] != label] + \
                [(label, lambda u=url: _scrape_request_seconds(u))]

    def remove_source(self, label: str) -> None:
        with self._lock:
            self._sources = [s for s in self._sources
                             if s[0] != label]
            self._last.pop(label, None)

    # -- sampling ----------------------------------------------------

    def sample_now(self) -> float:
        """One sampling step: worst per-source p99 of the traffic
        since the previous sample; updates the pace.  Called by the
        watcher thread, and directly by tests (deterministic)."""
        cfg = current()
        slo = cfg.slo_p99_ms / 1e3
        with self._lock:
            sources = list(self._sources)
        snaps = []
        for label, fn in sources:
            try:
                snap = fn()
            except (OSError, ValueError, KeyError, TypeError):
                continue    # a dead remote source must not kill the
            if snap:        # watcher; it just contributes nothing
                snaps.append((label, snap))
        worst = 0.0
        from . import stats
        with self._lock:
            for label, snap in snaps:
                counts = tuple(snap["counts"])
                prev = self._last.get(label)
                self._last[label] = counts
                if prev is None or len(prev) != len(counts):
                    continue
                delta = [max(c - p, 0)
                         for c, p in zip(counts, prev)]
                if sum(delta) <= 0:
                    continue
                worst = max(worst,
                            histogram_p99(snap["buckets"], delta))
            self._p99 = worst
            if slo <= 0:
                self._pace = 0.0
            elif worst > slo:
                self._pace = min(max(self._pace * 2,
                                     cfg.pace_min_ms / 1e3),
                                 cfg.pace_max_ms / 1e3)
            else:
                self._pace = 0.0 if self._pace <= \
                    cfg.pace_min_ms / 1e3 else self._pace / 2
            pace = self._pace
        stats.PROCESS.gauge_set(
            "qos_foreground_p99_seconds", worst,
            help_text="worst per-role request_seconds p99 over the "
                      "last QoS sample window")
        stats.PROCESS.gauge_set(
            "qos_ec_pace_ms", pace * 1e3,
            help_text="current background EC inter-window pace")
        return pace

    def pace(self) -> float:
        with self._lock:
            return self._pace

    def p99(self) -> float:
        with self._lock:
            return self._p99

    def set_pace(self, pace_s: float) -> None:
        """Runtime lever / tests: force the pace directly."""
        with self._lock:
            self._pace = max(float(pace_s), 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {"paceMs": self._pace * 1e3,
                    "lastP99Ms": self._p99 * 1e3,
                    "sources": [label for label, _ in self._sources],
                    "running": self._thread is not None and
                    self._thread.is_alive()}

    # -- watcher -----------------------------------------------------

    def maybe_start(self) -> None:
        """Start the sampling thread if the SLO is configured and it
        isn't running.  Idempotent; cheap enough to call from every
        role constructor."""
        if current().slo_p99_ms <= 0:
            return
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="qos-feedback-throttle")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(
                max(current().check_interval_ms, 50.0) / 1e3):
            try:
                self.sample_now()
            except Exception as e:   # noqa: BLE001 — the throttle is
                # advisory; it must never die, only report
                from .util import wlog
                wlog.warning("qos throttle sample failed: %s", e,
                             component="qos")


def _scrape_request_seconds(url: str) -> "dict | None":
    """Cumulative request_seconds bucket counts from a remote role's
    /metrics (merged across method/code label sets)."""
    from .server.httpd import http_bytes
    status, body, _ = http_bytes("GET", f"{url}/metrics", timeout=5)
    if status != 200:
        return None
    by_le: dict[float, float] = {}
    for line in body.decode(errors="replace").splitlines():
        if "_request_seconds_bucket{" not in line:
            continue
        head, _, value = line.rpartition(" ")
        i = head.find('le="')
        if i < 0:
            continue
        le_s = head[i + 4:head.find('"', i + 4)]
        le = float("inf") if le_s == "+Inf" else float(le_s)
        try:
            by_le[le] = by_le.get(le, 0.0) + float(value)
        except ValueError:
            continue
    if not by_le:
        return None
    les = sorted(k for k in by_le if k != float("inf"))
    # cumulative -> per-bucket
    counts, prev = [], 0.0
    for le in les:
        counts.append(by_le[le] - prev)
        prev = by_le[le]
    counts.append(by_le.get(float("inf"), prev) - prev)
    return {"buckets": tuple(les), "counts": counts}


# -- process singletons + the EC pipelines' hook ---------------------------

_controller = AdmissionController()
_throttle = FeedbackThrottle()


def controller() -> AdmissionController:
    return _controller


def throttle() -> FeedbackThrottle:
    return _throttle


def current() -> QosConfig:
    return _controller.config()


def configure(config: "QosConfig | None") -> None:
    """Install a new process QoS config (None resets to inert)."""
    _controller.configure(config or QosConfig())
    _throttle.maybe_start()


def reset() -> None:
    """Back to the inert boot state (test isolation, like
    faults.reset): config cleared, pace zeroed, sample history
    dropped.  Registered sources stay — live servers own those."""
    _controller.configure(QosConfig())
    _throttle.stop()
    with _throttle._lock:
        _throttle._pace = 0.0
        _throttle._p99 = 0.0
        _throttle._last.clear()
    _brownout_reset()
    set_brownout_factor(None)  # noqa: SWFS021 — reset to baseline,
    # not a competing controller


def _env_default_config() -> None:
    slo = _env_float("SEAWEEDFS_TPU_QOS_SLO_P99_MS", 0.0)
    if slo > 0:
        cfg = _controller.config()
        cfg.slo_p99_ms = slo
        cfg.check_interval_ms = _env_float(
            "SEAWEEDFS_TPU_QOS_CHECK_MS", cfg.check_interval_ms)
        cfg.pace_min_ms = _env_float(
            "SEAWEEDFS_TPU_QOS_PACE_MIN_MS", cfg.pace_min_ms)
        cfg.pace_max_ms = _env_float(
            "SEAWEEDFS_TPU_QOS_PACE_MAX_MS", cfg.pace_max_ms)


def ec_pace(kind: str) -> float:
    """The background pipelines' per-window hook (ShardSink sends,
    ShardSource slice fetches): sleeps the current pace, counting the
    downshift.  Unconfigured cost: one lock round, no sleep."""
    pace = _throttle.pace()
    if pace <= 0.0:
        return 0.0
    from . import stats
    stats.PROCESS.counter_add(
        "qos_ec_paced_total", 1.0,
        help_text="background EC windows delayed by the QoS throttle",
        kind=kind)
    time.sleep(pace)
    return pace


_watch_lock = threading.Lock()
_watch_refs: "dict[str, int]" = {}   # url -> concurrent watcher count


class remote_slo_watch:
    """Context manager for background jobs running OUTSIDE the serving
    processes (the maintenance worker): watch the named peers'
    /metrics for the job's duration so the feedback loop closes even
    though the worker holds no foreground histogram of its own.

    Sources are refcounted per url: a worker running concurrent jobs
    (max_concurrent > 1) whose url lists overlap must not have one
    job's exit remove a scrape source another job still needs."""

    def __init__(self, urls):
        self.urls = [u for u in dict.fromkeys(urls) if u]
        self._added: list = []

    def __enter__(self):
        if current().slo_p99_ms > 0:
            with _watch_lock:
                for u in self.urls:
                    _watch_refs[u] = _watch_refs.get(u, 0) + 1
                    self._added.append(u)
                    _throttle.add_scrape(f"remote:{u}", u)
            _throttle.maybe_start()
        return self

    def __exit__(self, *exc):
        with _watch_lock:
            for u in self._added:
                n = _watch_refs.get(u, 1) - 1
                if n <= 0:
                    _watch_refs.pop(u, None)
                    _throttle.remove_source(f"remote:{u}")
                else:
                    _watch_refs[u] = n
            self._added = []
        return False


_env_default_config()
