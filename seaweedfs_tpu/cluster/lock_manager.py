"""Distributed lock manager, hosted by the filer
(weed/cluster/lock_manager/distributed_lock_manager.go, lock_manager.go).

Semantics follow the reference:
- Lock(key, ttl, owner, token) grants a fresh renew-token, or RENEWS
  when the presented token matches the live lock, or steals only when
  the previous lock expired.  A mismatched token on a live lock is a
  conflict naming the current owner.
- Unlock requires the token (a crashed holder's lock simply expires).
- Ring placement: each lock key hashes onto the sorted member list;
  a non-owner answers `movedTo` and the client re-dials, exactly the
  reference's CalculateTargetServer shape.  With a single filer the
  ring is {self} and every lock is local.

Consumers: the MQ broker wraps partition takeover in a cluster lock
(closing the CONF_TTL read-modify-write race the round-3 ROADMAP
documented), and shell/maintenance flows may lock arbitrary keys.
"""

from __future__ import annotations

import hashlib
import threading
import time
import uuid


def normalize_address(addr: str) -> str:
    """Canonicalize a ring-member address (ADVICE r4): membership is
    compared by string, so `localhost:8888` vs `127.0.0.1:8888` spelled
    differently across -lockPeers lists would make the owning filer
    fail its own `target == self` check and bounce every acquire
    through movedTo redirects until the client times out.

    Deliberately NO DNS here: resolution is per-host state (a resolver
    blip or split-horizon DNS on one filer would silently diverge the
    member lists and break lock mutual exclusion — worse than the
    redirect loop this fixes).  Only deterministic rewrites: lowercase,
    strip scheme / trailing slash, and the loopback aliases every host
    agrees on."""
    a = addr.strip().lower()
    if "://" in a:
        a = a.split("://", 1)[1]
    a = a.rstrip("/")
    if a.startswith("["):             # [v6]:port or bare [v6]
        host, _, rest = a.partition("]")
        host, port = host[1:], rest.lstrip(":")
    elif a.count(":") > 1:            # bare IPv6, no port
        host, port = a, ""
    else:
        host, _, port = a.rpartition(":")
        if not host:                  # bare hostname/IPv4, no port
            host, port = a, ""
    # only the NAME alias collapses; ::1 stays a v6 address — mapping
    # it to 127.0.0.1 would advertise a dial target a socket bound
    # only to v6 loopback does not accept
    if host in ("localhost", "ip4-localhost"):
        host = "127.0.0.1"
    elif ":" in host:                 # keep v6 hosts bracketed so the
        host = f"[{host}]"            # port separator stays parseable
    return f"{host}:{port}" if port else host


class LockManager:
    """Server-side lock table (one per filer)."""

    def __init__(self, host: str = ""):
        self.host = host
        self._lock = threading.Lock()
        # key -> (owner, token, expires_at_monotonic)
        self._locks: dict[str, tuple[str, str, float]] = {}
        self.members: list[str] = [host] if host else []

    # -- ring placement -------------------------------------------------

    def target_server(self, key: str) -> str:
        """distributed_lock_manager.go:151 CalculateTargetServer."""
        members = sorted(m for m in self.members if m)
        if not members or len(members) == 1:
            return self.host
        h = int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:8], "big")
        return members[h % len(members)]

    # -- lock table -----------------------------------------------------

    def acquire(self, key: str, owner: str, ttl_sec: float,
                token: str = "") -> "tuple[str, float] | str":
        """Returns (renew_token, expires_at_epoch) on success, or the
        current owner string on conflict."""
        now = time.monotonic()
        with self._lock:
            cur = self._locks.get(key)
            if cur is not None and cur[2] > now:
                cur_owner, cur_token, _ = cur
                if token and token == cur_token:
                    pass  # renewal by the live holder
                else:
                    return cur_owner
            new_token = token if (cur and token == cur[1]) \
                else uuid.uuid4().hex
            self._locks[key] = (owner, new_token, now + ttl_sec)
            return new_token, time.time() + ttl_sec

    def release(self, key: str, token: str) -> bool:
        with self._lock:
            cur = self._locks.get(key)
            if cur is None:
                return True  # already gone (expired)
            if cur[1] != token:
                return False
            del self._locks[key]
            return True

    def find_owner(self, key: str) -> "str | None":
        now = time.monotonic()
        with self._lock:
            cur = self._locks.get(key)
            if cur is None or cur[2] <= now:
                return None
            return cur[0]

    def all_locks(self) -> "list[dict]":
        now = time.monotonic()
        with self._lock:
            return [{"key": k, "owner": o,
                     "ttlRemainingSec": round(exp - now, 2)}
                    for k, (o, _t, exp) in self._locks.items()
                    if exp > now]


class ClusterLock:
    """Client-side lock handle with background renewal
    (wdclient's LiveLock analog, cluster/lock_client.go): acquire
    blocks (with timeout), a renew thread keeps the lock alive at
    ttl/3 cadence, release stops it.  Usable as a context manager.
    Follows `movedTo` redirects across the filer ring."""

    def __init__(self, filer: str, key: str, owner: str,
                 ttl_sec: float = 10.0):
        self.filer = filer
        self.key = key
        self.owner = owner
        self.ttl = ttl_sec
        self._token = ""
        self._stop = threading.Event()
        self._renewer: threading.Thread | None = None
        # set when the lock is CONFIRMED taken by someone else; a
        # holder in a long critical section can check is_held()
        self.lost = threading.Event()

    def _call(self, path: str, payload: dict) -> dict:
        from ..server.httpd import http_json
        target = self.filer
        for _ in range(3):  # ring redirects
            r = http_json("POST", f"{target}{path}", payload, timeout=10)
            moved = r.get("movedTo")
            if moved and moved != target:
                target = moved
                continue
            return r
        return r

    def _try_acquire(self) -> str:
        """One acquire/renew attempt: "ok", "conflict" (someone else
        holds it — authoritative), or "transient" (server error /
        unreachable: retry within the TTL, the lock may still be
        ours)."""
        try:
            r = self._call("/admin/locks/acquire", {
                "key": self.key, "owner": self.owner,
                "ttlSec": self.ttl, "renewToken": self._token})
        except OSError:
            return "transient"
        if "renewToken" in r:
            self._token = r["renewToken"]
            return "ok"
        # http_json returns HTTP error bodies as dicts, never raising:
        # only an explicit "locked" conflict is an authoritative loss
        if r.get("error") == "locked":
            return "conflict"
        return "transient"

    def is_held(self) -> bool:
        return bool(self._token) and not self.lost.is_set()

    def acquire(self, timeout: float = 30.0) -> "ClusterLock":
        deadline = time.monotonic() + timeout
        while True:
            if self._try_acquire() == "ok":
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"lock {self.key}: held by another owner")
            time.sleep(min(0.2, self.ttl / 10))
        self.lost.clear()
        self._stop.clear()
        self._renewer = threading.Thread(target=self._renew_loop,
                                         daemon=True)
        self._renewer.start()
        return self

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.ttl / 3):
            if self._try_acquire() == "conflict":
                # someone else holds it now — surface the loss; the
                # holder's critical section checks is_held().
                # Transient errors keep retrying at ttl/3 cadence: the
                # server-side lock is still ours until TTL expiry.
                self.lost.set()
                return

    def release(self) -> None:
        self._stop.set()
        if self._renewer is not None:
            self._renewer.join(timeout=1)
        if self._token:
            try:
                self._call("/admin/locks/release",
                           {"key": self.key, "renewToken": self._token})
            except OSError:
                pass  # expires on its own
            self._token = ""

    def __enter__(self) -> "ClusterLock":
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()
