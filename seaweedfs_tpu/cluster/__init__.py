"""Cluster coordination primitives (weed/cluster analog)."""

from .lock_manager import ClusterLock, LockManager  # noqa: F401
