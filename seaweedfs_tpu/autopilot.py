"""SLO autopilot: the closed feedback loop over the knobs PRs 13-14
shipped open-loop (ROADMAP item 4).

Every sensor already exists — deadline verdicts, hedge issued/won,
brownout sheds, cache hit/miss/eviction counters, `gil_wait_ratio` —
and every actuator already exists as a hand-set env var or runtime
lever.  This module closes the loop: a per-role controller thread
(~1 s tick) reads counter DELTAS between its own ticks off the shared
`stats.PROCESS` registry and drives the actuators through a typed
registry that is the ONLY sanctioned runtime mutation path for an
autopilot-controlled knob (devtools rule SWFS021 enforces that — a
direct env write or ad-hoc setter call elsewhere is a second driver
fighting this one).

Control discipline (every rule, no exceptions):

* **Bounded** — an actuator carries `[lo, hi]`; `actuate()` clamps
  and refuses a step past the bound rather than sliding toward it.
* **Hysteresis-damped** — a rule must see its trigger condition for
  `confirm` CONSECUTIVE ticks before a knob moves, and a move smaller
  than `deadband` (relative) is not worth a flight note and is
  skipped.
* **Per-knob cooldown** — after an actuation the knob holds for
  `cooldown` seconds no matter what the sensors say, so one noisy
  window cannot saw a knob back and forth.
* **Sensor gap = hold** — a failed scrape, a missing counter, or a
  window with too few samples NEVER actuates.  The controller only
  moves on evidence; absence of evidence parks the knob where it is.
* **Observable** — every actuation lands in the bounded action log
  (`/debug/autopilot`), the `autopilot_actions_total{knob,direction}`
  counter, the per-knob `autopilot_knob{knob}` gauge and (when a
  request context is armed, e.g. the debug lever) a `flight_note`.

Kill switches, strongest first: `SEAWEEDFS_TPU_AUTOPILOT=0` (the
loop never starts and a running loop holds), `POST /debug/autopilot
{"enabled": false}` (runtime, per process), and per-knob absence —
a role that never registers a "workers" actuator can never have its
workers touched.

Native-plane supervision rides the same tick: each registered
`PlaneGuard` watches a plane's error/fallback share of its own
request delta; a spike disarms the plane through the SAME lever
`POST /debug/meta_plane {"armed": false}` drives, then a background
probe re-arms it after an exponentially-backed-off probation — the
zero-Python hot paths get a supervised degradation path instead of
an operator page.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from . import profiling, stats
from .util import wlog


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def enabled_by_env() -> bool:
    return os.environ.get("SEAWEEDFS_TPU_AUTOPILOT", "1") \
        not in ("0", "false")


def tick_interval() -> float:
    return max(0.05,
               _env_float("SEAWEEDFS_TPU_AUTOPILOT_TICK_MS", 1000.0)
               / 1e3)


class Actuator:
    """One controllable knob: a getter, a setter, hard bounds and a
    cooldown.  `set` is only ever called by `Autopilot.actuate()` —
    the registry IS the mutation path (SWFS021)."""

    __slots__ = ("name", "get", "set", "lo", "hi", "cooldown",
                 "last_actuated", "describe")

    def __init__(self, name: str, get, set, lo: float, hi: float,
                 cooldown: "float | None" = None,
                 describe: str = ""):
        if not (lo <= hi):
            raise ValueError(f"{name}: lo {lo} > hi {hi}")
        self.name = name
        self.get = get
        self.set = set
        self.lo = float(lo)
        self.hi = float(hi)
        self.cooldown = (cooldown if cooldown is not None else
                         _env_float(
                             "SEAWEEDFS_TPU_AUTOPILOT_COOLDOWN_S",
                             5.0))
        self.last_actuated: "float | None" = None
        self.describe = describe


class PlaneGuard:
    """Supervision state for one native plane.

    `stats` returns the plane's cumulative counter dict (requests,
    fallbacks, *_errors...); `arm(bool)` is the existing
    /debug/*_plane lever; `armed()` reports the current state so an
    operator disarm is respected (the guard never re-arms a plane it
    did not itself disarm).  A trip needs BOTH an absolute error
    floor (`min_errors` in the window) and an error share of the
    plane's own traffic (`trip_ratio`) — a single failed request on
    an idle plane is not a spike.  Probation doubles per consecutive
    trip up to `max_backoff` and resets after a clean probation."""

    __slots__ = ("name", "stats", "arm", "armed", "trip_ratio",
                 "min_errors", "backoff", "max_backoff",
                 "disarmed_by_us", "probation_until", "trips",
                 "_prev", "_streak", "confirm")

    def __init__(self, name: str, stats, arm, armed,
                 trip_ratio: float = 0.5, min_errors: int = 5,
                 backoff: float = 10.0, max_backoff: float = 300.0,
                 confirm: int = 1):
        self.name = name
        self.stats = stats
        self.arm = arm
        self.armed = armed
        self.trip_ratio = trip_ratio
        self.min_errors = min_errors
        self.backoff = backoff
        self.max_backoff = max_backoff
        self.confirm = max(1, confirm)
        self.disarmed_by_us = False
        self.probation_until = 0.0
        self.trips = 0
        self._streak = 0
        self._prev: "dict | None" = None

    _ERROR_KEYS = ("wal_errors", "upstream_errors", "errors")

    def window(self) -> "tuple[float, float, float] | None":
        """(requests, errors, fallbacks) delta since the last tick,
        or None on the first sample / a failed scrape (sensor gap =
        hold)."""
        try:
            cur = dict(self.stats() or {})
        except Exception:
            return None
        prev, self._prev = self._prev, cur
        if prev is None:
            return None
        d = {k: max(0.0, float(cur.get(k, 0)) - float(prev.get(k, 0)))
             for k in cur}
        errors = sum(d.get(k, 0.0) for k in self._ERROR_KEYS)
        return (d.get("requests", 0.0), errors,
                d.get("fallbacks", 0.0))


class Autopilot:
    """The per-role controller.  Construction wires nothing; the
    server registers its actuators/planes, then `start()` spins the
    daemon tick thread.  `tick(now)` is deliberately callable by hand
    with a pinned clock so every control rule is unit-testable with
    zero threads and zero sleeps."""

    ACTION_LOG = 64

    def __init__(self, role: str,
                 metrics: "stats.Metrics | None" = None,
                 sense=None, now=time.monotonic,
                 confirm: "int | None" = None):
        self.role = role
        self.metrics = metrics if metrics is not None else \
            stats.PROCESS
        self.now = now
        self.enabled = enabled_by_env()
        self.confirm = confirm if confirm is not None else max(
            1, int(_env_float("SEAWEEDFS_TPU_AUTOPILOT_CONFIRM", 2)))
        self.deadband = 0.02
        self.actuators: "dict[str, Actuator]" = {}
        self.planes: "list[PlaneGuard]" = []
        self.actions: "deque[dict]" = deque(maxlen=self.ACTION_LOG)
        self.ticks = 0
        self.sensor_gaps = 0
        self._sense = sense if sense is not None else self._sense_process
        self._prev_sample: "dict | None" = None
        self._streaks: "dict[str, int]" = {}
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None
        self._lock = threading.Lock()
        self._publish_enabled()

    # -- registry ---------------------------------------------------------

    def register(self, act: Actuator) -> Actuator:
        self.actuators[act.name] = act
        try:
            self.metrics.gauge_set("autopilot_knob", float(act.get()),
                                   knob=act.name)
        except Exception:  # noqa: SWFS004 — metrics are best-effort;
            pass           # a gauge failure must not block wiring
        return act

    def register_plane(self, guard: PlaneGuard) -> PlaneGuard:
        self.planes.append(guard)
        return guard

    # -- the sole sanctioned mutation path --------------------------------

    def actuate(self, name: str, target: float, reason: str,
                force: bool = False) -> bool:
        """Clamp `target` into the knob's bounds and apply it.  The
        ONLY caller of an Actuator's `set` — every move is bounded,
        cooldown-checked, logged, counted and gauged.  `force` skips
        cooldown/deadband (the debug lever and plane guards use it);
        it never skips the bounds."""
        act = self.actuators.get(name)
        if act is None:
            return False
        t = self.now()
        if not force and act.last_actuated is not None and \
                t - act.last_actuated < act.cooldown:
            return False
        try:
            cur = float(act.get())
        except Exception:
            return False                      # sensor gap = hold
        new = min(act.hi, max(act.lo, float(target)))
        if not force and abs(new - cur) <= \
                self.deadband * max(abs(cur), 1e-9):
            return False
        if new == cur:
            return False
        act.set(new)
        act.last_actuated = t
        direction = "up" if new > cur else "down"
        entry = {"t": time.time(), "knob": name, "from": cur,
                 "to": new, "direction": direction, "reason": reason}
        with self._lock:
            self.actions.append(entry)
        self.metrics.counter_add(
            "autopilot_actions_total", 1.0,
            help_text="autopilot knob movements",
            knob=name, direction=direction)
        self.metrics.gauge_set("autopilot_knob", new, knob=name)
        profiling.flight_note("autopilot",
                              {"knob": name, "from": round(cur, 6),
                               "to": round(new, 6),
                               "reason": reason})
        wlog.info("autopilot[%s] %s: %.4g -> %.4g (%s)",
                  self.role, name, cur, new, reason,
                  component="autopilot")
        return True

    # -- sensors ----------------------------------------------------------

    def _sense_process(self) -> dict:
        """Cumulative sensor snapshot off the shared registry.  Keys
        are stable names the rules subtract between ticks; a key the
        process has never emitted is simply absent (its rules hold)."""
        m = self.metrics
        s: dict = {
            "hedges_issued": m.counter_sum("hedges_issued_total"),
            "hedges_won": m.counter_sum("hedges_won_total"),
            "brownout_shed": m.counter_sum("qos_rejected_total",
                                           reason="brownout"),
            "deadline_exceeded":
                m.counter_sum("deadline_exceeded_total"),
        }
        for cache, label in (("chunk", "filer_chunk"),
                             ("needle", "volume_needle"),
                             ("meta", "filer_meta")):
            hits = m.counter_value("read_cache_hits_total",
                                   cache=label)
            misses = m.counter_value("read_cache_misses_total",
                                     cache=label)
            if hits is None and misses is None:
                continue          # this cache never served: hold
            s[f"cache.{cache}.hits"] = hits or 0.0
            s[f"cache.{cache}.misses"] = misses or 0.0
            s[f"cache.{cache}.evictions"] = m.counter_value(
                "read_cache_evictions_total", cache=label) or 0.0
        g = m.gauge_value("gil_wait_ratio")
        if g is not None:
            s["gil_wait_ratio"] = g
        return s

    def _streak(self, key: str, cond: bool) -> bool:
        """Hysteresis: `cond` must hold for `confirm` consecutive
        ticks before the rule fires; any non-triggering tick resets
        the streak."""
        n = self._streaks.get(key, 0) + 1 if cond else 0
        self._streaks[key] = n
        return n >= self.confirm

    # -- the loop ---------------------------------------------------------

    def start(self) -> "Autopilot":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name=f"weed-autopilot-{self.role}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.wait(tick_interval()):
            try:
                self.tick()
            except Exception as e:  # noqa: BLE001 — the controller
                # must never take its process down; a broken tick is
                # a held tick
                wlog.warning("autopilot tick failed: %s", e,
                             component="autopilot")

    def set_enabled(self, on: bool) -> None:
        self.enabled = bool(on)
        if not on:
            # stale baselines must not actuate on re-enable: the
            # first tick after a gap is baseline-only
            self._prev_sample = None
            self._streaks.clear()
        self._publish_enabled()

    def _publish_enabled(self) -> None:
        try:
            self.metrics.gauge_set(
                "autopilot_enabled",
                1.0 if self.enabled else 0.0,
                help_text="1 when the SLO autopilot loop may actuate")
        except Exception:  # noqa: SWFS004 — metrics are best-effort;
            pass           # the kill switch must work without them

    def tick(self) -> None:
        """One control step: sense, diff, rule pass, plane pass.
        Deterministic given (sense, now) — the tests drive it with a
        fake clock and synthetic counters."""
        self.ticks += 1
        if not enabled_by_env():
            # env kill flipped at runtime: hold AND forget baselines
            if self.enabled:
                self.set_enabled(False)
            return
        if not self.enabled:
            return
        try:
            sample = self._sense()
        except Exception:
            sample = None
        if sample is None:
            self.sensor_gaps += 1
            self._prev_sample = None          # gap = hold, and the
        else:                                 # next tick re-baselines
            prev, self._prev_sample = self._prev_sample, sample
            if prev is not None:              # else baseline-only
                delta = {k: sample[k] - prev[k]
                         for k in sample if k in prev
                         and isinstance(sample[k], (int, float))}
                self._rule_hedge(delta)
                self._rule_hedge_floor(delta)
                self._rule_brownout(delta)
                self._rule_caches(delta)
                self._rule_workers(sample)
        # plane supervision scrapes its own counters — it runs every
        # enabled tick, baseline ticks and metric gaps included (each
        # guard's window() holds on ITS OWN first sample / gap)
        self._plane_pass()

    # -- control rules ----------------------------------------------------

    MIN_HEDGE_WINDOW = 5.0

    def _rule_hedge(self, d: dict) -> None:
        """Adapt hedge aggressiveness to the measured win rate.  A
        hedge that usually wins is buying real tail latency — earn
        tokens faster and fire earlier; a hedge that almost never
        wins is pure extra load — starve it."""
        if "hedges_issued" not in d or "hedges_won" not in d:
            return
        issued, won = d["hedges_issued"], d["hedges_won"]
        if issued < self.MIN_HEDGE_WINDOW:
            self._streak("hedge.hi", False)
            self._streak("hedge.lo", False)
            return
        rate = won / issued
        if self._streak("hedge.hi", rate > 0.7):
            r = self.actuators.get("hedge.ratio")
            if r is not None:
                self.actuate("hedge.ratio", r.get() * 1.25,
                             f"win rate {rate:.2f} > 0.7")
            m = self.actuators.get("hedge.min_ms")
            if m is not None:
                self.actuate("hedge.min_ms", m.get() * 0.8,
                             f"win rate {rate:.2f} > 0.7")
        elif self._streak("hedge.lo", rate < 0.2):
            r = self.actuators.get("hedge.ratio")
            if r is not None:
                self.actuate("hedge.ratio", r.get() * 0.8,
                             f"win rate {rate:.2f} < 0.2")
            m = self.actuators.get("hedge.min_ms")
            if m is not None:
                self.actuate("hedge.min_ms", m.get() * 1.25,
                             f"win rate {rate:.2f} < 0.2")

    def _rule_hedge_floor(self, d: dict) -> None:
        """The slow-replica rescue: deadlines are blowing and the
        hedge NEVER fires — the threshold floor sits above the point
        where insurance could still pay out inside the budget.  Halve
        it (the win-rate rule cannot help here: a hedge that never
        issues produces no win-rate evidence, so this is the only
        path out of a misconfigured floor)."""
        blown = d.get("deadline_exceeded")
        issued = d.get("hedges_issued")
        if blown is None or issued is None:
            return
        if self._streak("hedge.floor", blown >= 3 and issued == 0):
            m = self.actuators.get("hedge.min_ms")
            if m is not None:
                self.actuate("hedge.min_ms", m.get() * 0.5,
                             f"{blown:.0f} blown deadlines, "
                             f"0 hedges issued")

    def _rule_brownout(self, d: dict) -> None:
        """Balance shed-vs-blown: deadlines blowing with no sheds
        means admission is too optimistic (raise the factor: shed
        earlier); sheds with zero blown deadlines means it is too
        pessimistic (lower it)."""
        if "brownout_shed" not in d or "deadline_exceeded" not in d:
            return
        shed, blown = d["brownout_shed"], d["deadline_exceeded"]
        act = self.actuators.get("brownout.factor")
        if act is None:
            return
        if self._streak("brownout.up", blown >= 3 and shed == 0):
            self.actuate("brownout.factor", act.get() * 1.25,
                         f"{blown:.0f} blown deadlines, 0 shed")
        elif self._streak("brownout.down", shed >= 3 and blown == 0):
            self.actuate("brownout.factor", act.get() * 0.8,
                         f"{shed:.0f} shed, 0 blown deadlines")

    MIN_CACHE_WINDOW = 20.0

    def _rule_caches(self, d: dict) -> None:
        """Resize by marginal hit value: a cache that hits well AND
        still evicts would convert more bytes into more hits — grow
        it; a busy cache that almost never hits is churn — shrink it
        and give the memory back."""
        for cache in ("chunk", "needle", "meta"):
            name = f"cache.{cache}"
            act = self.actuators.get(name)
            if act is None:
                continue
            hits = d.get(f"cache.{cache}.hits")
            misses = d.get(f"cache.{cache}.misses")
            ev = d.get(f"cache.{cache}.evictions")
            if hits is None or misses is None:
                continue                      # sensor gap = hold
            lookups = hits + misses
            if lookups < self.MIN_CACHE_WINDOW:
                self._streak(f"{name}.up", False)
                self._streak(f"{name}.down", False)
                continue
            ratio = hits / lookups
            if self._streak(f"{name}.up",
                            ratio > 0.6 and (ev or 0) > 0):
                self.actuate(name, act.get() * 1.25,
                             f"hit {ratio:.2f} with "
                             f"{ev:.0f} evictions")
            elif self._streak(f"{name}.down",
                              ratio < 0.1 and (ev or 0) > 0):
                # evictions are the churn proof: a COLD cache (wipe,
                # restart) also reads hit~0 but evicts nothing — it
                # must be left to warm, never shrunk
                self.actuate(name, act.get() * 0.8,
                             f"hit {ratio:.2f} < 0.1 while "
                             f"evicting")

    def _rule_workers(self, sample: dict) -> None:
        """Grow/drain pre-fork workers off the scheduler probe: a
        sustained GIL-convoyed process wants a sibling; a sustained
        idle fleet wants one fewer wakeup source.  Only a role that
        registered a "workers" actuator (the pre-fork parent) can be
        moved."""
        act = self.actuators.get("workers")
        if act is None:
            return
        ratio = sample.get("gil_wait_ratio")
        if ratio is None:
            self._streak("workers.up", False)
            self._streak("workers.down", False)
            return
        if self._streak("workers.up", ratio > 0.5):
            self.actuate("workers", act.get() + 1,
                         f"gil_wait_ratio {ratio:.2f} > 0.5")
        elif self._streak("workers.down", ratio < 0.02):
            self.actuate("workers", act.get() - 1,
                         f"gil_wait_ratio {ratio:.2f} < 0.02")

    # -- native-plane supervision -----------------------------------------

    def _plane_pass(self) -> None:
        t = self.now()
        for g in self.planes:
            try:
                armed = bool(g.armed())
            except Exception:  # noqa: SWFS004 — a plane probe that
                continue       # errors is a sensor gap: hold, retry
            if armed:
                w = g.window()
                if w is None:
                    g._streak = 0
                    continue
                requests, errors, _fallbacks = w
                spike = errors >= g.min_errors and \
                    errors / max(requests, 1.0) >= g.trip_ratio
                g._streak = g._streak + 1 if spike else 0
                if g._streak < g.confirm:
                    if not spike and g.disarmed_by_us and \
                            g.trips and \
                            t >= g.probation_until + g.backoff:
                        # a full clean probation after a re-arm:
                        # forgive history so an old incident cannot
                        # escalate a fresh one straight to max
                        g.trips = 0
                        g.disarmed_by_us = False
                    continue
                g._streak = 0
                g.trips += 1
                g.disarmed_by_us = True
                g.probation_until = t + min(
                    g.max_backoff,
                    g.backoff * (2 ** (g.trips - 1)))
                try:
                    g.arm(False)
                except Exception:  # noqa: SWFS004 — a failed disarm
                    continue       # retries next tick (trip recorded)
                self._note_plane(g, "disarm",
                                 f"{errors:.0f} errors / "
                                 f"{requests:.0f} requests")
            elif g.disarmed_by_us and t >= g.probation_until:
                # probe + re-arm; the next spike re-trips with a
                # doubled probation
                try:
                    g.arm(True)
                except Exception:
                    g.probation_until = t + g.backoff
                    continue
                g._prev = None                # re-baseline the window
                self._note_plane(g, "rearm",
                                 f"probation over after trip "
                                 f"#{g.trips}")

    def _note_plane(self, g: PlaneGuard, what: str,
                    reason: str) -> None:
        entry = {"t": time.time(), "knob": f"plane.{g.name}",
                 "direction": what, "reason": reason}
        with self._lock:
            self.actions.append(entry)
        self.metrics.counter_add(
            "autopilot_actions_total", 1.0,
            help_text="autopilot knob movements",
            knob=f"plane.{g.name}", direction=what)
        profiling.flight_note("autopilot",
                              {"plane": g.name, "action": what,
                               "reason": reason})
        wlog.warning("autopilot[%s] plane %s: %s (%s)",
                     self.role, g.name, what, reason,
                     component="autopilot")

    # -- introspection ----------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            actions = list(self.actions)
        return {
            "role": self.role,
            "enabled": self.enabled and enabled_by_env(),
            "ticks": self.ticks,
            "sensorGaps": self.sensor_gaps,
            "confirm": self.confirm,
            "knobs": {
                name: {"value": self._safe_get(a), "lo": a.lo,
                       "hi": a.hi, "cooldown": a.cooldown,
                       "describe": a.describe}
                for name, a in sorted(self.actuators.items())},
            "planes": [
                {"name": g.name, "armed": self._safe_armed(g),
                 "disarmedByAutopilot": g.disarmed_by_us,
                 "trips": g.trips,
                 "probationUntil": g.probation_until}
                for g in self.planes],
            "actions": actions,
        }

    @staticmethod
    def _safe_get(a: Actuator):
        try:
            return a.get()
        except Exception:
            return None

    @staticmethod
    def _safe_armed(g: PlaneGuard):
        try:
            return bool(g.armed())
        except Exception:
            return None


# -- role wiring -----------------------------------------------------------

def build_for_filer(fs) -> Autopilot:
    """Wire the filer's controllable surface: hedge threshold/ratio,
    brownout factor, chunk + meta cache sizes, and guards over both
    native planes.  The pre-fork parent adds a "workers" actuator on
    top (see __main__)."""
    from . import qos
    from .util import hedge
    ap = Autopilot("filer")
    ap.register(Actuator(
        "hedge.ratio",
        get=hedge.effective_ratio,
        set=hedge.set_ratio,
        lo=0.02, hi=0.3,
        describe="hedge tokens earned per primary read"))
    ap.register(Actuator(
        "hedge.min_ms",
        get=lambda: hedge.min_threshold() * 1e3,
        set=hedge.set_min_threshold_ms,
        lo=1.0, hi=50.0,
        describe="hedge threshold floor (ms)"))
    ap.register(Actuator(
        "brownout.factor",
        get=qos.effective_brownout_factor,
        set=qos.set_brownout_factor,
        lo=0.5, hi=4.0,
        describe="shed when remaining < estimate * f"))
    flr = getattr(fs, "filer", None)
    cc = getattr(flr, "chunk_cache", None)
    if cc is not None:
        ap.register(Actuator(
            "cache.chunk",
            get=lambda: cc.mem.limit / (1 << 20),
            set=lambda mb: cc.set_mem_limit(int(mb * (1 << 20))),
            lo=8.0, hi=512.0,
            describe="filer chunk-body mem cache (MB)"))
    mc = getattr(flr, "meta_cache", None)
    if mc is not None:
        ap.register(Actuator(
            "cache.meta",
            get=lambda: mc.capacity,
            set=lambda n: mc.set_capacity(int(n)),
            lo=256.0, hi=65536.0,
            describe="filer metadata cache (entries)"))
    # `armed` is a PROPERTY on both plane classes — wrap it in a
    # thunk; passing `nm.armed` bare would freeze the wiring-time bool
    nm = getattr(fs, "native_meta", None)
    if nm is not None:
        ap.register_plane(PlaneGuard(
            "meta", stats=nm.stats, arm=nm.arm,
            armed=lambda: nm.armed))
    nr = getattr(fs, "native_read", None)
    if nr is not None:
        ap.register_plane(PlaneGuard(
            "read", stats=nr.stats, arm=nr.arm,
            armed=lambda: nr.armed))
    return ap


def build_for_volume(vs) -> Autopilot:
    """The volume server's surface: the hot-needle cache.  The
    brownout knob is module-global (qos.py) and deliberately NOT
    registered here — in-process test clusters co-locate roles, and
    two loops driving one global knob is exactly the dual-controller
    shape SWFS021 outlaws; the filer's loop owns it."""
    ap = Autopilot("volume")
    nc = getattr(vs, "needle_cache", None)
    if nc is not None:
        ap.register(Actuator(
            "cache.needle",
            get=lambda: nc.mem.limit / (1 << 20),
            set=lambda mb: nc.set_mem_limit(int(mb * (1 << 20))),
            lo=8.0, hi=512.0,
            describe="volume hot-needle mem cache (MB)"))
    return ap
