"""Opt-in cluster telemetry (reference: weed/telemetry/client.go +
collector.go, telemetry/proto).

STRICTLY opt-in (the reference ships -telemetry=false by default; so
do we): when enabled on the master CLI, a background reporter
periodically collects anonymous cluster shape — version, os, server/
volume counts, total size — and POSTs it as JSON to the collector
URL.  The instance id is a random UUID generated in memory only
(never persisted), exactly the reference's privacy posture."""

from __future__ import annotations

import json
import platform
import threading
import uuid

from .server.httpd import http_bytes, http_json

from . import __version__

VERSION = f"seaweedfs-tpu/{__version__}"

# -- in-process repair aggregates (collector.go shape: counts only) -------
#
# The streaming EC rebuild records anonymous totals here; the opt-in
# reporter folds them into its periodic shape report so fleet-wide
# repair volume is visible without any per-volume identifiers.

_repair_lock = threading.Lock()
_repair_totals = {"count": 0, "bytesFetched": 0}
_scatter_totals = {"count": 0, "bytesScattered": 0}


def note_ec_rebuild(bytes_fetched: int) -> None:
    with _repair_lock:
        _repair_totals["count"] += 1
        _repair_totals["bytesFetched"] += int(bytes_fetched)


def ec_rebuild_totals() -> dict:
    with _repair_lock:
        return dict(_repair_totals)


def note_ec_scatter_encode(bytes_scattered: int) -> None:
    """One scatter encode completed; `bytes_scattered` is shard bytes
    that streamed to REMOTE placement targets (the bytes the seed path
    would have written locally and then re-copied in balance)."""
    with _repair_lock:
        _scatter_totals["count"] += 1
        _scatter_totals["bytesScattered"] += int(bytes_scattered)


def ec_scatter_totals() -> dict:
    with _repair_lock:
        return dict(_scatter_totals)


class TelemetryClient:
    def __init__(self, url: str, enabled: bool = False,
                 interval: float = 24 * 3600.0):
        self.url = url
        self.enabled = enabled and bool(url)
        self.interval = interval
        self.instance_id = str(uuid.uuid4())   # memory-only
        self._stop = threading.Event()
        self._thread: "threading.Thread | None" = None

    # -- collection (telemetry/collector.go shape) ------------------------

    def collect(self, master: str) -> dict:
        data = {
            "version": VERSION,
            "os": f"{platform.system()}/{platform.machine()}",
            "instanceId": self.instance_id,
        }
        try:
            status = http_json("GET", f"{master}/cluster/status", timeout=30)
            vols = http_json("GET", f"{master}/vol/list", timeout=30)
            data["clusterId"] = status.get("topologyId", "")
            # a healthy single-master cluster reports `peers: []` —
            # the answering master IS a master, so the count floors
            # at 1 (len(peers or [1]) read an empty-but-present list
            # as zero masters)
            data["masterCount"] = max(1, len(status.get("peers")
                                             or []))
            data["serverCount"] = len(status.get("dataNodes", []))
            count = size = 0
            for dc in vols.get("dataCenters", {}).values():
                for rack in dc.get("racks", {}).values():
                    for node in rack.get("nodes", []):
                        for v in node.get("volumes", []):
                            count += 1
                            size += int(v.get("size", 0))
            data["volumeCount"] = count
            data["totalSizeBytes"] = size
        except (OSError, ValueError):
            pass   # partial reports are fine; the shape matters
        rep = ec_rebuild_totals()
        data["ecRebuildCount"] = rep["count"]
        data["ecRebuildBytesFetched"] = rep["bytesFetched"]
        sca = ec_scatter_totals()
        data["ecScatterEncodeCount"] = sca["count"]
        data["ecScatterBytes"] = sca["bytesScattered"]
        return data

    def send(self, master: str) -> bool:
        if not self.enabled:
            return False
        try:
            st, _, _ = http_bytes(
                "POST", self.url, json.dumps(
                    self.collect(master)).encode(),
                {"Content-Type": "application/json"}, timeout=60)
            return st < 300
        except OSError:
            return False

    # -- reporter loop (client.go StartReporting) -------------------------

    def start(self, master: str) -> "TelemetryClient":
        if not self.enabled:
            return self
        def loop():
            self.send(master)            # first report at startup
            while not self._stop.wait(self.interval):
                self.send(master)
        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
