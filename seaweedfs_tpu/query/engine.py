"""SQL-subset evaluation over JSON-lines / CSV byte streams
(weed/query/engine/ — the reference evaluates SELECTs over parquet and
JSON files stored as needles, served by volume_server.proto:132 Query
and s3 SelectObjectContent).

Supported grammar (the core of AWS S3 Select / the reference's tests):

    SELECT <* | col[, col...]> FROM s3object
      [WHERE <col> <op> <literal> [AND ...]]
      [LIMIT <n>]

ops: = != <> < <= > >=      literals: 'str' | number | true | false
Column access supports dotted paths into nested JSON (a.b.c).
"""

from __future__ import annotations

import csv
import io
import json
import re


class QueryError(ValueError):
    pass


_SQL_RE = re.compile(
    r"^\s*select\s+(?P<cols>.+?)\s+from\s+s3object\s*"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)

_COND_RE = re.compile(
    r"^\s*(?P<col>[\w.\"]+)\s*(?P<op>=|!=|<>|<=|>=|<|>)\s*"
    r"(?P<val>'(?:[^']|'')*'|[-\w.]+)\s*$")

_OPS = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _split_conjuncts(where: str) -> "list[str]":
    """Split a WHERE clause on AND — but only OUTSIDE single-quoted
    literals ('black and white' must stay one token; '' escapes a
    quote)."""
    parts: list[str] = []
    buf: list[str] = []
    i, n = 0, len(where)
    in_quote = False
    while i < n:
        c = where[i]
        if c == "'":
            if in_quote and i + 1 < n and where[i + 1] == "'":
                buf.append("''")
                i += 2
                continue
            in_quote = not in_quote
            buf.append(c)
            i += 1
            continue
        if not in_quote and where[i:i + 3].lower() == "and" and \
                (i == 0 or where[i - 1].isspace()) and \
                (i + 3 >= n or where[i + 3].isspace()):
            parts.append("".join(buf))
            buf = []
            i += 3
            continue
        buf.append(c)
        i += 1
    parts.append("".join(buf))
    return [p for p in (s.strip() for s in parts) if p]


def _parse_literal(tok: str):
    if tok.startswith("'"):
        return tok[1:-1].replace("''", "'")
    low = tok.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        return None
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            raise QueryError(f"bad literal {tok!r}")


def parse_sql(sql: str) -> dict:
    m = _SQL_RE.match(sql)
    if not m:
        raise QueryError(f"unsupported SQL: {sql!r}")
    cols_raw = m.group("cols").strip()
    cols = None if cols_raw == "*" else \
        [c.strip().strip('"') for c in cols_raw.split(",")]
    conds = []
    if m.group("where"):
        for part in _split_conjuncts(m.group("where")):
            cm = _COND_RE.match(part)
            if not cm:
                raise QueryError(f"unsupported condition {part!r}")
            conds.append((cm.group("col").strip('"'), cm.group("op"),
                          _parse_literal(cm.group("val"))))
    limit = int(m.group("limit")) if m.group("limit") else None
    return {"cols": cols, "conds": conds, "limit": limit}


def _get_path(row: dict, col: str):
    cur = row
    for part in col.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _matches(row: dict, conds) -> bool:
    for col, op, want in conds:
        got = _get_path(row, col)
        if got is None and want is not None:
            return False
        # CSV fields arrive as strings; coerce toward the literal type
        if isinstance(want, (int, float)) and isinstance(got, str):
            try:
                got = float(got) if isinstance(want, float) else \
                    int(got)
            except ValueError:
                return False
        try:
            if not _OPS[op](got, want):
                return False
        except TypeError:
            return False
    return True


def _rowgroup_may_match(md_rg, conds) -> bool:
    """Row-group statistics pruning (the reference prunes parquet row
    groups by min/max the same way, query/engine/aggregations.go:40):
    False only when some conjunct PROVABLY matches no row of the
    group.  Missing/typeless stats keep the group."""
    cols = {md_rg.column(i).path_in_schema: md_rg.column(i)
            for i in range(md_rg.num_columns)}
    for col, op, want in conds:
        c = cols.get(col)
        if c is None or not isinstance(want, (int, float)) or \
                isinstance(want, bool):
            continue
        stats = c.statistics
        if stats is None or not stats.has_min_max or \
                not isinstance(stats.min, (int, float)):
            continue
        lo, hi = stats.min, stats.max
        if (op in ("=", "<=", "<") and lo > want) or \
                (op in ("=", ">=", ">") and hi < want) or \
                (op == "<" and lo >= want) or \
                (op == ">" and hi <= want):
            return False
    return True


def _parquet_rows(data: bytes, conds):
    """Parquet scan with row-group pruning; rows surface as plain
    dicts (binary columns decoded latin-1 so predicates on text-ish
    bytes behave)."""
    try:
        import pyarrow.parquet as pq
    except ImportError:  # pragma: no cover
        raise QueryError("parquet support requires pyarrow")
    try:
        pf = pq.ParquetFile(io.BytesIO(data))
    except Exception as e:
        raise QueryError(f"malformed parquet: {e}")
    for rg in range(pf.num_row_groups):
        if not _rowgroup_may_match(pf.metadata.row_group(rg), conds):
            continue
        table = pf.read_row_group(rg)
        for row in table.to_pylist():
            yield {k: (v.decode("latin-1")
                       if isinstance(v, bytes) else v)
                   for k, v in row.items()}


def _rows_from(data: bytes, input_format: str,
               csv_header: bool = True, conds=()):
    if input_format == "parquet":
        yield from _parquet_rows(data, conds)
    elif input_format == "json":
        for line in data.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                raise QueryError("malformed JSON record")
    elif input_format == "csv":
        text = data.decode("utf-8", errors="replace")
        reader = csv.reader(io.StringIO(text))
        rows = iter(reader)
        if csv_header:
            header = next(rows, None)
            if header is None:
                return
            for r in rows:
                yield dict(zip(header, r))
        else:
            for r in rows:
                yield {f"_{i + 1}": v for i, v in enumerate(r)}
    else:
        raise QueryError(f"unsupported input format {input_format!r}")


def run_query(sql: str, data: bytes, input_format: str = "json",
              csv_header: bool = True) -> "list[dict]":
    """Evaluate; returns the projected rows."""
    q = parse_sql(sql)
    if q["limit"] == 0:
        return []
    out = []
    for row in _rows_from(data, input_format, csv_header,
                          q["conds"]):
        if not _matches(row, q["conds"]):
            continue
        if q["cols"] is None:
            out.append(row)
        else:
            out.append({c: _get_path(row, c) for c in q["cols"]})
        if q["limit"] is not None and len(out) >= q["limit"]:
            break
    return out
