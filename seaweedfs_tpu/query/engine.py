"""SQL-subset evaluation over JSON-lines / CSV byte streams
(weed/query/engine/ — the reference evaluates SELECTs over parquet and
JSON files stored as needles, served by volume_server.proto:132 Query
and s3 SelectObjectContent).

Supported grammar (the core of AWS S3 Select / the reference's
query/engine tests, round 5 widened toward aggregations.go):

    SELECT <* | item[, item...]> FROM s3object
      [WHERE <cond> [AND ...]]
      [GROUP BY col[, col...]]
      [LIMIT <n>] [OFFSET <m>]

    item: col | COUNT(*) | COUNT(col) | SUM(col) | AVG(col)
        | MIN(col) | MAX(col)          (each with optional AS alias)
    cond: col <op> literal | col [NOT] LIKE 'pat' | col IS [NOT] NULL

ops: = != <> < <= > >=      literals: 'str' | number | true | false
LIKE patterns use SQL % / _ wildcards.  Column access supports dotted
paths into nested JSON (a.b.c).

Parquet fast paths (the reference's aggregations.go metadata
shortcuts): COUNT(*) with no WHERE answers from row-group row counts
without reading data; MIN/MAX with no WHERE answer from column
statistics when every row group carries them.
"""

from __future__ import annotations

import csv
import fnmatch
import io
import json
import re


class QueryError(ValueError):
    pass


_SQL_RE = re.compile(
    r"^\s*select\s+(?P<cols>.+?)\s+from\s+s3object\s*"
    r"(?:\s+where\s+(?P<where>.+?))?"
    r"(?:\s+group\s+by\s+(?P<group>[\w.\",\s]+?))?"
    r"(?:\s+limit\s+(?P<limit>\d+))?"
    r"(?:\s+offset\s+(?P<offset>\d+))?\s*;?\s*$",
    re.IGNORECASE | re.DOTALL)

_COND_RE = re.compile(
    r"^\s*(?P<col>[\w.\"]+)\s*(?P<op>=|!=|<>|<=|>=|<|>)\s*"
    r"(?P<val>'(?:[^']|'')*'|[-\w.]+)\s*$")

_LIKE_RE = re.compile(
    r"^\s*(?P<col>[\w.\"]+)\s+(?P<neg>not\s+)?like\s+"
    r"(?P<val>'(?:[^']|'')*')\s*$", re.IGNORECASE)

_NULL_RE = re.compile(
    r"^\s*(?P<col>[\w.\"]+)\s+is\s+(?P<neg>not\s+)?null\s*$",
    re.IGNORECASE)

_AGG_RE = re.compile(
    r"^(?P<fn>count|sum|avg|min|max)\s*\(\s*"
    r"(?P<arg>\*|[\w.\"]+)\s*\)$", re.IGNORECASE)

_AS_RE = re.compile(r"^(?P<expr>.+?)\s+as\s+(?P<alias>[\w.]+)$",
                    re.IGNORECASE)

_OPS = {
    "=": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<>": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}


def _split_conjuncts(where: str) -> "list[str]":
    """Split a WHERE clause on AND — but only OUTSIDE single-quoted
    literals ('black and white' must stay one token; '' escapes a
    quote)."""
    parts: list[str] = []
    buf: list[str] = []
    i, n = 0, len(where)
    in_quote = False
    while i < n:
        c = where[i]
        if c == "'":
            if in_quote and i + 1 < n and where[i + 1] == "'":
                buf.append("''")
                i += 2
                continue
            in_quote = not in_quote
            buf.append(c)
            i += 1
            continue
        if not in_quote and where[i:i + 3].lower() == "and" and \
                (i == 0 or where[i - 1].isspace()) and \
                (i + 3 >= n or where[i + 3].isspace()):
            parts.append("".join(buf))
            buf = []
            i += 3
            continue
        buf.append(c)
        i += 1
    parts.append("".join(buf))
    return [p for p in (s.strip() for s in parts) if p]


def _parse_literal(tok: str):
    if tok.startswith("'"):
        return tok[1:-1].replace("''", "'")
    low = tok.lower()
    if low == "true":
        return True
    if low == "false":
        return False
    if low == "null":
        return None
    try:
        return int(tok)
    except ValueError:
        try:
            return float(tok)
        except ValueError:
            raise QueryError(f"bad literal {tok!r}")


def _parse_cond(part: str):
    cm = _COND_RE.match(part)
    if cm:
        return (cm.group("col").strip('"'), cm.group("op"),
                _parse_literal(cm.group("val")))
    lm = _LIKE_RE.match(part)
    if lm:
        op = "not like" if lm.group("neg") else "like"
        return (lm.group("col").strip('"'), op,
                _parse_literal(lm.group("val")))
    nm = _NULL_RE.match(part)
    if nm:
        return (nm.group("col").strip('"'),
                "is not null" if nm.group("neg") else "is null",
                None)
    raise QueryError(f"unsupported condition {part!r}")


def _split_select_items(raw: str) -> "list[str]":
    """Split the select list on commas OUTSIDE parentheses (AVG(a),b
    must not split inside the call)."""
    items, buf, depth = [], [], 0
    for c in raw:
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
        if c == "," and depth == 0:
            items.append("".join(buf).strip())
            buf = []
        else:
            buf.append(c)
    items.append("".join(buf).strip())
    return [i for i in items if i]


def parse_sql(sql: str) -> dict:
    m = _SQL_RE.match(sql)
    if not m:
        raise QueryError(f"unsupported SQL: {sql!r}")
    cols_raw = m.group("cols").strip()
    cols: "list | None" = None
    aggs: list = []          # (fn, arg_col_or_None, output_name)
    if cols_raw != "*":
        cols = []
        for item in _split_select_items(cols_raw):
            alias = ""
            am = _AS_RE.match(item)
            if am:
                item, alias = am.group("expr").strip(), \
                    am.group("alias")
            gm = _AGG_RE.match(item)
            if gm:
                fn = gm.group("fn").lower()
                arg = gm.group("arg").strip('"')
                if arg == "*":
                    if fn != "count":
                        raise QueryError(f"{fn}(*) is not valid")
                    arg = None
                aggs.append((fn, arg,
                             alias or f"{fn}({arg or '*'})"))
            else:
                cols.append((item.strip('"'),
                             alias or item.strip('"')))
    conds = [_parse_cond(p)
             for p in _split_conjuncts(m.group("where") or "")]
    group_by = [c.strip().strip('"')
                for c in (m.group("group") or "").split(",")
                if c.strip()]
    if aggs and cols and not group_by:
        raise QueryError("plain columns beside aggregates need "
                         "GROUP BY")
    if group_by and not aggs:
        raise QueryError("GROUP BY needs at least one aggregate")
    if group_by:
        grouped = {c for c, _a in (cols or [])}
        if grouped - set(group_by):
            raise QueryError(
                f"non-grouped columns {sorted(grouped - set(group_by))} "
                "in an aggregate select")
    limit = int(m.group("limit")) if m.group("limit") else None
    offset = int(m.group("offset")) if m.group("offset") else 0
    return {"cols": cols, "aggs": aggs, "group_by": group_by,
            "conds": conds, "limit": limit, "offset": offset}


def _get_path(row: dict, col: str):
    cur = row
    for part in col.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def _like_match(got, pattern: str) -> bool:
    """SQL LIKE: % = any run, _ = one char (translated to fnmatch;
    fnmatch's own specials are escaped first)."""
    if not isinstance(got, str):
        got = "" if got is None else str(got)
    out = []
    for ch in pattern:
        if ch == "%":
            out.append("*")
        elif ch == "_":
            out.append("?")
        elif ch in "*?[":
            out.append(f"[{ch}]")      # literal under fnmatch
        else:
            out.append(ch)
    return fnmatch.fnmatchcase(got, "".join(out))


def _matches(row: dict, conds) -> bool:
    for col, op, want in conds:
        got = _get_path(row, col)
        if op == "is null":
            if got is not None:
                return False
            continue
        if op == "is not null":
            if got is None:
                return False
            continue
        if op in ("like", "not like"):
            if got is None:
                return False    # SQL 3VL: NULL satisfies neither
            hit = _like_match(got, want)
            if hit == (op == "not like"):
                return False
            continue
        if got is None and want is not None:
            return False
        # CSV fields arrive as strings; coerce toward the literal type
        if isinstance(want, (int, float)) and isinstance(got, str):
            try:
                got = float(got) if isinstance(want, float) else \
                    int(got)
            except ValueError:
                return False
        try:
            if not _OPS[op](got, want):
                return False
        except TypeError:
            return False
    return True


def _rowgroup_may_match(md_rg, conds) -> bool:
    """Row-group statistics pruning (the reference prunes parquet row
    groups by min/max the same way, query/engine/aggregations.go:40):
    False only when some conjunct PROVABLY matches no row of the
    group.  Missing/typeless stats keep the group."""
    cols = {md_rg.column(i).path_in_schema: md_rg.column(i)
            for i in range(md_rg.num_columns)}
    for col, op, want in conds:
        c = cols.get(col)
        if c is None or not isinstance(want, (int, float)) or \
                isinstance(want, bool):
            continue
        stats = c.statistics
        if stats is None or not stats.has_min_max or \
                not isinstance(stats.min, (int, float)):
            continue
        lo, hi = stats.min, stats.max
        if (op in ("=", "<=", "<") and lo > want) or \
                (op in ("=", ">=", ">") and hi < want) or \
                (op == "<" and lo >= want) or \
                (op == ">" and hi <= want):
            return False
    return True


def _parquet_rows(data: bytes, conds):
    """Parquet scan with row-group pruning; rows surface as plain
    dicts (binary columns decoded latin-1 so predicates on text-ish
    bytes behave)."""
    try:
        import pyarrow.parquet as pq
    except ImportError:  # pragma: no cover
        raise QueryError("parquet support requires pyarrow")
    try:
        pf = pq.ParquetFile(io.BytesIO(data))
    except Exception as e:
        raise QueryError(f"malformed parquet: {e}")
    for rg in range(pf.num_row_groups):
        if not _rowgroup_may_match(pf.metadata.row_group(rg), conds):
            continue
        table = pf.read_row_group(rg)
        for row in table.to_pylist():
            yield {k: (v.decode("latin-1")
                       if isinstance(v, bytes) else v)
                   for k, v in row.items()}


def _rows_from(data: bytes, input_format: str,
               csv_header: bool = True, conds=()):
    if input_format == "parquet":
        yield from _parquet_rows(data, conds)
    elif input_format == "json":
        for line in data.splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                raise QueryError("malformed JSON record")
    elif input_format == "csv":
        text = data.decode("utf-8", errors="replace")
        reader = csv.reader(io.StringIO(text))
        rows = iter(reader)
        if csv_header:
            header = next(rows, None)
            if header is None:
                return
            for r in rows:
                yield dict(zip(header, r))
        else:
            for r in rows:
                yield {f"_{i + 1}": v for i, v in enumerate(r)}
    else:
        raise QueryError(f"unsupported input format {input_format!r}")


class _Acc:
    """One aggregate accumulator (aggregations.go state shape)."""

    def __init__(self, fn: str):
        self.fn = fn
        self.count = 0
        self.total = 0.0
        self.min = None
        self.max = None

    def add(self, val) -> None:
        if self.fn == "count":
            if val is not None:       # COUNT(col) skips nulls;
                self.count += 1       # COUNT(*) feeds a constant
            return
        if val is None:
            return
        if isinstance(val, str):
            # CSV fields arrive as strings: MIN/MAX must compare
            # numerically when the value IS numeric (lexicographic
            # '10' < '9' is wrong); non-numeric strings stay strings
            try:
                val = float(val)
            except ValueError:
                if self.fn in ("sum", "avg"):
                    return
        if self.fn in ("sum", "avg"):
            # only genuine numbers feed the divisor — a dict/list/
            # bool incrementing count would skew AVG
            if isinstance(val, bool) or \
                    not isinstance(val, (int, float)):
                return
            self.count += 1
            self.total += val
            return
        if not isinstance(val, (str, int, float)) or \
                isinstance(val, bool):
            return                       # unorderable for MIN/MAX
        self.count += 1
        try:
            if self.min is None or val < self.min:
                self.min = val
            if self.max is None or val > self.max:
                self.max = val
        except TypeError:
            pass

    def result(self):
        if self.fn == "count":
            return self.count
        if self.fn == "sum":
            return self.total if self.count else None
        if self.fn == "avg":
            return self.total / self.count if self.count else None
        return self.min if self.fn == "min" else self.max


def _parquet_metadata_fastpath(q: dict, data: bytes):
    """aggregations.go metadata shortcuts: COUNT(*) from row-group
    row counts, MIN/MAX from column statistics — no data read.  None
    when the query shape or the file's stats don't allow it."""
    if q["conds"] or q["group_by"] or not q["aggs"] or q["cols"]:
        return None
    try:
        import pyarrow.parquet as pq
        pf = pq.ParquetFile(io.BytesIO(data))
    except Exception:
        return None
    md = pf.metadata
    out = {}
    for fn, arg, name in q["aggs"]:
        if fn == "count" and arg is None:
            out[name] = md.num_rows
            continue
        if fn in ("min", "max") and arg is not None:
            vals = []
            for rg in range(md.num_row_groups):
                col = next(
                    (md.row_group(rg).column(i)
                     for i in range(md.row_group(rg).num_columns)
                     if md.row_group(rg).column(i).path_in_schema
                     == arg), None)
                st = col.statistics if col is not None else None
                if st is None or not st.has_min_max:
                    return None        # stats gap: scan instead
                vals.append(st.min if fn == "min" else st.max)
            if not vals:
                return None
            out[name] = min(vals) if fn == "min" else max(vals)
            continue
        return None                    # SUM/AVG/COUNT(col): scan
    return [out]


def run_query(sql: str, data: bytes, input_format: str = "json",
              csv_header: bool = True) -> "list[dict]":
    """Evaluate; returns the projected rows (aggregate queries return
    one row per group, or a single row without GROUP BY)."""
    q = parse_sql(sql)
    if q["limit"] == 0:
        return []
    if q["aggs"]:
        if input_format == "parquet":
            fast = _parquet_metadata_fastpath(q, data)
            if fast is not None:
                lo = q["offset"]
                hi = None if q["limit"] is None else lo + q["limit"]
                return fast[lo:hi]   # same pagination as the scan
        groups: dict = {}
        for row in _rows_from(data, input_format, csv_header,
                              q["conds"]):
            if not _matches(row, q["conds"]):
                continue
            key = tuple(_get_path(row, c) for c in q["group_by"])
            accs = groups.get(key)
            if accs is None:
                accs = groups[key] = [_Acc(fn)
                                      for fn, _a, _n in q["aggs"]]
            for acc, (fn, arg, _n) in zip(accs, q["aggs"]):
                acc.add(1 if arg is None else _get_path(row, arg))
        if not q["group_by"] and not groups:
            groups[()] = [_Acc(fn) for fn, _a, _n in q["aggs"]]
        out = []
        for key in sorted(groups,
                          key=lambda k: tuple(str(x) for x in k)):
            row_out = {}
            for (col, alias) in (q["cols"] or []):
                row_out[alias] = key[q["group_by"].index(col)]
            for acc, (_fn, _arg, name) in zip(groups[key],
                                              q["aggs"]):
                row_out[name] = acc.result()
            out.append(row_out)
        lo = q["offset"]
        hi = None if q["limit"] is None else lo + q["limit"]
        return out[lo:hi]
    out = []
    skipped = 0
    for row in _rows_from(data, input_format, csv_header,
                          q["conds"]):
        if not _matches(row, q["conds"]):
            continue
        if skipped < q["offset"]:
            skipped += 1
            continue
        if q["cols"] is None:
            out.append(row)
        else:
            out.append({alias: _get_path(row, c)
                        for c, alias in q["cols"]})
        if q["limit"] is not None and len(out) >= q["limit"]:
            break
    return out
