"""Query engine (weed/query/engine/): SQL-subset select over stored
JSON/CSV objects, served by the volume Query RPC
(volume_server.proto:132) and the S3 Select surface."""

from .engine import QueryError, run_query  # noqa: F401
