"""wdclient follow stream: a push-fed vid map.

The reference's wdclient keeps a KeepConnected stream open to the
master and applies pushed VolumeLocation deltas to its vid map, so
lookups are local and leadership changes propagate instantly
(weed/wdclient/masterclient.go:417-471, vid_map.go).  This is that
client: a background thread long-polls the master's /cluster/watch
endpoint (the HTTP leg of the same LocationHub the gRPC KeepConnected
stream serves), maintains vid -> locations, and feeds the discovered
leader back into operation's leader cache.

Long-lived processes (filer, mount, gateways) call
operation.enable_follow(master); one-shot CLI verbs keep using the
TTL'd lookup cache.
"""

from __future__ import annotations

import threading


class MasterFollower:
    def __init__(self, master: str, poll_timeout: float = 25.0):
        self.master = master
        self.poll_timeout = poll_timeout
        # the address the stream loop actually polls.  It starts at the
        # configured seed (possibly a comma list) and FOLLOWS THE
        # LEADER: every watch response and every {"leader": ...} hub
        # event re-points it, so after a graceful transfer the follower
        # re-dials the new leader on the next turn instead of riding
        # 503 redirect hints off the old one (masterclient.go re-dials
        # on the leader announced over KeepConnected).  Stream errors
        # reset it to the seed list.
        self._target = master
        self._lock = threading.Lock()
        self._vids: dict[int, dict[str, dict]] = {}  # vid -> url -> loc
        self._leader: str | None = None
        self._synced = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- consumer surface ----------------------------------------------

    def get_locations(self, vid: int) -> "list[dict] | None":
        """Pushed locations for a vid; None for unknown/unsynced — the
        caller falls back to a lookup RPC (same contract as the
        reference vid_map: a miss is a miss, the RPC is authoritative;
        a push event for a freshly grown volume may trail the assign
        that referenced it)."""
        if not self._synced.is_set():
            return None
        with self._lock:
            m = self._vids.get(vid)
            return list(m.values()) if m else None

    @property
    def leader(self) -> "str | None":
        return self._leader

    @property
    def target(self) -> str:
        """Where the stream loop is currently pointed (the discovered
        leader once one is known; the configured seed otherwise)."""
        return self._target

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "MasterFollower":
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        # no join at all: the thread is almost always parked inside
        # the 25s long poll, so even a short join timeout burns its
        # FULL budget on every filer/gateway shutdown (0.2s here was
        # ~15s of every tier-1 run across teardowns).  It is a daemon
        # checking _stop at each loop turn and in its backoff wait —
        # let it drain on its own.

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._synced.wait(timeout)

    # -- stream loop ----------------------------------------------------

    def _run(self) -> None:
        from .operation import master_json
        from .util import retry as _retry
        cursor = -1
        failures = 0
        while not self._stop.is_set():
            try:
                if cursor < 0:
                    # background follower thread: no request deadline
                    # is ever armed here, and the snapshot bound is a
                    # deliberate fixed choice
                    r = master_json(self._target, "GET",
                                    "/cluster/watch?snapshot=1",
                                    timeout=10)  # noqa: SWFS016
                    if "error" in r:  # http_json returns error bodies
                        raise OSError(r["error"])  # as dicts, unraised
                    self._apply_snapshot(r.get("snapshot") or {})
                    self._note_leader(r.get("leader"))
                    cursor = int(r.get("cursor", 0))
                    self._synced.set()
                    failures = 0
                    continue
                r = master_json(
                    self._target, "GET",
                    f"/cluster/watch?since={cursor}"
                    f"&timeout={self.poll_timeout}",
                    timeout=self.poll_timeout + 10)
                if "error" in r:
                    raise OSError(r["error"])
                if r.get("lagged"):
                    cursor = -1  # resync from a fresh snapshot
                    self._synced.clear()
                    continue
                failures = 0
                cursor = int(r.get("cursor", cursor))
                moved = self._note_leader(r.get("leader"))
                for ev in r.get("events", []):
                    if "leader" in ev:
                        # leadership handed over mid-stream: the hub
                        # publishes {"leader": X} the moment X wins
                        moved = self._note_leader(ev["leader"]) or moved
                        continue
                    self._apply_event(ev)
                if moved:
                    # the stream we were riding is no longer the
                    # leader's hub — a new leader starts a fresh hub,
                    # so cursors don't carry over; resync against it
                    cursor = -1
                    self._synced.clear()
            except (OSError, ValueError):
                # master unreachable / erroring / failover in
                # progress: back off under the unified jittered policy
                # (util/retry), then resync (leadership may have
                # moved, and a new leader starts a fresh hub — cursors
                # don't carry over).  A REFUSED connect fails in
                # microseconds: the seed's fixed 1s re-poll hammered a
                # partitioned master and flooded its logs, while the
                # growing full-jitter delay (0.5s base, 15s cap) also
                # decorrelates the reconnect stampede when the master
                # comes back and every follower notices at once.
                self._synced.clear()
                cursor = -1
                failures += 1
                # a leader we re-targeted onto may be the thing that
                # just died — fall back to the configured seed list,
                # whose redirect hints rediscover whoever leads now
                self._target = self.master
                self._stop.wait(max(
                    0.05, _retry.backoff_delay(failures, base=0.5,
                                               cap=15.0)))

    def _note_leader(self, leader: "str | None") -> bool:
        """Record a leader announcement; returns True when it moved the
        poll target (the caller must then resync — the new leader's hub
        is fresh and our cursor means nothing there)."""
        if leader and leader != self._leader:
            self._leader = leader
            from . import operation
            with operation._leader_lock:
                operation._leader_cache[self.master] = leader
        if leader and leader != self._target:
            self._target = leader
            return True
        return False

    def _apply_snapshot(self, topo: dict) -> None:
        """EC shard locations deliberately stay RPC-resolved
        (/dir/ec_lookup): the degraded-read path needs per-shard
        placement, which the push events don't carry."""
        vids: dict[int, dict[str, dict]] = {}
        for dc in (topo.get("dataCenters") or {}).values():
            for rack in dc.get("racks", {}).values():
                for node in rack.get("nodes", []):
                    loc = {"url": node["url"],
                           "publicUrl": node.get("publicUrl",
                                                 node["url"])}
                    for v in node.get("volumes", []):
                        vids.setdefault(v["id"], {})[loc["url"]] = loc
        with self._lock:
            self._vids = vids

    def _apply_event(self, ev: dict) -> None:
        if "url" not in ev:
            return  # leader-only events are handled via _note_leader
        loc = {"url": ev["url"],
               "publicUrl": ev.get("publicUrl", ev["url"])}
        with self._lock:
            for vid in ev.get("newVids", []):
                self._vids.setdefault(vid, {})[loc["url"]] = loc
            for vid in ev.get("deletedVids", []):
                m = self._vids.get(vid)
                if m:
                    m.pop(loc["url"], None)
                    if not m:
                        self._vids.pop(vid, None)
