"""Cluster metadata: DC -> rack -> data-node tree, volume layouts,
placement, and the EC shard registry (weed/topology)."""

from .topology import Topology, DataNodeInfo  # noqa: F401


def iter_volume_list_nodes(volume_list: dict):
    """Yield node dicts from a /vol/list JSON tree."""
    for dc in volume_list.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            yield from rack.get("nodes", [])


def iter_volume_list_volumes(volume_list: dict):
    """Yield (node, volume) pairs — the canonical walk shared by the
    shell and every detection handler."""
    for node in iter_volume_list_nodes(volume_list):
        for v in node.get("volumes", []):
            yield node, v


def iter_volume_list_ec_shards(volume_list: dict):
    for node in iter_volume_list_nodes(volume_list):
        for e in node.get("ecShards", []):
            yield node, e


def fetch_ec_shard_locations(master: str, vid: int
                             ) -> "dict[str, list[int]]":
    """{url: [shard ids]} from the master's /dir/ec_lookup — the one
    parser for that payload (shell, repair worker, and the streaming
    rebuild handler all consume it)."""
    from ..operation import master_json
    r = master_json(master, "GET", f"/dir/ec_lookup?volumeId={vid}",
            timeout=30)
    if "error" in r:
        return {}
    return {loc["url"]: loc["shardIds"]
            for loc in r.get("shardIdLocations", [])}


def shard_ids_to_urls(locations: "dict[str, list[int]]"
                      ) -> "dict[str, list[str]]":
    """Invert {url: [sids]} into the {str(sid): [urls]} shape the
    streaming /admin/ec/rebuild payload carries."""
    out: dict[str, list[str]] = {}
    for url, sids in locations.items():
        for sid in sids:
            out.setdefault(str(sid), []).append(url)
    return out
