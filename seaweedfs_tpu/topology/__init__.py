"""Cluster metadata: DC -> rack -> data-node tree, volume layouts,
placement, and the EC shard registry (weed/topology)."""

from .topology import Topology, DataNodeInfo  # noqa: F401
