"""Cluster metadata: DC -> rack -> data-node tree, volume layouts,
placement, and the EC shard registry (weed/topology)."""

from .topology import Topology, DataNodeInfo  # noqa: F401


def iter_volume_list_nodes(volume_list: dict):
    """Yield node dicts from a /vol/list JSON tree."""
    for dc in volume_list.get("dataCenters", {}).values():
        for rack in dc.get("racks", {}).values():
            yield from rack.get("nodes", [])


def iter_volume_list_volumes(volume_list: dict):
    """Yield (node, volume) pairs — the canonical walk shared by the
    shell and every detection handler."""
    for node in iter_volume_list_nodes(volume_list):
        for v in node.get("volumes", []):
            yield node, v


def iter_volume_list_ec_shards(volume_list: dict):
    for node in iter_volume_list_nodes(volume_list):
        for e in node.get("ecShards", []):
            yield node, e
