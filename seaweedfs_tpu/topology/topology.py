"""Topology tree + volume layouts + EC shard registry.

Mirrors the behavior of weed/topology/topology.go (Topology,
:322 PickForWrite), volume_layout.go (writable lists per
(collection, replication, ttl)), data_center.go/rack.go/data_node.go
(the tree), and topology_ec.go:124 RegisterEcShards / :153
LookupEcShards.  The Go pointer-tree with per-node locks collapses to
plain dataclasses under one topology lock (single master process).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass, field

from ..storage.replica_placement import ReplicaPlacement


@dataclass
class VolumeInfo:
    id: int
    collection: str = ""
    size: int = 0
    file_count: int = 0
    delete_count: int = 0
    deleted_byte_count: int = 0
    read_only: bool = False
    replica_placement: int = 0
    ttl: int = 0
    version: int = 3


@dataclass
class EcShardInfo:
    volume_id: int
    collection: str = ""
    shard_bits: int = 0  # bitmask of shard ids present on the node
    data_shards: int = 10
    parity_shards: int = 4

    @property
    def shard_ids(self) -> list[int]:
        return [s for s in range(32) if self.shard_bits & (1 << s)]


@dataclass
class DataNodeInfo:
    """One volume server (weed/topology/data_node.go)."""

    url: str                  # ip:port — the node's identity
    public_url: str = ""
    data_center: str = "DefaultDataCenter"
    rack: str = "DefaultRack"
    max_volume_count: int = 8
    volumes: dict[int, VolumeInfo] = field(default_factory=dict)
    ec_shards: dict[int, EcShardInfo] = field(default_factory=dict)
    last_seen: float = 0.0

    @property
    def volume_count(self) -> int:
        return len(self.volumes)

    @property
    def free_space(self) -> int:
        return self.max_volume_count - len(self.volumes)


class Topology:
    """weed/topology/topology.go:76."""

    def __init__(self, volume_size_limit: int = 8 * 1024 * 1024 * 1024,
                 pulse_seconds: float = 5.0):
        self.lock = threading.RLock()
        self.nodes: dict[str, DataNodeInfo] = {}
        self.volume_size_limit = volume_size_limit
        self.pulse_seconds = pulse_seconds
        self._max_volume_id = 0
        import itertools
        self._pick_rr = itertools.count()

    # -- heartbeat registration (topology.go RegisterVolumeLayout etc) ----

    def register_heartbeat(self, hb: dict) -> None:
        url = f"{hb['ip']}:{hb['port']}"
        with self.lock:
            node = self.nodes.get(url)
            if node is None:
                node = DataNodeInfo(url=url)
                self.nodes[url] = node
            node.public_url = hb.get("publicUrl", url)
            node.data_center = hb.get("dataCenter") or node.data_center
            node.rack = hb.get("rack") or node.rack
            node.max_volume_count = hb.get("maxVolumeCount",
                                           node.max_volume_count)
            node.last_seen = time.monotonic()
            node.volumes = {
                v["id"]: VolumeInfo(
                    id=v["id"], collection=v.get("collection", ""),
                    size=v.get("size", 0),
                    file_count=v.get("fileCount", 0),
                    delete_count=v.get("deleteCount", 0),
                    deleted_byte_count=v.get("deletedByteCount", 0),
                    read_only=v.get("readOnly", False),
                    replica_placement=v.get("replicaPlacement", 0),
                    ttl=v.get("ttl", 0), version=v.get("version", 3))
                for v in hb.get("volumes", [])}
            node.ec_shards = {
                e["id"]: EcShardInfo(
                    volume_id=e["id"], collection=e.get("collection", ""),
                    shard_bits=e.get("ecIndexBits", 0),
                    data_shards=e.get("dataShards", 10),
                    parity_shards=e.get("parityShards", 4))
                for e in hb.get("ecShards", [])}
            for vid in node.volumes:
                self._max_volume_id = max(self._max_volume_id, vid)
            for vid in node.ec_shards:
                self._max_volume_id = max(self._max_volume_id, vid)

    def _liveness_deadline(self) -> float:
        # heartbeat ages on the monotonic clock (SWFS011): an NTP step
        # backwards would otherwise declare the whole fleet dead, and
        # a step forward would immortalize nodes that stopped pulsing
        return time.monotonic() - 3 * self.pulse_seconds

    def alive_nodes(self) -> list[DataNodeInfo]:
        deadline = self._liveness_deadline()
        with self.lock:
            return [n for n in self.nodes.values()
                    if n.last_seen >= deadline]

    def mark_dead(self, url: str) -> None:
        """Immediately expire a node observed unreachable (the analog of
        topology_event_handling.go UnRegisterDataNode on a broken
        heartbeat stream) — don't wait out the missed-pulse deadline."""
        with self.lock:
            n = self.nodes.get(url)
            if n is not None:
                n.last_seen = 0.0

    # -- volume id assignment ---------------------------------------------

    def next_volume_id(self) -> int:
        with self.lock:
            self._max_volume_id += 1
            return self._max_volume_id

    # -- lookups (master_grpc_server_volume.go LookupVolume,
    #    topology_ec.go:153 LookupEcShards) -------------------------------

    def lookup(self, vid: int, collection: str | None = None) -> list[dict]:
        """All locations serving volume vid (normal or EC)."""
        out = []
        with self.lock:
            for node in self.nodes.values():
                v = node.volumes.get(vid)
                if v is not None and \
                        (collection is None or v.collection == collection):
                    out.append({"url": node.url,
                                "publicUrl": node.public_url})
            if not out:
                for node in self.nodes.values():
                    e = node.ec_shards.get(vid)
                    if e is not None:
                        out.append({"url": node.url,
                                    "publicUrl": node.public_url,
                                    "shardBits": e.shard_bits})
        return out

    def lookup_ec_shards(self, vid: int) -> dict[str, list[int]]:
        """url -> shard ids (topology_ec.go:153)."""
        out: dict[str, list[int]] = {}
        with self.lock:
            for node in self.nodes.values():
                e = node.ec_shards.get(vid)
                if e is not None:
                    out[node.url] = e.shard_ids
        return out

    # -- write placement (topology.go:322 PickForWrite +
    #    volume_layout.go writable selection) ----------------------------

    def writable_volumes(self, collection: str = "", replication: str = "",
                         ttl_u32: int = 0) -> list[tuple[int, list[DataNodeInfo]]]:
        """(vid, nodes) groups satisfying (collection, rp, ttl), not
        read-only and under the size limit, with a full replica set."""
        rp = ReplicaPlacement.from_string(replication or "000")
        want_copies = rp.copy_count()
        by_vid: dict[int, list[DataNodeInfo]] = {}
        deadline = self._liveness_deadline()
        with self.lock:
            for node in self.nodes.values():
                if node.last_seen < deadline:
                    # a disconnected node's volumes leave the writable
                    # set (volume_layout.go SetVolumeUnavailable)
                    continue
                for vid, v in node.volumes.items():
                    if v.collection != collection:
                        continue
                    if replication and v.replica_placement != rp.byte():
                        continue
                    if v.ttl != ttl_u32:
                        continue
                    if v.read_only or v.size >= self.volume_size_limit:
                        continue
                    by_vid.setdefault(vid, []).append(node)
        return [(vid, nodes) for vid, nodes in by_vid.items()
                if len(nodes) >= want_copies]

    def pick_for_write(self, collection: str = "", replication: str = "",
                       ttl_u32: int = 0) -> tuple[int, list[DataNodeInfo]]:
        candidates = self.writable_volumes(collection, replication, ttl_u32)
        if not candidates:
            raise LookupError("no writable volumes")
        # round-robin, not random.choice: with clients batching fids
        # (assign?count=N windows) each assign pins a volume for many
        # writes, and random selection leaves streaks where several
        # gateways hammer one volume while its siblings idle — strict
        # rotation keeps the per-volume write load even
        candidates.sort(key=lambda c: c[0])
        return candidates[next(self._pick_rr) % len(candidates)]

    # -- growth (volume_growth.go) ----------------------------------------

    def plan_growth(self, replication: str = "",
                    exclude: set[str] | None = None
                    ) -> list[DataNodeInfo]:
        """Pick target nodes for a new volume's replica set honoring the
        xyz placement (volume_growth.go findEmptySlotsForOneVolume,
        simplified: grouped by DC then rack with free-slot weighting).
        `exclude` drops nodes that just refused an allocation."""
        rp = ReplicaPlacement.from_string(replication or "000")
        alive = [n for n in self.alive_nodes()
                 if n.free_space > 0 and n.url not in (exclude or ())]
        if not alive:
            raise LookupError("no free volume slots in cluster")
        main = max(alive, key=lambda n: (n.free_space, random.random()))
        picked = [main]

        def pick(pool, count, err):
            chosen = []
            pool = [n for n in pool if n not in picked and n.free_space > 0]
            if len(pool) < count:
                raise LookupError(err)
            pool.sort(key=lambda n: (-n.free_space, random.random()))
            chosen.extend(pool[:count])
            return chosen

        picked += pick([n for n in alive
                        if n.data_center == main.data_center
                        and n.rack == main.rack],
                       rp.same_rack_count,
                       "not enough same-rack nodes")
        picked += pick([n for n in alive
                        if n.data_center == main.data_center
                        and n.rack != main.rack],
                       rp.diff_rack_count,
                       "not enough cross-rack nodes")
        picked += pick([n for n in alive
                        if n.data_center != main.data_center],
                       rp.diff_data_center_count,
                       "not enough cross-DC nodes")
        return picked

    # -- full cluster snapshot (master_grpc_server_volume.go VolumeList) --

    def to_volume_list(self) -> dict:
        with self.lock:
            dcs: dict[str, dict] = {}
            for node in self.nodes.values():
                dc = dcs.setdefault(node.data_center, {"racks": {}})
                rack = dc["racks"].setdefault(node.rack, {"nodes": []})
                rack["nodes"].append({
                    "url": node.url,
                    "publicUrl": node.public_url,
                    "maxVolumeCount": node.max_volume_count,
                    # camelCase field names: same wire contract as the
                    # heartbeat messages (VolumeInformationMessage)
                    "volumes": [{
                        "id": v.id,
                        "collection": v.collection,
                        "size": v.size,
                        "fileCount": v.file_count,
                        "deleteCount": v.delete_count,
                        "deletedByteCount": v.deleted_byte_count,
                        "readOnly": v.read_only,
                        "replicaPlacement": v.replica_placement,
                        "ttl": v.ttl,
                        "version": v.version,
                    } for v in node.volumes.values()],
                    "ecShards": [{
                        "volumeId": e.volume_id,
                        "collection": e.collection,
                        "shardBits": e.shard_bits,
                        "dataShards": e.data_shards,
                        "parityShards": e.parity_shards,
                    } for e in node.ec_shards.values()],
                })
            return {"maxVolumeId": self._max_volume_id,
                    "dataCenters": dcs}
