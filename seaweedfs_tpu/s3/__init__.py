"""S3 API gateway over the filer (weed/s3api)."""

from .s3_server import S3ApiServer  # noqa: F401
