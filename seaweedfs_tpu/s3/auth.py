"""AWS Signature V4 signing + verification
(weed/s3api/auth_signature_v4.go).

Implements the standard SigV4 flow: canonical request -> string to sign
-> derived signing key -> HMAC signature.  The same primitives serve
both the server-side verifier and the client-side signer used by tests
and tools (the cross-checking the reference gets from s3tests).
"""

from __future__ import annotations

import hashlib
import hmac
import urllib.parse
from datetime import datetime, timezone

ALGORITHM = "AWS4-HMAC-SHA256"
UNSIGNED_PAYLOAD = "UNSIGNED-PAYLOAD"


def _sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def uri_encode(s: str, encode_slash: bool = True) -> str:
    safe = "-._~" + ("" if encode_slash else "/")
    return urllib.parse.quote(s, safe=safe)


def canonical_request(method: str, canonical_uri: str, query: dict,
                      headers: dict, signed_headers: list[str],
                      payload_hash: str) -> str:
    """canonical_uri must be the WIRE form of the path (already
    percent-encoded once) — re-encoding here would double-encode keys
    with spaces/unicode and break verification for real clients."""
    cq = "&".join(
        f"{uri_encode(k)}={uri_encode(str(v))}"
        for k, v in sorted(query.items()))
    ch = "".join(
        f"{h}:{' '.join(str(headers.get(h, '')).split())}\n"
        for h in signed_headers)
    return "\n".join([
        method,
        canonical_uri or "/",
        cq,
        ch,
        ";".join(signed_headers),
        payload_hash,
    ])


def signing_key(secret: str, date: str, region: str,
                service: str = "s3") -> bytes:
    k = _hmac(("AWS4" + secret).encode(), date)
    k = hmac.new(k, region.encode(), hashlib.sha256).digest()
    k = hmac.new(k, service.encode(), hashlib.sha256).digest()
    return hmac.new(k, b"aws4_request", hashlib.sha256).digest()


def string_to_sign(amz_date: str, scope: str, creq: str) -> str:
    return "\n".join([ALGORITHM, amz_date, scope, _sha256(creq.encode())])


def sign_request(method: str, host: str, path: str, query: dict,
                 headers: dict, payload: bytes, access_key: str,
                 secret_key: str, region: str = "us-east-1",
                 amz_date: str | None = None,
                 service: str = "s3") -> dict:
    """Client-side signer: returns headers with Authorization added.
    `path` is the raw (unencoded) path; the request must be sent to
    its once-encoded form (`uri_encode(path, False)`)."""
    if amz_date is None:
        amz_date = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    payload_hash = _sha256(payload)
    headers = {k.lower(): v for k, v in headers.items()}
    headers.setdefault("host", host)
    headers["x-amz-date"] = amz_date
    headers["x-amz-content-sha256"] = payload_hash
    signed = sorted(h for h in headers
                    if h in ("host", "content-type") or
                    h.startswith("x-amz-"))
    creq = canonical_request(method, uri_encode(path, False), query,
                             headers, signed, payload_hash)
    scope = f"{date}/{region}/{service}/aws4_request"
    sts = string_to_sign(amz_date, scope, creq)
    sig = hmac.new(signing_key(secret_key, date, region, service),
                   sts.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"{ALGORITHM} Credential={access_key}/{scope}, "
        f"SignedHeaders={';'.join(signed)}, Signature={sig}")
    return headers


STREAMING_PAYLOAD = "STREAMING-AWS4-HMAC-SHA256-PAYLOAD"


def presign_url(method: str, host: str, path: str, query: dict,
                access_key: str, secret_key: str, expires: int = 3600,
                region: str = "us-east-1",
                amz_date: str | None = None) -> str:
    """Client-side presigner (the URL form of SigV4 — what
    `aws s3 presign` emits; verified by s3api auth query-string path).
    Returns the full URL (without scheme)."""
    if amz_date is None:
        amz_date = datetime.now(timezone.utc).strftime("%Y%m%dT%H%M%SZ")
    date = amz_date[:8]
    scope = f"{date}/{region}/s3/aws4_request"
    q = dict(query)
    q.update({
        "X-Amz-Algorithm": ALGORITHM,
        "X-Amz-Credential": f"{access_key}/{scope}",
        "X-Amz-Date": amz_date,
        "X-Amz-Expires": str(expires),
        "X-Amz-SignedHeaders": "host",
    })
    creq = canonical_request(method, uri_encode(path, False), q,
                             {"host": host}, ["host"],
                             UNSIGNED_PAYLOAD)
    sts = string_to_sign(amz_date, scope, creq)
    sig = hmac.new(signing_key(secret_key, date, region),
                   sts.encode(), hashlib.sha256).hexdigest()
    q["X-Amz-Signature"] = sig
    qs = "&".join(f"{uri_encode(k)}={uri_encode(v)}"
                  for k, v in sorted(q.items()))
    return f"{host}{uri_encode(path, False)}?{qs}"


def chunk_string_to_sign(prev_signature: str, amz_date: str, scope: str,
                         chunk_data: bytes) -> str:
    """Per-chunk string-to-sign of the streaming-chunked upload format
    (s3api/chunked_reader_v4.go buildChunkStringToSign)."""
    return "\n".join([
        "AWS4-HMAC-SHA256-PAYLOAD", amz_date, scope, prev_signature,
        _sha256(b""), _sha256(chunk_data)])


class AuthContext:
    """What a successful header-auth verification learned — the seed
    the streaming-chunked body verifier needs
    (chunked_reader_v4.go newSignV4ChunkedReader)."""

    def __init__(self, identity: str, seed_signature: str,
                 signing_key: bytes, amz_date: str, scope: str,
                 payload_hash: str, sts_identity=None):
        self.identity = identity
        self.seed_signature = seed_signature
        self.signing_key = signing_key
        self.amz_date = amz_date
        self.scope = scope
        self.payload_hash = payload_hash
        # ephemeral iam.Identity resolved from an STS session token —
        # authorization must use its role actions, not a store lookup
        self.sts_identity = sts_identity

    @property
    def is_streaming(self) -> bool:
        return self.payload_hash == STREAMING_PAYLOAD


class SigV4Verifier:
    """Server-side verification (auth_signature_v4.go doesSignatureMatch
    + the reference's 15-minute request-time window).  Handles both
    header auth (Authorization) and query auth (presigned URLs,
    auth_signature_v4.go doesPresignedSignatureMatch)."""

    MAX_SKEW_SECONDS = 15 * 60

    def __init__(self, credentials, sts=None):
        # anything with .get(access_key) -> secret: a plain dict or an
        # IdentityStore.secrets_view()
        self.credentials = credentials
        self.sts = sts  # optional iam.StsService for temp credentials

    def _lookup_secret(self, access_key: str, token: str
                       ) -> "tuple[str | None, object | None]":
        """(secret, sts_identity): static store first, then STS
        session-token resolution (s3api auth: x-amz-security-token)."""
        secret = self.credentials.get(access_key)
        if secret is not None:
            return secret, None
        if self.sts is not None and token:
            resolved = self.sts.resolve(access_key, token)
            if resolved is not None:
                return resolved
        return None, None

    def verify(self, method: str, path: str, query: dict,
               headers: dict, payload: bytes
               ) -> "tuple[bool, str, AuthContext | None]":
        """Returns (ok, identity-or-error, context).  `path` is the
        wire form (still percent-encoded) — used verbatim as the
        canonical URI.  Query-auth (presigned) requests are routed by
        the presence of X-Amz-Signature in the query."""
        if "X-Amz-Signature" in query:
            ok, who, sts_ident = self._verify_presigned(
                method, path, query, headers)
            ctx = AuthContext(who, "", b"", "", "", UNSIGNED_PAYLOAD,
                              sts_identity=sts_ident) if ok else None
            return ok, who, ctx
        auth = headers.get("authorization", "")
        if not auth.startswith(ALGORITHM):
            return False, "unsupported authorization", None
        try:
            parts = dict(
                p.strip().split("=", 1)
                for p in auth[len(ALGORITHM):].strip().split(","))
            cred = parts["Credential"]
            signed = parts["SignedHeaders"].split(";")
            got_sig = parts["Signature"]
            access_key, date, region, service, _ = cred.split("/")
        except (KeyError, ValueError):
            return False, "malformed authorization header", None
        secret, sts_ident = self._lookup_secret(
            access_key, headers.get("x-amz-security-token", ""))
        if secret is None:
            return False, "unknown access key", None
        amz_date = headers.get("x-amz-date", "")
        skew_err = self._check_date(amz_date, date)
        if skew_err:
            return False, skew_err, None
        payload_hash = headers.get("x-amz-content-sha256") or \
            UNSIGNED_PAYLOAD
        if payload_hash not in (UNSIGNED_PAYLOAD, STREAMING_PAYLOAD):
            if payload_hash != _sha256(payload):
                return False, "payload checksum mismatch", None
        creq = canonical_request(
            method, path, query,
            {k.lower(): v for k, v in headers.items()}, signed,
            payload_hash)
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, creq)
        key = signing_key(secret, date, region, service)
        want = hmac.new(key, sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, got_sig):
            return False, "signature mismatch", None
        return True, access_key, AuthContext(
            access_key, got_sig, key, amz_date, scope, payload_hash,
            sts_identity=sts_ident)

    def _verify_presigned(self, method: str, path: str, query: dict,
                          headers: dict
                          ) -> "tuple[bool, str, object | None]":
        try:
            if query.get("X-Amz-Algorithm") != ALGORITHM:
                return False, "unsupported algorithm", None
            cred = query["X-Amz-Credential"]
            amz_date = query["X-Amz-Date"]
            expires = int(query["X-Amz-Expires"])
            signed = query["X-Amz-SignedHeaders"].split(";")
            got_sig = query["X-Amz-Signature"]
            access_key, date, region, service, _ = cred.split("/")
        except (KeyError, ValueError):
            return False, "malformed presigned query", None
        secret, sts_ident = self._lookup_secret(
            access_key, query.get("X-Amz-Security-Token", ""))
        if secret is None:
            return False, "unknown access key", None
        # expiry: valid from X-Amz-Date for X-Amz-Expires seconds
        # (and Expires itself is capped at 7 days, as AWS does)
        if not 0 < expires <= 7 * 24 * 3600:
            return False, "invalid X-Amz-Expires", None
        try:
            t0 = datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ").replace(tzinfo=timezone.utc)
        except ValueError:
            return False, "malformed X-Amz-Date", None
        if amz_date[:8] != date:
            return False, "credential scope date mismatch", None
        now = datetime.now(timezone.utc)
        if (now - t0).total_seconds() > expires:
            return False, "request has expired", None
        if (t0 - now).total_seconds() > self.MAX_SKEW_SECONDS:
            return False, "request time too skewed", None
        # canonical query = all X-Amz-* params EXCEPT the signature
        q = {k: v for k, v in query.items() if k != "X-Amz-Signature"}
        creq = canonical_request(
            method, path, q,
            {k.lower(): v for k, v in headers.items()}, signed,
            UNSIGNED_PAYLOAD)
        scope = f"{date}/{region}/{service}/aws4_request"
        sts = string_to_sign(amz_date, scope, creq)
        want = hmac.new(signing_key(secret, date, region, service),
                        sts.encode(), hashlib.sha256).hexdigest()
        if not hmac.compare_digest(want, got_sig):
            return False, "signature mismatch", None
        return True, access_key, sts_ident

    def _check_date(self, amz_date: str, scope_date: str) -> str | None:
        """Replay window: x-amz-date within 15 minutes of now and
        consistent with the credential scope date."""
        try:
            req_time = datetime.strptime(
                amz_date, "%Y%m%dT%H%M%SZ").replace(tzinfo=timezone.utc)
        except ValueError:
            return "malformed x-amz-date"
        if amz_date[:8] != scope_date:
            return "credential scope date mismatch"
        now = datetime.now(timezone.utc)
        if abs((now - req_time).total_seconds()) > self.MAX_SKEW_SECONDS:
            return "request time too skewed"
        return None
