"""S3 circuit breaker — concurrent-request admission control
(reference: weed/s3api/s3api_circuit_breaker.go, config shape
s3_pb.S3CircuitBreakerConfig stored at /etc/s3/circuit_breaker.json
per weed/s3api/s3_constants/s3_config.go:8-9).

Limits are on SIMULTANEOUS load, not rates: a request admits by
incrementing in-flight counters (per-bucket and global, request count
and request bytes) and rolls every increment back when it finishes.
Exceeding any limit rejects with the reference's 503 codes
(ErrTooManyRequest / ErrRequestBytesExceed) before any work is done.

Config JSON::

    {"global": {"enabled": true,
                "actions": {"Read:Count": 100, "Write:MB": 64}},
     "buckets": {"img": {"enabled": true,
                         "actions": {"Write:Count": 8}}}}

Action names are the coarse identity actions (Read/Write/List/
Tagging/Admin); limit types are Count and MB (converted to bytes at
load time, matching the reference's LimitTypeBytes counters).
"""

from __future__ import annotations

import json
import threading

CONFIG_DIR = "/etc/s3"
CONFIG_FILE = "circuit_breaker.json"
CONFIG_PATH = CONFIG_DIR + "/" + CONFIG_FILE

_SEP = ":"


def _key(*parts: str) -> str:
    return _SEP.join(parts)


class CircuitBreaker:
    def __init__(self):
        self.enabled = False
        self._lock = threading.Lock()
        self._limits: dict[str, int] = {}
        self._counters: dict[str, int] = {}

    # -- config -----------------------------------------------------------

    def load(self, doc: dict | None) -> None:
        """Replace limits atomically; unknown keys are rejected so a
        typo'd action name fails loudly at config time, not silently
        at enforcement time."""
        limits: dict[str, int] = {}
        if doc:
            glob = doc.get("global", {}) or {}
            # a disabled global section contributes NO limits (its
            # action entries are kept in the JSON so -disable is
            # reversible, matching the reference config model), and
            # per-bucket sections enable independently of it
            if glob.get("enabled", False):
                for action, value in (glob.get("actions", {}) or
                                      {}).items():
                    limits[_key(*_parse_action(action))] = \
                        _to_bytes(action, value)
            else:
                for action, value in (glob.get("actions", {}) or
                                      {}).items():
                    _parse_action(action)        # still validate
                    _to_bytes(action, value)
            for bucket, cfg in (doc.get("buckets", {}) or {}).items():
                if not (cfg or {}).get("enabled", True):
                    continue
                for action, value in (cfg.get("actions", {}) or
                                      {}).items():
                    limits[_key(bucket, *_parse_action(action))] = \
                        _to_bytes(action, value)
        with self._lock:
            self.enabled = bool(limits)
            self._limits = limits
            # in-flight counters survive a reload: requests admitted
            # under the old config still roll back correctly because
            # rollback closures reference keys, not limits

    def load_bytes(self, content: bytes) -> None:
        self.load(json.loads(content) if content else None)

    # -- admission --------------------------------------------------------

    def admit(self, bucket: str, action: str,
              content_length: int):
        """Returns (rollback, error).  error is None when admitted;
        rollback is a zero-arg callable to run when the request
        finishes (always non-None).  Check order matches the
        reference: bucket count, bucket bytes, global count, global
        bytes — with full rollback of partial increments on trip."""
        if not self.enabled:
            return (lambda: None), None
        checks = [(_key(bucket, action, "Count"), 1,
                   "ErrTooManyRequest"),
                  (_key(bucket, action, "Bytes"),
                   max(content_length, 0), "ErrRequestBytesExceed"),
                  (_key(action, "Count"), 1, "ErrTooManyRequest"),
                  (_key(action, "Bytes"), max(content_length, 0),
                   "ErrRequestBytesExceed")]
        taken: list[tuple[str, int]] = []
        with self._lock:
            for key, inc, code in checks:
                limit = self._limits.get(key)
                if limit is None:
                    continue
                new = self._counters.get(key, 0) + inc
                if new > limit:
                    for k, i in taken:
                        self._counters[k] -= i
                    return None, code
                self._counters[key] = new
                taken.append((key, inc))

        def rollback():
            with self._lock:
                for k, i in taken:
                    self._counters[k] -= i
        return rollback, None

    def in_flight(self) -> dict[str, int]:
        with self._lock:
            return {k: v for k, v in self._counters.items() if v}


def _parse_action(spec: str) -> tuple[str, str]:
    action, _, ltype = spec.partition(_SEP)
    if action not in ("Read", "Write", "List", "Tagging", "Admin"):
        raise ValueError(f"unknown circuit-breaker action {action!r}")
    if ltype not in ("Count", "MB", "Bytes"):
        raise ValueError(f"unknown limit type {ltype!r} "
                         "(use Count or MB)")
    return action, ("Bytes" if ltype in ("MB", "Bytes") else "Count")


def _to_bytes(spec: str, value) -> int:
    v = int(value)
    if v <= 0:
        raise ValueError(f"limit for {spec!r} must be positive")
    return v * (1 << 20) if spec.endswith(_SEP + "MB") else v
