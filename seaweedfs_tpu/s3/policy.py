"""Bucket policy engine (weed/s3api/policy_engine/): the IAM-style
JSON policy document evaluated per request.

Supported subset (the core of the reference's engine):

    {"Version": "2012-10-17", "Statement": [{
        "Effect": "Allow" | "Deny",
        "Principal": "*" | {"AWS": "*" | [access-key, ...]},
        "Action": "s3:GetObject" | ["s3:*", "s3:Get*"],
        "Resource": "arn:aws:s3:::bucket/key-or-*" | [...],
        "Condition": {"<Operator>": {"<context-key>": value|[...]}}
    }]}

Conditions evaluate against the per-request context the gateway
builds (aws:SourceIp, aws:SecureTransport, aws:username,
aws:CurrentTime, aws:UserAgent, aws:Referer, s3:prefix, ...), with
the reference's operator set (policy_engine/conditions.go:643
GetConditionEvaluator): String*, Numeric*, Date*, Bool,
IpAddress/NotIpAddress, Null, plus the ...IfExists suffix.

Evaluation order is AWS's: explicit Deny wins over Allow; otherwise a
matching Allow grants (this is how anonymous/public access is opened);
no match falls back to the gateway's signature-based default.
"""

from __future__ import annotations

import fnmatch
import ipaddress
import json
from datetime import datetime, timezone


class PolicyError(ValueError):
    pass


# -- Condition operators (conditions.go) -----------------------------------

def _parse_date(s: str) -> float:
    s = str(s)
    for fmt in ("%Y-%m-%dT%H:%M:%SZ", "%Y-%m-%dT%H:%M:%S%z",
                "%Y-%m-%d"):
        try:
            dt = datetime.strptime(s, fmt)
            if dt.tzinfo is None:
                dt = dt.replace(tzinfo=timezone.utc)
            return dt.timestamp()
        except ValueError:
            continue
    raise PolicyError(f"undecodable date {s!r}")


def _op_string(op, wanted, got):
    if op == "StringEquals":
        return got in wanted
    if op == "StringNotEquals":
        return got not in wanted
    if op == "StringLike":
        return any(fnmatch.fnmatchcase(got, w) for w in wanted)
    if op == "StringNotLike":
        return not any(fnmatch.fnmatchcase(got, w) for w in wanted)
    return None


def _cmp(op_suffix, g, w) -> bool:
    return {"Equals": g == w, "NotEquals": g != w,
            "LessThan": g < w, "LessThanEquals": g <= w,
            "GreaterThan": g > w,
            "GreaterThanEquals": g >= w}[op_suffix]


def _op_numeric(op, wanted, got):
    """Values within one key are OR'd (AWS multi-value semantics)."""
    try:
        g = float(got)
        ws = [float(w) for w in wanted]
    except ValueError:
        return False
    return any(_cmp(op.removeprefix("Numeric"), g, w) for w in ws)


def _op_date(op, wanted, got):
    try:
        g = _parse_date(got)
        ws = [_parse_date(w) for w in wanted]
    except PolicyError:
        return False
    return any(_cmp(op.removeprefix("Date"), g, w) for w in ws)


def _op_ip(op, wanted, got):
    try:
        addr = ipaddress.ip_address(got)
        nets = [ipaddress.ip_network(w, strict=False) for w in wanted]
    except ValueError:
        return False
    inside = any(addr in n for n in nets)
    return inside if op == "IpAddress" else not inside


_KNOWN_OPERATORS = {
    "StringEquals", "StringNotEquals", "StringLike", "StringNotLike",
    "NumericEquals", "NumericNotEquals", "NumericLessThan",
    "NumericLessThanEquals", "NumericGreaterThan",
    "NumericGreaterThanEquals", "DateEquals", "DateNotEquals",
    "DateLessThan", "DateLessThanEquals", "DateGreaterThan",
    "DateGreaterThanEquals", "Bool", "IpAddress", "NotIpAddress",
    "Null",
}


def _condition_matches(conditions: dict, context: dict) -> bool:
    """ALL operator blocks and ALL keys within must pass (AWS AND
    semantics; values within one key are OR'd)."""
    for op_raw, block in conditions.items():
        if_exists = op_raw.endswith("IfExists")
        op = op_raw.removesuffix("IfExists")
        for key, wanted in block.items():
            wanted = [str(w) for w in (
                wanted if isinstance(wanted, list) else [wanted])]
            if not wanted:
                return False     # defensive: parse rejects this
            got = context.get(key)
            if op == "Null":
                want_null = wanted[0].lower() == "true"
                if (got is None) != want_null:
                    return False
                continue
            if got is None:
                if if_exists:
                    continue        # absent key passes with IfExists
                # negative operators pass vacuously on absent keys
                # (AWS semantics: NotEquals/NotLike/NotIpAddress
                # match when the key is missing)
                if op in ("StringNotEquals", "StringNotLike",
                          "NotIpAddress", "NumericNotEquals",
                          "DateNotEquals"):
                    continue
                return False
            got = str(got)
            if op.startswith("String"):
                ok = _op_string(op, wanted, got)
            elif op.startswith("Numeric"):
                ok = _op_numeric(op, wanted, got)
            elif op.startswith("Date"):
                ok = _op_date(op, wanted, got)
            elif op == "Bool":
                ok = got.lower() in (w.lower() for w in wanted)
            elif op in ("IpAddress", "NotIpAddress"):
                ok = _op_ip(op, wanted, got)
            else:
                ok = None
            if not ok:
                return False
    return True


def parse_policy(doc: bytes) -> "list[dict]":
    try:
        p = json.loads(doc)
    except ValueError as e:
        raise PolicyError(f"malformed policy JSON: {e}")
    stmts = p.get("Statement")
    if not isinstance(stmts, list) or not stmts:
        raise PolicyError("policy needs a Statement list")
    out = []
    for s in stmts:
        effect = s.get("Effect")
        if effect not in ("Allow", "Deny"):
            raise PolicyError(f"bad Effect {effect!r}")
        conditions = s.get("Condition", {})
        if not isinstance(conditions, dict):
            raise PolicyError("Condition must be an object")
        for op in conditions:
            if op.removesuffix("IfExists") not in _KNOWN_OPERATORS:
                # an engine that cannot EVALUATE an operator must not
                # silently grant unconditionally — that widens access
                # beyond what the document states
                raise PolicyError(
                    f"unsupported condition operator {op!r}")
            if not isinstance(conditions[op], dict):
                raise PolicyError(f"Condition {op} must map keys")
            for ck, cv in conditions[op].items():
                if isinstance(cv, list) and not cv:
                    # an empty value list would crash evaluation
                    raise PolicyError(
                        f"Condition {op}/{ck} needs at least one "
                        f"value")
        principal = s.get("Principal", "*")
        if isinstance(principal, dict):
            unsupported = set(principal) - {"AWS"}
            if unsupported:
                # collapsing e.g. {"Federated": ...} to "*" would turn
                # an unsupported principal type into a wildcard grant
                raise PolicyError(
                    f"unsupported Principal types: "
                    f"{sorted(unsupported)}")
            principal = principal.get("AWS", "*")
        principals = principal if isinstance(principal, list) \
            else [principal]
        actions = s.get("Action", [])
        actions = actions if isinstance(actions, list) else [actions]
        resources = s.get("Resource", [])
        resources = resources if isinstance(resources, list) \
            else [resources]
        if not actions or not resources:
            raise PolicyError("statement needs Action and Resource")
        for a in actions:
            if not str(a).startswith("s3:"):
                raise PolicyError(f"unsupported action {a!r}")
        out.append({"effect": effect, "principals": principals,
                    "actions": [str(a) for a in actions],
                    "resources": [str(r) for r in resources],
                    "conditions": conditions})
    return out


def _match_any(patterns: "list[str]", value: str) -> bool:
    return any(fnmatch.fnmatchcase(value, p) for p in patterns)


def evaluate(statements: "list[dict]", principal: str, action: str,
             resource: str, context: "dict | None" = None
             ) -> "str | None":
    """'Deny' | 'Allow' | None (no statement matched).  `principal` is
    the authenticated access key, or "*"/"anonymous" for unsigned
    requests.  Explicit Deny wins.  `context` feeds Condition
    evaluation; statements with conditions simply don't match when
    their conditions fail."""
    decision = None
    for s in statements:
        if not (_match_any(s["principals"], principal) or
                "*" in s["principals"]):
            continue
        if not _match_any(s["actions"], action):
            continue
        if not _match_any(s["resources"], resource):
            continue
        if s.get("conditions") and not _condition_matches(
                s["conditions"], context or {}):
            continue
        if s["effect"] == "Deny":
            return "Deny"
        decision = "Allow"
    return decision


# bucket subresources get their OWN action names: an s3:ListBucket
# grant must not expose the policy/CORS/versioning/lock configs
_SUBRESOURCE_ACTIONS = {
    "policy": "BucketPolicy",
    "cors": "BucketCORS",
    "versioning": "BucketVersioning",
    "object-lock": "BucketObjectLockConfiguration",
    "lifecycle": "BucketLifecycle",
    "versions": None,  # ListBucketVersions, handled below
}


def action_for(method: str, bucket: str, key: str,
               query: dict) -> str:
    """Map an S3 request to its IAM action name (the subset the
    reference's engine distinguishes first)."""
    if "acl" in query:
        # ACL ops get their own names on BOTH bucket and object paths:
        # a plain read/write grant must not confer ReadAcp/WriteAcp
        verb = "Put" if method == "PUT" else "Get"
        return f"s3:{verb}{'ObjectAcl' if key else 'BucketAcl'}"
    if not key:
        for sub, name in _SUBRESOURCE_ACTIONS.items():
            if sub in query:
                if sub == "versions":
                    return "s3:ListBucketVersions"
                verb = {"GET": "Get", "HEAD": "Get", "PUT": "Put",
                        "DELETE": "Delete"}.get(method, method.title())
                return f"s3:{verb}{name}"
    if key:
        if method in ("GET", "HEAD"):
            return "s3:GetObject" if "versionId" not in query else \
                "s3:GetObjectVersion"
        if method == "PUT":
            return "s3:PutObject"
        if method == "DELETE":
            return "s3:DeleteObject" if "versionId" not in query \
                else "s3:DeleteObjectVersion"
        if method == "POST":
            return "s3:PutObject"
        return f"s3:{method.title()}Object"
    if method in ("GET", "HEAD"):
        return "s3:ListBucket"
    if method == "PUT":
        return "s3:CreateBucket"
    if method == "DELETE":
        return "s3:DeleteBucket"
    if method == "POST":
        return "s3:DeleteObject"  # batch delete
    return f"s3:{method.title()}Bucket"


def resource_arn(bucket: str, key: str) -> str:
    return f"arn:aws:s3:::{bucket}/{key}" if key else \
        f"arn:aws:s3:::{bucket}"
