"""Bucket policy engine (weed/s3api/policy_engine/): the IAM-style
JSON policy document evaluated per request.

Supported subset (the core of the reference's engine):

    {"Version": "2012-10-17", "Statement": [{
        "Effect": "Allow" | "Deny",
        "Principal": "*" | {"AWS": "*" | [access-key, ...]},
        "Action": "s3:GetObject" | ["s3:*", "s3:Get*"],
        "Resource": "arn:aws:s3:::bucket/key-or-*" | [...]
    }]}

Evaluation order is AWS's: explicit Deny wins over Allow; otherwise a
matching Allow grants (this is how anonymous/public access is opened);
no match falls back to the gateway's signature-based default.
"""

from __future__ import annotations

import fnmatch
import json


class PolicyError(ValueError):
    pass


def parse_policy(doc: bytes) -> "list[dict]":
    try:
        p = json.loads(doc)
    except ValueError as e:
        raise PolicyError(f"malformed policy JSON: {e}")
    stmts = p.get("Statement")
    if not isinstance(stmts, list) or not stmts:
        raise PolicyError("policy needs a Statement list")
    out = []
    for s in stmts:
        effect = s.get("Effect")
        if effect not in ("Allow", "Deny"):
            raise PolicyError(f"bad Effect {effect!r}")
        if "Condition" in s:
            # an engine that cannot EVALUATE conditions must not
            # silently grant unconditionally — that widens access
            # beyond what the document states
            raise PolicyError("Condition elements are not supported")
        principal = s.get("Principal", "*")
        if isinstance(principal, dict):
            unsupported = set(principal) - {"AWS"}
            if unsupported:
                # collapsing e.g. {"Federated": ...} to "*" would turn
                # an unsupported principal type into a wildcard grant
                raise PolicyError(
                    f"unsupported Principal types: "
                    f"{sorted(unsupported)}")
            principal = principal.get("AWS", "*")
        principals = principal if isinstance(principal, list) \
            else [principal]
        actions = s.get("Action", [])
        actions = actions if isinstance(actions, list) else [actions]
        resources = s.get("Resource", [])
        resources = resources if isinstance(resources, list) \
            else [resources]
        if not actions or not resources:
            raise PolicyError("statement needs Action and Resource")
        for a in actions:
            if not str(a).startswith("s3:"):
                raise PolicyError(f"unsupported action {a!r}")
        out.append({"effect": effect, "principals": principals,
                    "actions": [str(a) for a in actions],
                    "resources": [str(r) for r in resources]})
    return out


def _match_any(patterns: "list[str]", value: str) -> bool:
    return any(fnmatch.fnmatchcase(value, p) for p in patterns)


def evaluate(statements: "list[dict]", principal: str, action: str,
             resource: str) -> "str | None":
    """'Deny' | 'Allow' | None (no statement matched).  `principal` is
    the authenticated access key, or "*"/"anonymous" for unsigned
    requests.  Explicit Deny wins."""
    decision = None
    for s in statements:
        if not (_match_any(s["principals"], principal) or
                "*" in s["principals"]):
            continue
        if not _match_any(s["actions"], action):
            continue
        if not _match_any(s["resources"], resource):
            continue
        if s["effect"] == "Deny":
            return "Deny"
        decision = "Allow"
    return decision


# bucket subresources get their OWN action names: an s3:ListBucket
# grant must not expose the policy/CORS/versioning/lock configs
_SUBRESOURCE_ACTIONS = {
    "policy": "BucketPolicy",
    "cors": "BucketCORS",
    "versioning": "BucketVersioning",
    "object-lock": "BucketObjectLockConfiguration",
    "versions": None,  # ListBucketVersions, handled below
}


def action_for(method: str, bucket: str, key: str,
               query: dict) -> str:
    """Map an S3 request to its IAM action name (the subset the
    reference's engine distinguishes first)."""
    if not key:
        for sub, name in _SUBRESOURCE_ACTIONS.items():
            if sub in query:
                if sub == "versions":
                    return "s3:ListBucketVersions"
                verb = {"GET": "Get", "HEAD": "Get", "PUT": "Put",
                        "DELETE": "Delete"}.get(method, method.title())
                return f"s3:{verb}{name}"
    if key:
        if method in ("GET", "HEAD"):
            return "s3:GetObject" if "versionId" not in query else \
                "s3:GetObjectVersion"
        if method == "PUT":
            return "s3:PutObject"
        if method == "DELETE":
            return "s3:DeleteObject" if "versionId" not in query \
                else "s3:DeleteObjectVersion"
        if method == "POST":
            return "s3:PutObject"
        return f"s3:{method.title()}Object"
    if method in ("GET", "HEAD"):
        return "s3:ListBucket"
    if method == "PUT":
        return "s3:CreateBucket"
    if method == "DELETE":
        return "s3:DeleteBucket"
    if method == "POST":
        return "s3:DeleteObject"  # batch delete
    return f"s3:{method.title()}Bucket"


def resource_arn(bucket: str, key: str) -> str:
    return f"arn:aws:s3:::{bucket}/{key}" if key else \
        f"arn:aws:s3:::{bucket}"
