"""AWS event-stream framing (application/vnd.amazon.eventstream) —
the response encoding SelectObjectContent uses
(s3api_object_select.go; AWS "Event Stream Encoding" spec).

Message layout:
    total_length  u32 BE
    headers_length u32 BE
    prelude_crc   u32 BE   (CRC32 of the 8 prelude bytes)
    headers:  per header: name_len u8, name, value_type u8 (7 =
              string), value_len u16 BE, value
    payload
    message_crc   u32 BE   (CRC32 of everything before it)
"""

from __future__ import annotations

import struct
import zlib


def encode_message(headers: "dict[str, str]", payload: bytes) -> bytes:
    hbytes = b""
    for name, value in headers.items():
        nb, vb = name.encode(), value.encode()
        hbytes += (struct.pack(">B", len(nb)) + nb + b"\x07" +
                   struct.pack(">H", len(vb)) + vb)
    total = 4 + 4 + 4 + len(hbytes) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hbytes))
    prelude_crc = struct.pack(">I", zlib.crc32(prelude))
    body = prelude + prelude_crc + hbytes + payload
    return body + struct.pack(">I", zlib.crc32(body))


def records_event(data: bytes) -> bytes:
    return encode_message({
        ":message-type": "event",
        ":event-type": "Records",
        ":content-type": "application/octet-stream"}, data)


def stats_event(bytes_scanned: int, bytes_returned: int) -> bytes:
    xml = (f"<Stats><BytesScanned>{bytes_scanned}</BytesScanned>"
           f"<BytesProcessed>{bytes_scanned}</BytesProcessed>"
           f"<BytesReturned>{bytes_returned}</BytesReturned>"
           f"</Stats>").encode()
    return encode_message({
        ":message-type": "event",
        ":event-type": "Stats",
        ":content-type": "text/xml"}, xml)


def end_event() -> bytes:
    return encode_message({":message-type": "event",
                           ":event-type": "End"}, b"")


def decode_messages(stream: bytes) -> "list[tuple[dict, bytes]]":
    """Parse a concatenated event stream (test/client side), verifying
    both CRCs."""
    out = []
    pos = 0
    while pos < len(stream):
        total, hlen = struct.unpack_from(">II", stream, pos)
        prelude_crc = struct.unpack_from(">I", stream, pos + 8)[0]
        if zlib.crc32(stream[pos:pos + 8]) != prelude_crc:
            raise ValueError("event-stream prelude CRC mismatch")
        msg = stream[pos:pos + total]
        msg_crc = struct.unpack_from(">I", msg, total - 4)[0]
        if zlib.crc32(msg[:total - 4]) != msg_crc:
            raise ValueError("event-stream message CRC mismatch")
        headers = {}
        hp = 12
        hend = 12 + hlen
        while hp < hend:
            nlen = msg[hp]
            name = msg[hp + 1:hp + 1 + nlen].decode()
            vtype = msg[hp + 1 + nlen]
            if vtype != 7:
                raise ValueError(f"unsupported header type {vtype}")
            vlen = struct.unpack_from(">H", msg, hp + 2 + nlen)[0]
            vstart = hp + 4 + nlen
            headers[name] = msg[vstart:vstart + vlen].decode()
            hp = vstart + vlen
        out.append((headers, msg[hend:total - 4]))
        pos += total
    return out
