"""Streaming-chunked SigV4 payload codec
(weed/s3api/chunked_reader_v4.go).

Clients that sign uploads with `x-amz-content-sha256:
STREAMING-AWS4-HMAC-SHA256-PAYLOAD` send the body as aws-chunked
frames, each carrying its own signature chained from the previous one
(seeded by the Authorization header's signature):

    <hex-size>;chunk-signature=<sig64>\r\n
    <data>\r\n
    ...
    0;chunk-signature=<final-sig>\r\n\r\n

Each chunk's signature is HMAC(signing_key,
"AWS4-HMAC-SHA256-PAYLOAD\\n{date}\\n{scope}\\n{prev}\\n{sha256('')}\\n
{sha256(data)}") — chunk_string_to_sign in auth.py.  The decoder
verifies every frame and the final empty frame, so a tampered or
truncated stream is rejected as a whole.
"""

from __future__ import annotations

import hashlib
import hmac

from .auth import AuthContext, chunk_string_to_sign


class ChunkedDecodeError(ValueError):
    pass


def decode_streaming_body(body: bytes, ctx: AuthContext | None
                          ) -> bytes:
    """Verify and strip the aws-chunked framing; returns the payload.
    Raises ChunkedDecodeError on any malformed frame or signature
    mismatch (chunked_reader_v4.go readChunkedBody).  With ctx=None
    (gateway running without credentials) the framing is stripped but
    signatures cannot be checked — there is no secret to check against."""
    out = bytearray()
    prev_sig = ctx.seed_signature if ctx else ""
    pos = 0
    while True:
        nl = body.find(b"\r\n", pos)
        if nl < 0:
            raise ChunkedDecodeError("truncated chunk header")
        header = body[pos:nl].decode("latin-1")
        size_hex, _, ext = header.partition(";")
        try:
            size = int(size_hex, 16)
        except ValueError:
            raise ChunkedDecodeError(f"bad chunk size {size_hex!r}")
        if ext.startswith("chunk-signature="):
            sig = ext[len("chunk-signature="):]
        else:
            raise ChunkedDecodeError("missing chunk-signature")
        data_start = nl + 2
        data_end = data_start + size
        if data_end > len(body):
            raise ChunkedDecodeError("truncated chunk data")
        data = body[data_start:data_end]
        if ctx is not None:
            want = hmac.new(
                ctx.signing_key,
                chunk_string_to_sign(prev_sig, ctx.amz_date, ctx.scope,
                                     data).encode(),
                hashlib.sha256).hexdigest()
            if not hmac.compare_digest(want, sig):
                raise ChunkedDecodeError("chunk signature mismatch")
        prev_sig = sig
        if size == 0:
            return bytes(out)
        out += data
        pos = data_end
        if body[pos:pos + 2] == b"\r\n":
            pos += 2


def encode_streaming_body(payload: bytes, ctx: AuthContext,
                          chunk_size: int = 64 * 1024) -> bytes:
    """Client-side encoder (what an SDK does) — used by tests and the
    benchmark tool to exercise the decode path end-to-end."""
    out = bytearray()
    prev_sig = ctx.seed_signature
    offsets = list(range(0, len(payload), chunk_size)) or [0]
    pieces = [payload[o:o + chunk_size] for o in offsets]
    if pieces[-1]:
        pieces.append(b"")  # final zero chunk
    for data in pieces:
        sig = hmac.new(
            ctx.signing_key,
            chunk_string_to_sign(prev_sig, ctx.amz_date, ctx.scope,
                                 data).encode(),
            hashlib.sha256).hexdigest()
        out += f"{len(data):x};chunk-signature={sig}\r\n".encode()
        out += data
        out += b"\r\n"
        prev_sig = sig
    return bytes(out)
