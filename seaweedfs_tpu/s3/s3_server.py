"""S3 REST gateway over the filer (weed/s3api/s3api_server.go and
handler files; buckets live under /buckets/<name> as in the reference's
filer layout).

Implemented surface (the core the reference's s3tests exercise first):
  ListBuckets, Create/Delete/Head bucket, Put/Get/Head/Delete object,
  batch DeleteObjects, ListObjectsV2 (prefix/delimiter/continuation),
  multipart (initiate/uploadPart/complete/abort/listParts), SigV4 auth
  (header + presigned query), streaming-chunked uploads
  (chunked_reader_v4.go), object versioning with delete markers
  (s3api_object_versioning.go — versions archived under
  `<key>.versions/`, newest-first by inverted-timestamp id), and
  bucket CORS incl. preflight (s3api/cors/).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from ..filer import Entry, Filer
from ..filer.filechunks import total_size
from ..server.httpd import HttpServer, Request
from ..util import wlog
from .auth import SigV4Verifier
from .chunked import ChunkedDecodeError, decode_streaming_body
from .cors import evaluate as cors_evaluate, parse_cors_config

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = "/.uploads"
VERSIONS_EXT = ".versions"
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def new_version_id() -> str:
    """Inverted-timestamp version id: lexicographically ascending =
    newest first, so a plain sorted listing of `<key>.versions/` yields
    newest-first order (the reference's 'inverted format',
    s3api_object_versioning.go generateVersionId)."""
    # not a duration: a DESCENDING sort key derived from the wall
    # clock (newest version lists first, s3 semantics)
    return (f"{(1 << 63) - time.time_ns():016x}"  # noqa: SWFS011
            f"{os.urandom(3).hex()}")


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + \
        ET.tostring(root)


def _elem(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _error(status: int, code: str, message: str):
    root = ET.Element("Error")
    _elem(root, "Code", code)
    _elem(root, "Message", message)
    return status, (_xml(root), "application/xml")


def _with_headers(resp, extra: dict):
    """Merge extra response headers into any handler return shape."""
    status, payload = resp
    if isinstance(payload, tuple):
        body, second = payload
        if isinstance(second, dict):
            merged = dict(second)
            merged.update(extra)
            return status, (body, merged)
        h = dict(extra)
        h["Content-Type"] = second
        return status, (body, h)
    if isinstance(payload, (bytes, str)):
        body = payload if isinstance(payload, bytes) \
            else str(payload).encode()
        return status, (body, dict(extra))
    return resp  # JSON dict/list: headers not applicable


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class S3ApiServer:
    def __init__(self, filer: Filer, host: str = "127.0.0.1",
                 port: int = 0,
                 credentials: dict[str, str] | None = None,
                 iam=None, sts=None, kms=None,
                 metrics_port: int | None = None):
        """`credentials` is the legacy flat access->secret dict (every
        key acts as admin).  `iam` is an iam.IdentityStore: identities
        then carry coarse actions enforced per request
        (auth_credentials.go CanDo), `sts` an iam.StsService whose
        temporary credentials the verifier honors, `kms` an
        iam.kms.LocalKms enabling SSE-KMS."""
        self.filer = filer
        self.iam = iam
        self.kms = kms
        creds = iam.secrets_view() if iam is not None else credentials
        self.verifier = SigV4Verifier(creds, sts=sts) \
            if creds is not None else None
        self.http = HttpServer(host, port)
        self.http.fallback = self._dispatch
        # striped per-key locks: versioned mutations are
        # archive-then-write sequences; two concurrent PUTs to one key
        # must not interleave or the loser's acknowledged version is
        # silently lost (bounded stripe count — no per-key leak)
        self._stripes = [threading.Lock() for _ in range(64)]
        self._cors_cache: dict[str, tuple[str, list]] = {}
        self._policy_cache: dict[str, tuple[str, list]] = {}
        self._tbkt_cache: dict[str, tuple[float, bool]] = {}
        # admission control + per-bucket observability
        # (s3api_circuit_breaker.go; stats/metrics.go S3 families)
        from ..stats import Metrics
        from .circuit_breaker import CircuitBreaker
        self.circuit_breaker = CircuitBreaker()
        self._cb_stamp = (0.0, -1.0)     # (checked-at, entry-mtime)
        self.metrics = Metrics("s3")
        self.http.role = "s3"            # tracing + request_seconds
        self.http.metrics = self.metrics
        # QoS plane (qos.py): per-tenant admission at the tenant-facing
        # edge (tenant = SigV4 access key), and this gateway's
        # request_seconds histogram is a foreground-latency source for
        # the background EC throttle
        from .. import qos
        qos.install(self.http, "s3")
        qos.throttle().add_metrics(f"s3:{self.http.port}",
                                   self.metrics)
        qos.throttle().maybe_start()
        # metrics ride a SEPARATE listener (`weed s3 -metricsPort`):
        # the S3 port must keep every path free for bucket names
        self.metrics_http = None
        if metrics_port is not None:
            self.metrics_http = HttpServer(host, metrics_port)
            from ..stats import render_process
            self.metrics_http.route(
                "GET", "/metrics",
                lambda req: (200, ((self.metrics.render() +
                                    render_process()).encode(),
                                   "text/plain; version=0.0.4")))

    def _path_lock(self, path: str) -> "threading.Lock":
        return self._stripes[hash(path) % len(self._stripes)]

    def start(self):
        self.http.start()
        if self.metrics_http is not None:
            self.metrics_http.start()
        # filer -> s3 IAM cache propagation service (s3.proto
        # SeaweedS3IamCache): identity/policy/group pushes land in
        # the gateway's live auth state without a restart
        self.grpc_server, self.grpc_port = None, 0
        if self.iam is not None:
            try:
                from ..pb.iam_service import start_s3_cache_grpc
                self.grpc_server, self.grpc_port = \
                    start_s3_cache_grpc(self.iam, host=self.http.host)
            except ImportError:     # grpcio absent: HTTP-only mode
                pass
            except Exception as e:  # pragma: no cover — a real defect
                import sys
                print(f"s3 {self.url}: gRPC plane failed to start: "
                      f"{e!r}", file=sys.stderr)
        return self

    def stop(self):
        from .. import qos
        qos.throttle().remove_source(f"s3:{self.http.port}")
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5).wait()
            self.grpc_server = None
        if self.metrics_http is not None:
            self.metrics_http.stop()
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- dispatch ---------------------------------------------------------

    def _observe(self, req: Request, bucket: str, action: str,
                 resp) -> None:
        """Per-bucket request/byte counters (stats/metrics.go
        S3RequestCounter / S3 bytes families), served by the side
        metrics server (`weed s3 -metricsPort` analog)."""
        status = resp[0] if isinstance(resp, tuple) else 200
        # label-cardinality guard: only successful requests and
        # authenticated callers mint per-bucket label values — an
        # unauthenticated loop over random names must not grow the
        # registry without bound
        authed = bool(getattr(req, "s3_identity", None))
        blabel = bucket if bucket and \
            (authed or (isinstance(status, int) and status < 400)) \
            else "-"
        self.metrics.counter_add(
            "request_total", 1.0, "s3 requests",
            bucket=blabel, action=action, code=str(status))
        n_in = len(req.body or b"")
        if n_in:
            self.metrics.counter_add(
                "received_bytes_total", float(n_in),
                "request payload bytes", bucket=blabel)
        payload = resp[1] if isinstance(resp, tuple) and \
            len(resp) > 1 else b""
        if isinstance(payload, tuple):
            payload = payload[0]
        if isinstance(payload, (bytes, str)) and payload:
            self.metrics.counter_add(
                "sent_bytes_total", float(len(payload)),
                "response payload bytes", bucket=blabel)

    def _refresh_circuit_breaker(self) -> None:
        """Lazy 2s-TTL reload of /etc/s3/circuit_breaker.json from
        the filer (the reference subscribes to filer metadata; a TTL
        poll gives the same operator experience without a stream)."""
        import time as _t
        from .circuit_breaker import CONFIG_PATH
        now = _t.monotonic()
        checked, mtime = self._cb_stamp
        if now - checked < 2.0:
            return
        e = self.filer.find_entry(CONFIG_PATH)
        new_mtime = e.attributes.mtime if e is not None else 0
        if new_mtime != mtime:
            try:
                content = self.filer.read_file(CONFIG_PATH) \
                    if e is not None else b""
                self.circuit_breaker.load_bytes(content)
            except Exception as e:  # noqa: BLE001 — any malformed
                # config or unreachable chunk (read_file raises
                # RuntimeError/LookupError when the hosting volume is
                # down; load() raises on wrong-shape JSON) must keep
                # the last good config, never crash the request path
                wlog.warning("circuit-breaker config unreadable; "
                             "keeping previous: %s", e, component="s3")
        self._cb_stamp = (now, new_mtime)

    def _dispatch(self, req: Request):
        parts = req.path.lstrip("/").split("/", 1)
        bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        origin = req.headers.get("Origin", "")
        if req.method == "OPTIONS":
            # CORS preflight: unauthenticated by design (browsers send
            # no credentials on preflights)
            return self._preflight(req, bucket)
        from ..iam import coarse_action
        from .policy import action_for
        cb_action = coarse_action(
            action_for(req.method, bucket, key, req.query),
            req.method, req.query)
        self._refresh_circuit_breaker()
        rollback, err = self.circuit_breaker.admit(
            bucket, cb_action, len(req.body or b""))
        if err is not None:
            # falls through to the CORS tail below: a throttled
            # browser request must still read the 503 (else it sees
            # an opaque CORS failure instead of a retryable error)
            resp = _error(503, err,
                          "simultaneous request limit reached")
        else:
            try:
                resp = self._handle(req, bucket, key)
            finally:
                rollback()
        self._observe(req, bucket, cb_action, resp)
        if origin and bucket:
            cors = cors_evaluate(self._cors_rules(bucket), origin,
                                 req.method)
            if cors:
                resp = _with_headers(resp, cors)
        return resp

    def _handle(self, req: Request, bucket: str, key: str):
        from .policy import action_for, evaluate, resource_arn
        identity = "*"
        ctx = None
        stmts = self._policy_rules(bucket) if bucket else []
        decision = None
        action = action_for(req.method, bucket, key, req.query)
        arn = resource_arn(bucket, key)
        pctx = self._policy_context(req)
        ident_obj = None          # iam.Identity once resolved
        if self.verifier is not None:
            ok, who, ctx = self.verifier.verify(
                req.method, req.path, req.query,
                {k.lower(): v for k, v in req.headers.items()},
                req.body)
            if ok:
                identity = who
                if ctx is not None and ctx.sts_identity is not None:
                    ident_obj = ctx.sts_identity
                elif self.iam is not None:
                    ident_obj = self.iam.by_access_key(who)
                if ident_obj is not None:
                    identity = ident_obj.name
                req.s3_identity = identity
            else:
                # unsigned/invalid: an "anonymous" identity
                # (auth_credentials.go) or the bucket policy may still
                # open this resource (public-read buckets)
                anon = self.iam.anonymous() if self.iam else None
                decision = evaluate(stmts, "anonymous", action,
                                    arn, pctx) if stmts else None
                if decision == "Deny":
                    # explicit policy Deny binds the anonymous
                    # identity too — it can widen access, never
                    # override a Deny
                    return _error(403, "AccessDenied",
                                  "denied by bucket policy")
                acl_open = decision != "Allow" and anon is None and \
                    self._acl_allows(bucket, key, action, False)
                if decision != "Allow" and anon is None and \
                        not acl_open:
                    return _error(403, "AccessDenied", who)
                identity = "anonymous"
                ident_obj = anon
                if acl_open:
                    decision = "Allow"   # canned-ACL grant
        if self.iam is not None and self.verifier is not None and \
                decision != "Allow":
            # first authorization layer: coarse identity actions
            # (auth_credentials.go CanDo) — bucket policy can still
            # explicitly deny below, but cannot widen a missing grant
            # except for the anonymous-Allow path above
            from ..iam import coarse_action
            if not bucket:
                # ListAllMyBuckets: any authenticated identity may
                # call it; _list_buckets filters to visible buckets
                if ident_obj is None:
                    return _error(403, "AccessDenied", identity)
            elif ident_obj is None or not ident_obj.can_do(
                    coarse_action(action, req.method, req.query),
                    bucket, key):
                # canned ACLs (authenticated-read / public-*) can
                # still open reads to identities with no grant —
                # "authenticated" means a real signed principal, NOT
                # the anonymous fallback identity
                if not self._acl_allows(bucket, key, action,
                                        identity != "anonymous"):
                    return _error(403, "AccessDenied",
                                  f"{identity} may not "
                                  f"{coarse_action(action)} {bucket}")
            req.s3_identity_obj = ident_obj
        if stmts and decision is None:
            pctx["aws:username"] = identity
            if evaluate(stmts, identity, action, arn,
                        pctx) == "Deny":
                # explicit Deny beats a valid signature
                return _error(403, "AccessDenied",
                              "denied by bucket policy")
        sha = req.headers.get("x-amz-content-sha256", "")
        if sha.startswith("STREAMING-"):
            # aws-chunked framing (chunked_reader_v4.go): verify chunk
            # signatures when we hold credentials, then unwrap.  A
            # presigned-URL context carries no signing key — strip the
            # framing unverified, as before
            try:
                req._body = decode_streaming_body(
                    req.body,
                    ctx if ctx is not None and ctx.signing_key
                    else None)
            except ChunkedDecodeError as e:
                return _error(403, "SignatureDoesNotMatch", str(e))
        if not bucket:
            target = req.headers.get("X-Amz-Target", "")
            if req.method == "POST" and \
                    target.startswith("S3Tables."):
                return self._s3tables_op(req, target.split(".", 1)[1])
            if req.method == "GET":
                return self._list_buckets(
                    getattr(req, "s3_identity_obj", None))
            return _error(405, "MethodNotAllowed", req.method)
        if not key:
            return self._bucket_op(req, bucket)
        if key and req.method in ("PUT", "DELETE", "POST"):
            err = self._table_bucket_write_guard(req, bucket, key)
            if err is not None:
                return err
        return self._object_op(req, bucket, key)

    def _s3tables_op(self, req: Request, operation: str):
        """S3 Tables plane (s3tables.py; reference
        weed/s3api/s3tables/handler.go): POST / with
        X-Amz-Target: S3Tables.<Op> and a JSON body.  Mutating ops
        need the coarse Admin action on the target bucket; reads need
        Read (or Admin)."""
        from .s3tables import (S3TablesError, S3TablesStore,
                               handle_request, parse_bucket_arn,
                               parse_table_arn)
        try:
            body = json.loads(req.body or b"{}")
        except ValueError as e:
            return 400, (json.dumps(
                {"__type": "InvalidRequest",
                 "message": f"bad JSON body: {e}"}).encode(),
                "application/x-amz-json-1.1")
        ident = getattr(req, "s3_identity_obj", None)
        if self.verifier is not None:
            # resolve the target bucket for scoped grants
            tbkt = ""
            try:
                if body.get("tableBucketARN"):
                    tbkt = parse_bucket_arn(body["tableBucketARN"])
                elif body.get("tableARN"):
                    tbkt = parse_table_arn(body["tableARN"])[0]
                elif body.get("resourceArn"):
                    tbkt = parse_bucket_arn(
                        body["resourceArn"].split("/table/")[0])
                elif body.get("name") and \
                        operation == "CreateTableBucket":
                    tbkt = body["name"]
            except S3TablesError:
                tbkt = ""
            read_only = operation.startswith(("Get", "List"))
            needed = "Read" if read_only else "Admin"
            # legacy flat-credentials mode (no IdentityStore): every
            # valid signature acts as admin, per the class contract
            legacy_admin = self.iam is None and \
                bool(getattr(req, "s3_identity", None))
            if not legacy_admin and (ident is None or not (
                    ident.can_do(needed, tbkt) or ident.is_admin)):
                return 403, (json.dumps(
                    {"__type": "AccessDeniedException",
                     "message": f"not authorized to {operation}"}
                ).encode(), "application/x-amz-json-1.1")
        store = S3TablesStore(self.filer)
        try:
            resp = handle_request(store, operation, body)
        except S3TablesError as e:
            return e.status, (json.dumps(
                {"__type": e.code, "message": e.message}).encode(),
                "application/x-amz-json-1.1")
        if operation in ("CreateTableBucket", "DeleteTableBucket"):
            # a stale negative table-bucket cache entry would let
            # arbitrary objects into a just-created table bucket for
            # the TTL window — drop it on the spot
            if body.get("name"):
                self._tbkt_cache.pop(body["name"], None)
            if body.get("tableBucketARN"):
                try:
                    self._tbkt_cache.pop(
                        parse_bucket_arn(body["tableBucketARN"]),
                        None)
                except S3TablesError:
                    pass
        return 200, (json.dumps(resp).encode(),
                     "application/x-amz-json-1.1")

    def _is_table_bucket(self, bucket: str) -> bool:
        """2s-TTL cached table-bucket check: the guard runs on EVERY
        object write, and ordinary buckets (the hot path) must not
        pay an extra filer round trip per request.  Table-bucket-ness
        changes only on bucket create/delete, so a short TTL is
        safe."""
        from .s3tables import is_table_bucket
        now = time.monotonic()
        hit = self._tbkt_cache.get(bucket)
        if hit is not None and now - hit[0] < 2.0:
            return hit[1]
        val = is_table_bucket(
            self.filer.find_entry(self._bucket_path(bucket)))
        self._tbkt_cache[bucket] = (now, val)
        if len(self._tbkt_cache) > 4096:   # unauthenticated-probe cap
            self._tbkt_cache.clear()
        return val

    def _table_bucket_write_guard(self, req: Request, bucket: str,
                                  key: str):
        """Direct object writes into a TABLE bucket must target an
        existing table's subtree and follow the Iceberg file layout
        (reference: s3tables/iceberg_layout.go applied via
        bucket_paths.go) — arbitrary objects would corrupt the
        catalog's invariants.  Returns an error response or None."""
        from .s3tables import X_METADATA, validate_iceberg_key
        if not self._is_table_bucket(bucket):
            return None
        reason = validate_iceberg_key(key)
        if reason is None:
            ns, table = key.split("/")[0], key.split("/")[1]
            t = self.filer.find_entry(
                f"{self._bucket_path(bucket)}/{ns}/{table}")
            if t is None or X_METADATA not in t.extended:
                reason = f"no table {ns}/{table} in bucket {bucket}"
        if reason is not None and req.method != "DELETE":
            return _error(403, "AccessDenied",
                          f"table bucket {bucket}: {reason}")
        return None

    # -- CORS (s3api/cors/) -----------------------------------------------

    def _cors_rules(self, bucket: str):
        e = self.filer.find_entry(self._bucket_path(bucket))
        xml_text = (e.extended.get("cors") if e else None) or ""
        if not xml_text:
            return []
        if isinstance(xml_text, bytes):
            xml_text = xml_text.decode()
        cached = self._cors_cache.get(bucket)
        if cached is not None and cached[0] == xml_text:
            return cached[1]  # skip the per-request XML re-parse
        try:
            rules = parse_cors_config(xml_text.encode())
        except ValueError:
            rules = []
        self._cors_cache[bucket] = (xml_text, rules)
        return rules

    def _preflight(self, req: Request, bucket: str):
        origin = req.headers.get("Origin", "")
        want_method = req.headers.get("Access-Control-Request-Method",
                                      "")
        want_headers = req.headers.get("Access-Control-Request-Headers",
                                       "")
        if not origin or not want_method or not bucket:
            return _error(400, "BadRequest", "not a CORS preflight")
        headers = cors_evaluate(self._cors_rules(bucket), origin,
                                want_method, want_headers)
        if headers is None:
            return _error(403, "AccessForbidden",
                          "CORSResponse: no matching rule")
        return 200, (b"", headers)

    # -- ACLs (s3api_acp.go / s3acl; canned grants) -----------------------

    CANNED_ACLS = ("private", "public-read", "public-read-write",
                   "authenticated-read")
    _READ_ACTIONS = {"s3:GetObject", "s3:GetObjectVersion",
                     "s3:HeadObject", "s3:ListBucket",
                     "s3:ListBucketVersions"}
    _WRITE_ACTIONS = {"s3:PutObject", "s3:DeleteObject",
                      "s3:DeleteObjectVersion"}

    def _stored_acl(self, bucket: str, key: str = "") -> str:
        """Effective canned ACL: the object's own, else the bucket's
        (the reference consults both, object first)."""
        if key:
            e = self.filer.find_entry(
                f"{self._bucket_path(bucket)}/{key}")
            if e is not None and e.extended.get("acl"):
                return e.extended["acl"]
        e = self.filer.find_entry(self._bucket_path(bucket))
        return (e.extended.get("acl") if e else "") or "private"

    def _acl_allows(self, bucket: str, key: str, action: str,
                    authenticated: bool) -> bool:
        """Does the canned ACL open this request to a principal with
        no other grant? (public-read / public-read-write /
        authenticated-read semantics)."""
        if not bucket:
            return False
        acl = self._stored_acl(bucket, key)
        if acl == "public-read-write":
            return action in self._READ_ACTIONS | self._WRITE_ACTIONS
        if acl == "public-read":
            return action in self._READ_ACTIONS
        if acl == "authenticated-read":
            return authenticated and action in self._READ_ACTIONS
        return False

    def _acl_op(self, req: Request, bucket: str, key: str):
        """Get/Put{Bucket,Object}Acl (?acl): canned ACLs via the
        x-amz-acl header; GET renders the grant set the canned value
        implies (s3api_acp.go)."""
        path = f"{self._bucket_path(bucket)}/{key}" if key else \
            self._bucket_path(bucket)
        entry = self.filer.find_entry(path)
        if entry is None:
            return _error(404, "NoSuchKey" if key else "NoSuchBucket",
                          key or bucket)
        if req.method == "PUT":
            canned = req.headers.get("x-amz-acl", "")
            if not canned:
                # grant-body form: accept only documents expressing a
                # canned set; arbitrary grantees are out of scope.
                # Neither header nor body is AWS's MissingSecurityHeader
                # — NOT a silent reset to private
                return _error(
                    501 if req.body else 400,
                    "NotImplemented" if req.body
                    else "MissingSecurityHeader",
                    "only canned ACLs (x-amz-acl) are supported"
                    if req.body else
                    "PUT ?acl needs an x-amz-acl header")
            if canned not in self.CANNED_ACLS:
                return _error(400, "InvalidArgument",
                              f"unsupported ACL {canned!r}")
            entry.extended["acl"] = canned
            self.filer.create_entry(entry, create_parents=False)
            return 200, (b"", {})
        if req.method != "GET":
            return _error(405, "MethodNotAllowed", req.method)
        acl = entry.extended.get("acl", "") or "private"
        root = ET.Element("AccessControlPolicy", xmlns=S3_NS)
        owner = _elem(root, "Owner")
        _elem(owner, "ID", "seaweedfs-tpu")
        grants = _elem(root, "AccessControlList")

        def grant(grantee_uri, permission):
            g = _elem(grants, "Grant")
            ge = _elem(g, "Grantee")
            ge.set("{http://www.w3.org/2001/XMLSchema-instance}type",
                   "Group" if grantee_uri else "CanonicalUser")
            if grantee_uri:
                _elem(ge, "URI", grantee_uri)
            else:
                _elem(ge, "ID", "seaweedfs-tpu")
            _elem(g, "Permission", permission)

        grant("", "FULL_CONTROL")
        groups = "http://acs.amazonaws.com/groups/global/"
        if acl in ("public-read", "public-read-write"):
            grant(groups + "AllUsers", "READ")
        if acl == "public-read-write":
            grant(groups + "AllUsers", "WRITE")
        if acl == "authenticated-read":
            grant(groups + "AuthenticatedUsers", "READ")
        return 200, (_xml(root), "application/xml")

    @staticmethod
    def _policy_context(req: Request) -> dict:
        """Per-request condition context (policy_engine/engine.go
        buildConditionContext): the keys Condition blocks evaluate
        against."""
        from .. import security
        ctx = {
            "aws:SourceIp": req.remote_ip,
            "aws:SecureTransport":
                "true" if security.current().tls else "false",
            "aws:CurrentTime": time.strftime(
                "%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        ua = req.headers.get("User-Agent")
        if ua:
            ctx["aws:UserAgent"] = ua
        referer = req.headers.get("Referer")
        if referer:
            ctx["aws:Referer"] = referer
        for qk, ck in (("prefix", "s3:prefix"),
                       ("delimiter", "s3:delimiter"),
                       ("max-keys", "s3:max-keys")):
            if qk in req.query:
                ctx[ck] = req.query[qk]
        acl = req.headers.get("x-amz-acl")
        if acl:
            ctx["s3:x-amz-acl"] = acl
        return ctx

    def _policy_rules(self, bucket: str) -> list:
        from .policy import PolicyError, parse_policy
        e = self.filer.find_entry(self._bucket_path(bucket))
        doc = (e.extended.get("policy") if e else None) or ""
        if not doc:
            return []
        cached = self._policy_cache.get(bucket)
        if cached is not None and cached[0] == doc:
            return cached[1]
        try:
            stmts = parse_policy(doc.encode()
                                 if isinstance(doc, str) else doc)
        except PolicyError:
            stmts = []
        self._policy_cache[bucket] = (doc, stmts)
        return stmts

    def _bucket_lifecycle_op(self, req: Request, bucket: str):
        """Put/Get/DeleteBucketLifecycleConfiguration
        (s3api_bucket_handlers.go:800): rules persist on the bucket
        entry; the shell's s3.lifecycle.apply pass enforces them."""
        e = self.filer.find_entry(self._bucket_path(bucket))
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method == "PUT":
            # like _bucket_policy_op: lifecycle mutation is
            # destructive config — anonymous principals (even on
            # policy-opened buckets) may not install rules that
            # delete data
            if self.verifier is not None and \
                    not getattr(req, "s3_identity", None):
                return _error(403, "AccessDenied",
                              "lifecycle mutation requires a signed "
                              "request")
            from .lifecycle import LifecycleError, parse_lifecycle
            try:
                parse_lifecycle(req.body)
                doc = req.body.decode()
            except (LifecycleError, UnicodeDecodeError) as err:
                return _error(400, "MalformedXML", str(err))
            e.extended["lifecycle"] = doc
            self.filer.create_entry(e, create_parents=False)
            return 200, b""
        if req.method == "GET":
            doc = e.extended.get("lifecycle", "")
            if not doc:
                return _error(404,
                              "NoSuchLifecycleConfiguration", bucket)
            return 200, (doc.encode(), "application/xml")
        if req.method == "DELETE":
            if self.verifier is not None and \
                    not getattr(req, "s3_identity", None):
                return _error(403, "AccessDenied",
                              "lifecycle mutation requires a signed "
                              "request")
            e.extended.pop("lifecycle", None)
            self.filer.create_entry(e, create_parents=False)
            return 204, b""
        return _error(405, "MethodNotAllowed", req.method)

    def _bucket_policy_op(self, req: Request, bucket: str):
        """Put/Get/DeleteBucketPolicy (s3api policy_engine).  Policy
        mutation itself requires a SIGNED request — an anonymous
        principal must never be able to rewrite the policy that grants
        it access (checked here because _handle's anonymous path can
        reach bucket ops when a policy allows)."""
        from .policy import PolicyError, parse_policy
        e = self.filer.find_entry(self._bucket_path(bucket))
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method in ("PUT", "DELETE") and \
                self.verifier is not None and \
                not getattr(req, "s3_identity", None):
            return _error(403, "AccessDenied",
                          "policy mutation requires a signed request")
        if req.method == "PUT":
            try:
                parse_policy(req.body)
            except PolicyError as err:
                return _error(400, "MalformedPolicy", str(err))
            e.extended["policy"] = req.body.decode()
            self.filer.create_entry(e, create_parents=False)
            return 204, b""
        if req.method == "GET":
            doc = e.extended.get("policy", "")
            if not doc:
                return _error(404, "NoSuchBucketPolicy", bucket)
            return 200, (doc.encode(), "application/json")
        if req.method == "DELETE":
            e.extended.pop("policy", None)
            self.filer.create_entry(e, create_parents=False)
            return 204, b""
        return _error(405, "MethodNotAllowed", req.method)

    def _bucket_cors_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        e = self.filer.find_entry(path)
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method == "PUT":
            try:
                parse_cors_config(req.body)
            except (ValueError, ET.ParseError) as err:
                return _error(400, "MalformedXML", str(err))
            e.extended["cors"] = req.body.decode()
            self.filer.create_entry(e, create_parents=False)
            return 200, b""
        if req.method == "GET":
            xml_text = e.extended.get("cors", "")
            if not xml_text:
                return _error(404, "NoSuchCORSConfiguration", bucket)
            return 200, (xml_text.encode(), "application/xml")
        if req.method == "DELETE":
            e.extended.pop("cors", None)
            self.filer.create_entry(e, create_parents=False)
            return 204, b""
        return _error(405, "MethodNotAllowed", req.method)

    # -- object lock (s3api_object_retention.go, object lock) -------------

    LOCK_MODES = ("GOVERNANCE", "COMPLIANCE")

    def _bucket_object_lock_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        e = self.filer.find_entry(path)
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method == "PUT":
            if self._versioning_state(bucket) != "Enabled":
                return _error(409, "InvalidBucketState",
                              "object lock requires versioning")
            try:
                root = ET.fromstring(req.body)
            except ET.ParseError as err:
                return _error(400, "MalformedXML", str(err))
            mode, days = "", 0
            try:
                for el in root.iter():
                    tag = el.tag.rsplit("}", 1)[-1]
                    if tag == "Mode":
                        mode = (el.text or "").strip().upper()
                    elif tag in ("Days", "Years"):
                        days = int(el.text or 0) * \
                            (365 if tag == "Years" else 1)
            except ValueError as err:
                return _error(400, "MalformedXML", str(err))
            if mode and mode not in self.LOCK_MODES:
                return _error(400, "MalformedXML",
                              f"bad retention mode {mode!r}")
            if mode and days <= 0:
                return _error(400, "MalformedXML",
                              "retention needs positive Days/Years")
            e.extended["objectLock"] = "Enabled"
            # PUT replaces the WHOLE configuration: a config without a
            # Rule removes any previous default retention
            if mode:
                e.extended["lockDefaultMode"] = mode
                e.extended["lockDefaultDays"] = str(days)
            else:
                e.extended.pop("lockDefaultMode", None)
                e.extended.pop("lockDefaultDays", None)
            self.filer.create_entry(e, create_parents=False)
            return 200, b""
        if req.method == "GET":
            if e.extended.get("objectLock") != "Enabled":
                return _error(404,
                              "ObjectLockConfigurationNotFoundError",
                              bucket)
            root = ET.Element("ObjectLockConfiguration", xmlns=S3_NS)
            _elem(root, "ObjectLockEnabled", "Enabled")
            if e.extended.get("lockDefaultMode"):
                rule = _elem(root, "Rule")
                ret = _elem(rule, "DefaultRetention")
                _elem(ret, "Mode", e.extended["lockDefaultMode"])
                _elem(ret, "Days", e.extended.get("lockDefaultDays",
                                                  "0"))
            return 200, (_xml(root), "application/xml")
        return _error(405, "MethodNotAllowed", req.method)

    @staticmethod
    def _parse_retain_until(text: str) -> float:
        import calendar
        # timegm, NOT mktime-timezone: the date is UTC; mktime reads
        # the struct in LOCAL time and is an hour off under DST
        return calendar.timegm(time.strptime(
            text.split(".")[0].rstrip("Z"), "%Y-%m-%dT%H:%M:%S"))

    def _lock_for_put(self, req: Request, bucket: str,
                      state: str) -> "dict | tuple":
        """Resolve the retention to stamp on a new object version:
        explicit x-amz-object-lock-* headers, else the bucket default.
        Returns extended-dict updates, or an error response tuple.
        `state` is the caller's already-fetched versioning state (no
        redundant bucket lookups on the hot write path)."""
        lower = {k.lower(): v for k, v in req.headers.items()}
        mode = lower.get("x-amz-object-lock-mode", "").upper()
        until_raw = lower.get("x-amz-object-lock-retain-until-date",
                              "")
        if mode or until_raw:
            if mode not in self.LOCK_MODES or not until_raw:
                return _error(400, "InvalidArgument",
                              "object-lock mode AND retain-until-date "
                              "are both required")
            if state != "Enabled":
                return _error(400, "InvalidRequest",
                              "object lock requires versioning")
            try:
                until = self._parse_retain_until(until_raw)
            except ValueError:
                return _error(400, "InvalidArgument",
                              f"bad retain-until date {until_raw!r}")
            return {"lockMode": mode, "lockRetainUntil": str(until)}
        if state != "Enabled":
            # defaults only stamp real versions; never 'null' ones a
            # suspended bucket could silently destroy
            return {}
        b = self.filer.find_entry(self._bucket_path(bucket))
        if b is not None and b.extended.get("lockDefaultMode"):
            days = int(b.extended.get("lockDefaultDays", 0))
            return {"lockMode": b.extended["lockDefaultMode"],
                    "lockRetainUntil":
                        str(time.time() + days * 86400)}
        return {}

    @classmethod
    def _retention_active(cls, extended: dict) -> "str | None":
        """The active lock mode, or None when unlocked/expired."""
        mode = extended.get("lockMode", "")
        try:
            until = float(extended.get("lockRetainUntil", 0))
        except ValueError:
            until = 0
        if mode in cls.LOCK_MODES and time.time() < until:
            return mode
        return None

    def _check_version_deletable(self, req: Request, extended: dict):
        """403 response tuple when retention forbids deleting this
        version; None when allowed.  GOVERNANCE yields to the bypass
        header (the AWS permission model's s3:BypassGovernanceRetention
        reduced to the header check our auth model supports)."""
        mode = self._retention_active(extended)
        if mode is None:
            return None
        if mode == "GOVERNANCE":
            lower = {k.lower(): v for k, v in req.headers.items()}
            if lower.get("x-amz-bypass-governance-retention",
                         "").lower() == "true":
                return None
        return _error(403, "AccessDenied",
                      f"version is locked ({mode}) until "
                      f"{extended.get('lockRetainUntil')}")

    # -- versioning state (s3api_bucket_handlers.go) ----------------------

    def _versioning_state(self, bucket: str) -> str:
        e = self.filer.find_entry(self._bucket_path(bucket))
        return (e.extended.get("versioning", "") if e else "") or ""

    def _bucket_versioning_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        e = self.filer.find_entry(path)
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method == "PUT":
            status = ""
            try:
                for el in ET.fromstring(req.body).iter():
                    if el.tag.endswith("Status"):
                        status = (el.text or "").strip()
            except ET.ParseError as err:
                return _error(400, "MalformedXML", str(err))
            if status not in ("Enabled", "Suspended"):
                return _error(400, "MalformedXML",
                              f"bad versioning status {status!r}")
            if status == "Suspended" and \
                    e.extended.get("objectLock") == "Enabled":
                # AWS forbids this: suspension would let 'null'
                # versions overwrite/delete locked data
                return _error(409, "InvalidBucketState",
                              "versioning cannot be suspended on an "
                              "object-lock-enabled bucket")
            e.extended["versioning"] = status
            self.filer.create_entry(e, create_parents=False)
            return 200, b""
        if req.method == "GET":
            root = ET.Element("VersioningConfiguration", xmlns=S3_NS)
            state = self._versioning_state(bucket)
            if state:
                _elem(root, "Status", state)
            return 200, (_xml(root), "application/xml")
        return _error(405, "MethodNotAllowed", req.method)

    # -- buckets ----------------------------------------------------------

    def _bucket_path(self, bucket: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}"

    def _list_buckets(self, ident=None):
        """With an IAM identity, only buckets it can Read or List are
        shown (s3api_bucket_handlers.go ListBucketsHandler filters the
        same way)."""
        root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
        owner = _elem(root, "Owner")
        _elem(owner, "ID", "seaweedfs-tpu")
        buckets = _elem(root, "Buckets")
        for e in self.filer.list_directory(BUCKETS_ROOT):
            if not e.is_directory:
                continue
            if ident is not None and not (
                    ident.can_do("Read", e.name) or
                    ident.can_do("List", e.name)):
                continue
            b = _elem(buckets, "Bucket")
            _elem(b, "Name", e.name)
            _elem(b, "CreationDate", _iso(e.attributes.crtime))
        return 200, (_xml(root), "application/xml")

    # -- bucket default encryption (s3api_bucket_handlers.go
    #    PutBucketEncryption; applied at PUT when the request carries
    #    no SSE headers of its own) --------------------------------------

    def _bucket_encryption_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        e = self.filer.find_entry(path)
        if e is None:
            return _error(404, "NoSuchBucket", bucket)
        if req.method == "PUT":
            algo, kms_key = "", ""
            try:
                for el in ET.fromstring(req.body).iter():
                    tag = el.tag.rsplit("}", 1)[-1]
                    if tag == "SSEAlgorithm":
                        algo = (el.text or "").strip()
                    elif tag == "KMSMasterKeyID":
                        kms_key = (el.text or "").strip()
            except ET.ParseError as err:
                return _error(400, "MalformedXML", str(err))
            if algo not in ("AES256", "aws:kms"):
                return _error(400, "MalformedXML",
                              f"unsupported SSEAlgorithm {algo!r}")
            if self.kms is None:
                # both modes envelope-encrypt through the KMS here;
                # accepting the config would make every subsequent
                # object PUT fail 501 — reject the misconfiguration
                # at the source instead
                return _error(501, "NotImplemented",
                              "no KMS configured on this gateway")
            e.extended["encryptionConfig"] = json.dumps(
                {"algorithm": algo, "kmsKeyId": kms_key})
            self.filer.create_entry(e, create_parents=False)
            return 200, b""
        if req.method == "GET":
            raw = e.extended.get("encryptionConfig", "")
            if not raw:
                return _error(
                    404, "ServerSideEncryptionConfigurationNotFound"
                    "Error", "no default encryption configuration")
            cfg = json.loads(raw)
            root = ET.Element("ServerSideEncryptionConfiguration",
                              xmlns=S3_NS)
            rule = _elem(root, "Rule")
            by_default = _elem(rule,
                               "ApplyServerSideEncryptionByDefault")
            _elem(by_default, "SSEAlgorithm", cfg["algorithm"])
            if cfg.get("kmsKeyId"):
                _elem(by_default, "KMSMasterKeyID", cfg["kmsKeyId"])
            return 200, (_xml(root), "application/xml")
        if req.method == "DELETE":
            e.extended.pop("encryptionConfig", None)
            self.filer.create_entry(e, create_parents=False)
            return 204, b""
        return _error(405, "MethodNotAllowed", req.method)

    def _default_encryption(self, bucket: str
                            ) -> "tuple[str, str] | None":
        """The bucket's default-SSE setting in parse_sse_kms_headers'
        (mode, key_id) shape; None when unconfigured."""
        e = self.filer.find_entry(self._bucket_path(bucket))
        raw = e.extended.get("encryptionConfig", "") if e else ""
        if not raw:
            return None
        try:
            cfg = json.loads(raw)
            return cfg["algorithm"], cfg.get("kmsKeyId", "")
        except (ValueError, KeyError):
            return None

    def _bucket_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        if "encryption" in req.query:
            return self._bucket_encryption_op(req, bucket)
        if "versioning" in req.query:
            return self._bucket_versioning_op(req, bucket)
        if "object-lock" in req.query:
            return self._bucket_object_lock_op(req, bucket)
        if "policy" in req.query:
            return self._bucket_policy_op(req, bucket)
        if "lifecycle" in req.query:
            return self._bucket_lifecycle_op(req, bucket)
        if "cors" in req.query:
            return self._bucket_cors_op(req, bucket)
        if "acl" in req.query:
            return self._acl_op(req, bucket, "")
        if "versions" in req.query and req.method == "GET":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            return self._list_versions(req, bucket)
        if req.method == "PUT":
            # idempotent re-PUT must keep the existing entry: a fresh
            # Entry would wipe extended (policy/cors/acl configs)
            e = self.filer.find_entry(path) or \
                Entry(path, is_directory=True)
            canned = req.headers.get("x-amz-acl", "")
            if canned and canned not in self.CANNED_ACLS:
                # silently ignoring would store a different ACL than
                # the client believes it set
                return _error(400, "InvalidArgument",
                              f"unsupported ACL {canned!r}")
            if canned:
                e.extended["acl"] = canned
            self.filer.create_entry(e)
            return 200, b""
        if req.method == "HEAD":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            return 200, b""
        if req.method == "DELETE":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            # only the reserved .uploads scratch dir is not bucket content
            children = self.filer.list_directory(path, limit=1000)
            if any(c.name != UPLOADS_DIR[1:] for c in children):
                return _error(409, "BucketNotEmpty", bucket)
            self.filer.delete_entry(path, recursive=True)
            return 204, b""
        if req.method == "GET":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            return self._list_objects(req, bucket)
        if req.method == "POST" and "delete" in req.query:
            return self._delete_objects(req, bucket)
        return _error(405, "MethodNotAllowed", req.method)

    # -- objects ----------------------------------------------------------

    def _object_op(self, req: Request, bucket: str, key: str):
        bucket_entry = self.filer.find_entry(
            self._bucket_path(bucket))
        if bucket_entry is None:
            return _error(404, "NoSuchBucket", bucket)
        if any(seg.endswith(VERSIONS_EXT)
               for seg in key.split("/") if seg):
            # the version-archive namespace is reserved
            # (s3_constants.VersionsFolder)
            return _error(400, "InvalidArgument",
                          f"key may not contain a segment ending "
                          f"{VERSIONS_EXT}")
        if "acl" in req.query:
            return self._acl_op(req, bucket, key)
        if "select" in req.query and req.method == "POST":
            return self._select_object(req, bucket, key)
        if req.method == "PUT" or ("uploads" in req.query or
                                   "uploadId" in req.query):
            # quota enforcement (s3.bucket.quota.enforce): an
            # over-quota bucket is read-only — writes refused,
            # deletes still allowed so users can free space
            if bucket_entry.extended.get("readOnly") == "true" and \
                    req.method in ("PUT", "POST"):
                return _error(403, "AccessDenied",
                              f"bucket {bucket} is read-only "
                              f"(quota exceeded)")
        if "uploads" in req.query and req.method == "POST":
            return self._initiate_multipart(req, bucket, key)
        if "uploadId" in req.query:
            return self._multipart_op(req, bucket, key)
        path = f"{self._bucket_path(bucket)}/{key}"
        state = self._versioning_state(bucket)
        if req.method == "PUT":
            src = req.headers.get("x-amz-copy-source")
            if src:
                return self._copy_object(req, src, path, bucket)
            from .policy import resource_arn
            from .sse import (ALGO_HEADER, KEY_MD5_HEADER, SseError,
                              encrypt, kms_encrypt,
                              kms_response_headers,
                              parse_sse_c_headers,
                              parse_sse_kms_headers)
            lower = {k.lower(): v for k, v in req.headers.items()}
            canned_acl = req.headers.get("x-amz-acl", "")
            if canned_acl and canned_acl not in self.CANNED_ACLS:
                # rejecting beats storing a different ACL than the
                # client believes it set
                return _error(400, "InvalidArgument",
                              f"unsupported ACL {canned_acl!r}")
            kms_headers = {}
            try:
                sse = parse_sse_c_headers(lower)
                kms_req = parse_sse_kms_headers(lower)
            except SseError as e:
                return _error(e.status, e.code, str(e))
            if sse is None and kms_req is None:
                # bucket-default encryption: a PUT with no SSE headers
                # inherits the bucket's configured default (SSE-S3 or
                # SSE-KMS), exactly AWS's PutBucketEncryption behavior
                kms_req = self._default_encryption(bucket)
            body = req.body
            sse_ext = {}
            if sse is not None:
                key_bytes, key_md5 = sse
                body, iv_hex = encrypt(key_bytes, body)
                sse_ext = {"sseKeyMd5": key_md5, "sseIv": iv_hex}
            elif kms_req is not None:
                if self.kms is None:
                    return _error(501, "NotImplemented",
                                  "no KMS configured on this gateway")
                try:
                    body, sse_ext = kms_encrypt(
                        self.kms, kms_req[0], kms_req[1],
                        resource_arn(bucket, key), body)
                except SseError as e:
                    return _error(e.status, e.code, str(e))
                kms_headers = kms_response_headers(sse_ext)
            lock_ext = self._lock_for_put(req, bucket, state)
            if not isinstance(lock_ext, dict):
                return lock_ext  # error response
            with self._path_lock(path):
                vid = self._pre_write_archive(path, state)
                # SSE-C etag covers the CIPHERTEXT (a plaintext md5
                # would leak content equality; AWS's SSE-C etag is
                # likewise not the plaintext md5)
                etag = hashlib.md5(body).hexdigest()
                entry = self.filer.write_file(
                    path, body,
                    mime=req.headers.get("Content-Type", ""))
                entry.extended["etag"] = etag
                entry.extended.update(sse_ext)
                entry.extended.update(lock_ext)
                if vid is not None:
                    entry.extended["versionId"] = vid
                amz = {k: v for k, v in req.headers.items()
                       if k.lower().startswith("x-amz-meta-")}
                entry.extended.update(amz)
                if canned_acl:
                    entry.extended["acl"] = canned_acl
                self.filer.create_entry(entry)
            headers = {"ETag": f'"{etag}"'}
            headers.update(kms_headers)
            if sse is not None:
                headers["x-amz-server-side-encryption-customer-"
                        "algorithm"] = "AES256"
                headers[KEY_MD5_HEADER] = sse[1]
            if vid:
                headers["x-amz-version-id"] = vid
            return 200, (b"", headers)
        if req.method in ("GET", "HEAD"):
            return self._get_object(req, bucket, key, path)
        if req.method == "DELETE":
            return self._delete_object(req, bucket, key, path, state)
        return _error(405, "MethodNotAllowed", req.method)

    def _select_object(self, req: Request, bucket: str, key: str):
        """SelectObjectContent (POST /bucket/key?select&select-type=2):
        SQL-subset over a JSON-lines/CSV object (weed/query/engine/).
        Results stream back in genuine AWS event-stream framing
        (Records/Stats/End messages, CRC'd — s3/eventstream.py), with
        newline-delimited JSON records inside the Records payloads —
        the reference's own engine output shape."""
        from ..query import QueryError, run_query
        from .sse import SseError, check_read_key, decrypt_entry
        path = f"{self._bucket_path(bucket)}/{key}"
        entry = self.filer.find_entry(path)
        if entry is None or entry.is_directory:
            return _error(404, "NoSuchKey", key)
        # SSE-C: select is a READ — it must enforce and use the
        # customer key exactly like GET (querying raw ciphertext would
        # both leak it and never match)
        lower = {k.lower(): v for k, v in req.headers.items()}
        try:
            sse_key = check_read_key(entry.extended, lower)
        except SseError as e:
            return _error(e.status, e.code, str(e))
        try:
            root = ET.fromstring(req.body)
        except ET.ParseError as e:
            return _error(400, "MalformedXML", str(e))
        expression = ""
        input_format = "json"
        csv_header = True
        for el in root.iter():
            tag = el.tag.rsplit("}", 1)[-1]
            if tag == "Expression":
                expression = el.text or ""
            elif tag == "InputSerialization":
                # only the INPUT block decides the source format
                # (OutputSerialization may also contain <CSV>)
                for sub in el.iter():
                    stag = sub.tag.rsplit("}", 1)[-1]
                    if stag == "CSV":
                        input_format = "csv"
                    elif stag == "Parquet":
                        input_format = "parquet"
                    elif stag == "FileHeaderInfo":
                        csv_header = \
                            (sub.text or "").upper() != "NONE"
        if not expression:
            return _error(400, "MissingRequiredParameter",
                          "Expression is required")
        data = self.filer.read_file(path)
        if sse_key is not None and data:
            data = decrypt_entry(sse_key, entry.extended, data)
        elif entry.extended.get("sseKmsBlob") and data:
            data, kms_err = self._kms_read(entry, path, data)
            if kms_err is not None:
                return kms_err
        try:
            rows = run_query(expression, data, input_format,
                             csv_header)
        except QueryError as e:
            return _error(400, "InvalidTextEncoding", str(e))
        import json as _json
        from .eventstream import end_event, records_event, stats_event
        payload = b"".join(_json.dumps(r, separators=(",", ":"))
                           .encode() + b"\n" for r in rows)
        # AWS event-stream framing (Records* -> Stats -> End), 64KB
        # Records chunks like the reference's streaming writer
        events = [records_event(payload[off:off + 65536])
                  for off in range(0, len(payload), 65536)]
        events.append(stats_event(len(data), len(payload)))
        events.append(end_event())
        return 200, (b"".join(events),
                     "application/vnd.amazon.eventstream")

    # -- versioning core (s3api_object_versioning.go) ---------------------

    def _pre_write_archive(self, path: str, state: str) -> str | None:
        """Before a plain-path write: archive the current entry into
        `<key>.versions/` according to the bucket's versioning state.
        Returns the new content's version id (None = unversioned).

        Enabled: always archive the incumbent (its chunks move with the
        rename — never deleted), new content gets a fresh id.
        Suspended: a real-id incumbent is archived, a 'null' incumbent
        is simply overwritten; new content is the 'null' version."""
        if state == "Enabled":
            self._archive_current(path)
            return new_version_id()
        if state == "Suspended":
            cur = self.filer.find_entry(path)
            if cur is not None and not cur.is_directory and \
                    cur.extended.get("versionId", "null") != "null":
                self._archive_current(path)
            return "null"
        return None

    def _archive_current(self, path: str) -> None:
        cur = self.filer.find_entry(path)
        if cur is None or cur.is_directory:
            return
        vid = cur.extended.get("versionId", "null")
        cur.extended["versionId"] = vid
        self.filer.create_entry(cur, create_parents=False)
        self.filer.rename(path, f"{path}{VERSIONS_EXT}/{vid}")

    @staticmethod
    def _recency_key(e: Entry):
        """Version recency: newest first.  mtime is the truth — the
        inverted-timestamp id gives lexical newest-first for Enabled-era
        versions, but the suspended-era 'null' id sorts after every hex
        id and would otherwise always rank oldest (letting a
        null-marker-deleted object resurrect)."""
        return (-e.attributes.mtime, e.name)

    def _promote_latest(self, path: str) -> None:
        """After a specific-version delete: if the plain path is gone
        and the newest surviving archived version is REAL, it becomes
        the plain entry again (AWS latest-version semantics)."""
        if self.filer.find_entry(path) is not None:
            return
        vdir = path + VERSIONS_EXT
        versions = [e for e in self.filer.list_directory(vdir)
                    if not e.is_directory]
        if not versions:
            if self.filer.find_entry(vdir) is not None:
                self.filer.delete_entry(vdir, recursive=True)
            return
        newest = min(versions, key=self._recency_key)
        if newest.extended.get("deleteMarker") == "true":
            return
        self.filer.rename(f"{vdir}/{newest.name}", path)

    def _kms_read(self, entry: Entry, path: str, data: bytes):
        """Decrypt an SSE-KMS body on a read path; (data, None) on
        success, (None, error_response) otherwise — one place for the
        no-KMS/ bad-seal handling every read path needs."""
        from .sse import SseError, kms_decrypt
        if self.kms is None:
            return None, _error(501, "NotImplemented",
                                "object is SSE-KMS encrypted but "
                                "this gateway has no KMS")
        try:
            return kms_decrypt(self.kms, entry.extended,
                               self._arn_for_path(path), data), None
        except SseError as e:
            return None, _error(e.status, e.code, str(e))

    @staticmethod
    def _arn_for_path(path: str) -> str:
        """Object ARN from a filer path, versioned or not: all
        versions of a key share the key's ARN (the KMS encryption
        context must match what PUT bound)."""
        rel = path.removeprefix(BUCKETS_ROOT + "/")
        if VERSIONS_EXT + "/" in rel:
            rel = rel.split(VERSIONS_EXT + "/", 1)[0].rstrip("/")
        return f"arn:aws:s3:::{rel}"

    def _serve_entry(self, req: Request, path: str, entry: Entry):
        from .sse import (KEY_MD5_HEADER, SseError, check_read_key,
                          decrypt_entry, kms_response_headers)
        lower = {k.lower(): v for k, v in req.headers.items()}
        try:
            sse_key = check_read_key(entry.extended, lower)
        except SseError as e:
            return _error(e.status, e.code, str(e))
        # zero-copy plain-object GETs: no SSE transform means nothing
        # needs the whole body in memory — stream chunk views lazily
        # through the filer's hot chunk cache instead of buffering a
        # multi-GB object per request (SWFS013's reason to exist).
        # SSE-C/KMS objects still buffer: decryption wants the full
        # ciphertext.
        plain = sse_key is None and \
            not entry.extended.get("sseKmsBlob")
        stream_open = getattr(self.filer, "open_read_stream", None) \
            if plain and req.method == "GET" else None
        data = b"" if req.method == "HEAD" or stream_open else \
            self.filer.read_file(path)
        if sse_key is not None and data:
            data = decrypt_entry(sse_key, entry.extended, data)
        elif entry.extended.get("sseKmsBlob") and data:
            data, kms_err = self._kms_read(entry, path, data)
            if kms_err is not None:
                return kms_err
        etag = entry.extended.get("etag", "")
        mime = entry.attributes.mime or "application/octet-stream"
        headers = {"Content-Type": mime,
                   "ETag": f'"{etag}"',
                   "Content-Length": str(total_size(entry.chunks)),
                   "Last-Modified": _iso(entry.attributes.mtime)}
        if entry.extended.get("sseKeyMd5"):
            headers["x-amz-server-side-encryption-customer-"
                    "algorithm"] = "AES256"
            headers[KEY_MD5_HEADER] = entry.extended["sseKeyMd5"]
        headers.update(kms_response_headers(entry.extended))
        if entry.extended.get("lockMode"):
            headers["x-amz-object-lock-mode"] = \
                entry.extended["lockMode"]
            until = float(entry.extended.get("lockRetainUntil", 0))
            headers["x-amz-object-lock-retain-until-date"] = \
                time.strftime("%Y-%m-%dT%H:%M:%SZ",
                              time.gmtime(until))
        vid = entry.extended.get("versionId")
        if vid:
            headers["x-amz-version-id"] = vid
        if stream_open is not None:
            from ..server.httpd import parse_range
            total = total_size(entry.chunks)
            parsed = parse_range(req.headers.get("Range", ""), total)
            if parsed == "unsatisfiable":
                return 416, (b"", {"Content-Range":
                                   f"bytes */{total}"})
            start, size = parsed if parsed is not None else (0, total)
            from .. import qos
            release, deny = qos.charge_response(req, size, "s3")
            if deny is not None:
                return deny
            body = stream_open(entry, start, size, on_close=release)
            headers["Content-Length"] = str(size)
            if parsed is not None:
                headers["Content-Range"] = \
                    f"bytes {start}-{start + size - 1}/{total}"
                return 206, (body, headers)
            return 200, (body, headers)
        if req.method == "GET":
            # ranged GetObject over the BUFFERED (SSE) path: ranges
            # apply AFTER decryption — CTR mode could seek, but
            # correctness first; shared parser keeps semantics
            # identical with the filer paths
            from ..server.httpd import parse_range
            total = len(data)
            status = 200
            parsed = parse_range(req.headers.get("Range", ""), total)
            if parsed == "unsatisfiable":
                return 416, (b"", {"Content-Range":
                                   f"bytes */{total}"})
            if parsed is not None:
                start, size = parsed
                data = data[start:start + size]
                headers["Content-Range"] = \
                    f"bytes {start}-{start + len(data) - 1}/{total}"
                status = 206
            # the buffered (SSE) read is the MOST expensive shape on
            # the server — full ciphertext + plaintext resident — so
            # it must spend the same in-flight-byte budget the
            # streamed path does, not evade it
            from .. import qos
            release, deny = qos.charge_response(req, len(data), "s3")
            if deny is not None:
                return deny
            headers["Content-Length"] = str(len(data))
            if release is not None:
                return status, (qos.MeteredBody(data, release),
                                headers)
            return status, (data, headers)
        return 200, (data, headers)

    def _get_object(self, req: Request, bucket: str, key: str,
                    path: str):
        vid = req.query.get("versionId", "")
        if vid:
            entry = self.filer.find_entry(path)
            if entry is not None and \
                    entry.extended.get("versionId", "null") == vid:
                return self._serve_entry(req, path, entry)
            vpath = f"{path}{VERSIONS_EXT}/{vid}"
            entry = self.filer.find_entry(vpath)
            if entry is None:
                return _error(404, "NoSuchVersion", vid)
            if entry.extended.get("deleteMarker") == "true":
                # GET on a delete marker: 405 (AWS behavior)
                return 405, (b"", {"x-amz-delete-marker": "true",
                                   "x-amz-version-id": vid,
                                   "Allow": "DELETE"})
            return self._serve_entry(req, vpath, entry)
        entry = self.filer.find_entry(path)
        if entry is None or entry.is_directory:
            newest = self._newest_version(path)
            if newest is not None and \
                    newest.extended.get("deleteMarker") == "true":
                return 404, (_error(404, "NoSuchKey", key)[1][0],
                             {"x-amz-delete-marker": "true",
                              "Content-Type": "application/xml"})
            return _error(404, "NoSuchKey", key)
        return self._serve_entry(req, path, entry)

    def _newest_version(self, path: str) -> Entry | None:
        versions = [e for e in
                    self.filer.list_directory(path + VERSIONS_EXT)
                    if not e.is_directory]
        return min(versions, key=self._recency_key) if versions \
            else None

    def _delete_object(self, req: Request, bucket: str, key: str,
                       path: str, state: str):
        with self._path_lock(path):
            return self._delete_object_locked(req, bucket, key, path,
                                              state)

    def _delete_object_locked(self, req: Request, bucket: str,
                              key: str, path: str, state: str):
        vid = req.query.get("versionId", "")
        if vid:
            return self._delete_specific_version(bucket, path, vid,
                                                 req)
        if state in ("Enabled", "Suspended"):
            # archive the incumbent and leave a delete marker
            # (createDeleteMarker, s3api_object_versioning.go:160)
            cur = self.filer.find_entry(path)
            if cur is not None and not cur.is_directory:
                if state == "Suspended" and \
                        cur.extended.get("versionId", "null") == "null":
                    self.filer.delete_entry(path)
                else:
                    self._archive_current(path)
            marker_vid = new_version_id() if state == "Enabled" \
                else "null"
            mpath = f"{path}{VERSIONS_EXT}/{marker_vid}"
            if self.filer.find_entry(mpath) is not None:
                self.filer.delete_entry(mpath)
            marker = Entry(mpath)
            marker.extended["deleteMarker"] = "true"
            marker.extended["versionId"] = marker_vid
            self.filer.create_entry(marker)
            return 204, (b"", {"x-amz-delete-marker": "true",
                               "x-amz-version-id": marker_vid})
        entry = self.filer.find_entry(path)
        if entry is not None:
            self.filer.delete_entry(path)
            self._prune_empty_dirs(path, bucket)
        return 204, b""

    def _delete_specific_version(self, bucket: str, path: str,
                                 vid: str, req: "Request | None" = None):
        was_marker = False
        cur = self.filer.find_entry(path)
        if cur is not None and not cur.is_directory and \
                cur.extended.get("versionId", "null") == vid:
            if req is not None:
                blocked = self._check_version_deletable(
                    req, cur.extended)
                if blocked is not None:
                    return blocked
            self.filer.delete_entry(path)
        else:
            vpath = f"{path}{VERSIONS_EXT}/{vid}"
            e = self.filer.find_entry(vpath)
            if e is not None:
                if req is not None:
                    blocked = self._check_version_deletable(
                        req, e.extended)
                    if blocked is not None:
                        return blocked
                was_marker = e.extended.get("deleteMarker") == "true"
                self.filer.delete_entry(vpath)
        self._promote_latest(path)
        self._prune_empty_dirs(path, bucket)
        headers = {"x-amz-version-id": vid}
        if was_marker:
            headers["x-amz-delete-marker"] = "true"
        return 204, (b"", headers)

    # -- ListObjectVersions (GET /bucket?versions) ------------------------

    def _list_versions(self, req: Request, bucket: str):
        """s3api_object_versioning.go listObjectVersions.  Collected
        per key (latest first), emitted in key order; supports prefix +
        max-keys truncation with key/version markers."""
        prefix = req.query.get("prefix", "")
        max_keys = int(req.query.get("max-keys", 1000))
        key_marker = req.query.get("key-marker", "")
        vid_marker = req.query.get("version-id-marker", "")
        base = self._bucket_path(bucket)
        per_key: dict[str, list[Entry]] = {}

        def walk(dir_path: str, key_prefix: str):
            if prefix and not (key_prefix.startswith(prefix) or
                               prefix.startswith(key_prefix)):
                return
            for e in self.filer.list_directory(dir_path,
                                               limit=1_000_000):
                if e.is_directory:
                    if e.name.endswith(VERSIONS_EXT):
                        obj_key = key_prefix + \
                            e.name[:-len(VERSIONS_EXT)]
                        if obj_key.startswith(prefix):
                            vs = [v for v in self.filer.list_directory(
                                f"{dir_path}/{e.name}")
                                if not v.is_directory]
                            per_key.setdefault(obj_key, []).extend(
                                sorted(vs, key=self._recency_key))
                    elif not (key_prefix == "" and
                              e.name == UPLOADS_DIR[1:]):
                        walk(f"{dir_path}/{e.name}",
                             key_prefix + e.name + "/")
                else:
                    obj_key = key_prefix + e.name
                    if obj_key.startswith(prefix):
                        per_key.setdefault(obj_key, []).insert(0, e)

        walk(base, "")
        root = ET.Element("ListVersionsResult", xmlns=S3_NS)
        _elem(root, "Name", bucket)
        _elem(root, "Prefix", prefix)
        _elem(root, "MaxKeys", max_keys)
        count = 0
        truncated = False
        skipping = bool(key_marker)
        for obj_key in sorted(per_key):
            if key_marker and obj_key < key_marker:
                continue
            if key_marker and obj_key == key_marker and \
                    not vid_marker:
                # key-marker alone means "begin AFTER this key"
                continue
            for i, e in enumerate(per_key[obj_key]):
                e_vid = e.extended.get("versionId", "null")
                if skipping and obj_key == key_marker:
                    if e_vid == vid_marker:
                        skipping = False
                    continue  # markers are exclusive
                if count >= max_keys:
                    truncated = True
                    _elem(root, "NextKeyMarker", obj_key)
                    _elem(root, "NextVersionIdMarker", e_vid)
                    break
                is_marker = e.extended.get("deleteMarker") == "true"
                v = _elem(root,
                          "DeleteMarker" if is_marker else "Version")
                _elem(v, "Key", obj_key)
                _elem(v, "VersionId", e_vid)
                _elem(v, "IsLatest",
                      "true" if i == 0 else "false")
                _elem(v, "LastModified", _iso(e.attributes.mtime))
                if not is_marker:
                    _elem(v, "ETag",
                          f'"{e.extended.get("etag", "")}"')
                    _elem(v, "Size", total_size(e.chunks))
                    _elem(v, "StorageClass", "STANDARD")
                count += 1
            if truncated:
                break
        _elem(root, "IsTruncated", "true" if truncated else "false")
        return 200, (_xml(root), "application/xml")

    def _prune_empty_dirs(self, path: str, bucket: str) -> None:
        """Remove now-empty parent directories up to the bucket root
        (S3 has no directories — an emptied prefix must disappear;
        s3api/s3api_object_handlers_delete.go doDeleteEmptyDirectories)."""
        stop = self._bucket_path(bucket)
        parent = path.rsplit("/", 1)[0]
        while parent != stop and parent.startswith(stop + "/"):
            if self.filer.list_directory(parent, limit=1):
                break
            try:
                self.filer.delete_entry(parent)
            except IsADirectoryError:
                break  # concurrent PUT repopulated it — keep it
            parent = parent.rsplit("/", 1)[0]

    def _copy_object(self, req: Request, src: str, dst_path: str,
                     bucket: str):
        from .sse import (SseError, check_read_key, decrypt_entry,
                          encrypt, kms_encrypt, parse_sse_c_headers,
                          parse_sse_kms_headers)
        src = urllib.parse.unquote(src.lstrip("/"))
        src_path = f"{BUCKETS_ROOT}/{src}"
        entry = self.filer.find_entry(src_path)
        if entry is None:
            return _error(404, "NoSuchKey", src)
        lower = {k.lower(): v for k, v in req.headers.items()}
        # SSE-C source: the copy-source key headers are REQUIRED to
        # decrypt; copying raw ciphertext while dropping the SSE
        # metadata would serve garbage as if it were plaintext
        src_sse = {k.replace("x-amz-copy-source-server-side-"
                             "encryption-customer-",
                             "x-amz-server-side-encryption-customer-"):
                   v for k, v in lower.items()
                   if k.startswith("x-amz-copy-source-server-side-")}
        try:
            src_key = check_read_key(entry.extended, src_sse)
            dst_sse = parse_sse_c_headers(lower)
            dst_kms = parse_sse_kms_headers(lower)
        except SseError as e:
            return _error(e.status, e.code, str(e))
        if dst_sse is None and dst_kms is None:
            # the destination is a new object: the bucket's default
            # encryption applies exactly like a plain PUT
            dst_kms = self._default_encryption(bucket)
        data = self.filer.read_file(src_path)
        if src_key is not None:
            data = decrypt_entry(src_key, entry.extended, data)
        elif entry.extended.get("sseKmsBlob"):
            data, kms_err = self._kms_read(entry, src_path, data)
            if kms_err is not None:
                return kms_err
        sse_ext = {}
        if dst_sse is not None:
            dst_key, dst_md5 = dst_sse
            data, iv_hex = encrypt(dst_key, data)
            sse_ext = {"sseKeyMd5": dst_md5, "sseIv": iv_hex}
        elif dst_kms is not None:
            if self.kms is None:
                return _error(501, "NotImplemented",
                              "no KMS configured on this gateway")
            from .policy import resource_arn
            dst_key_part = dst_path.removeprefix(
                f"{self._bucket_path(bucket)}/")
            try:
                data, sse_ext = kms_encrypt(
                    self.kms, dst_kms[0], dst_kms[1],
                    resource_arn(bucket, dst_key_part), data)
            except SseError as e:
                return _error(e.status, e.code, str(e))
        # the copy is a new version: retention headers / bucket default
        # apply exactly like a plain PUT (silently skipping them would
        # bypass the bucket's retention policy)
        lock_ext = self._lock_for_put(
            req, bucket, self._versioning_state(bucket))
        if not isinstance(lock_ext, dict):
            return lock_ext
        etag = hashlib.md5(data).hexdigest()
        with self._path_lock(dst_path):
            vid = self._pre_write_archive(
                dst_path, self._versioning_state(bucket))
            new = self.filer.write_file(dst_path, data,
                                        mime=entry.attributes.mime)
            new.extended["etag"] = etag
            new.extended.update(sse_ext)
            new.extended.update(lock_ext)
            if vid is not None:
                new.extended["versionId"] = vid
            self.filer.create_entry(new)
        root = ET.Element("CopyObjectResult", xmlns=S3_NS)
        _elem(root, "ETag", f'"{etag}"')
        _elem(root, "LastModified", _iso(time.time()))
        resp = 200, (_xml(root), "application/xml")
        return _with_headers(resp, {"x-amz-version-id": vid}) if vid \
            else resp

    def _delete_objects(self, req: Request, bucket: str):
        """POST /bucket?delete — batch delete (versioning-aware: each
        key routes through the same delete path as single DELETE)."""
        root = ET.fromstring(req.body)
        result = ET.Element("DeleteResult", xmlns=S3_NS)
        state = self._versioning_state(bucket)
        for obj in root.iter():
            if not obj.tag.endswith("Object"):
                continue
            key = vid = ""
            for child in obj:
                if child.tag.endswith("Key"):
                    key = child.text or ""
                elif child.tag.endswith("VersionId"):
                    vid = child.text or ""
            if not key:
                continue
            path = f"{self._bucket_path(bucket)}/{key}"
            failed = None
            if vid:
                with self._path_lock(path):
                    r = self._delete_specific_version(bucket, path,
                                                      vid, req)
                if r[0] >= 300:
                    failed = r
            elif state in ("Enabled", "Suspended"):
                self._delete_object(req, bucket, key, path, state)
            else:
                self.filer.delete_entry(path)
                self._prune_empty_dirs(path, bucket)
            if failed is not None:
                # a locked version is NOT deleted — reporting
                # <Deleted> would lie to lifecycle/cleanup clients
                err = _elem(result, "Error")
                _elem(err, "Key", key)
                if vid:
                    _elem(err, "VersionId", vid)
                _elem(err, "Code", "AccessDenied")
                _elem(err, "Message", "version is locked")
                continue
            d = _elem(result, "Deleted")
            _elem(d, "Key", key)
            if vid:
                _elem(d, "VersionId", vid)
        return 200, (_xml(result), "application/xml")

    # -- ListObjectsV2 (s3api_objects_list_handlers.go) -------------------

    def _list_objects(self, req: Request, bucket: str):
        prefix = req.query.get("prefix", "")
        delimiter = req.query.get("delimiter", "")
        max_keys = int(req.query.get("max-keys", 1000))
        token = req.query.get("continuation-token", "")
        start_after = req.query.get("start-after", "")
        start = max(token, start_after)
        base = self._bucket_path(bucket)

        contents: list[tuple[str, Entry]] = []
        common: set[str] = set()

        def walk_sorted(dir_path: str, key_prefix: str):
            """Yield (key, entry) in global lexicographic key order.

            Children sort by their *effective* key start (name for
            files, name + "/" for directories — "a!" must come before
            "a/b"); each directory pages through the store so listings
            beyond one page are never dropped.
            """
            # prune: subtree can't contain the prefix, or every key in
            # it (all sharing key_prefix) sorts <= start
            if prefix and not (key_prefix.startswith(prefix) or
                               prefix.startswith(key_prefix)):
                return
            if start and key_prefix and key_prefix < start and \
                    not start.startswith(key_prefix):
                return
            page: list = []
            last = ""
            while True:
                batch = self.filer.list_directory(
                    dir_path, start_file=last, limit=1000)
                page.extend(batch)
                if len(batch) < 1000:
                    break
                last = batch[-1].name
            def eff(e):
                return e.name + ("/" if e.is_directory else "")
            for e in sorted(page, key=eff):
                if e.is_directory:
                    # hide the reserved multipart scratch dir at the
                    # bucket root and version-archive dirs anywhere;
                    # other dot-prefixed path segments are legal S3
                    # keys (e.g. ".well-known/acme")
                    if e.name.endswith(VERSIONS_EXT):
                        continue
                    if not (key_prefix == "" and
                            e.name == UPLOADS_DIR[1:]):
                        yield from walk_sorted(
                            f"{dir_path}/{e.name}",
                            key_prefix + e.name + "/")
                    continue
                yield key_prefix + e.name, e

        truncated = False
        for key, e in walk_sorted(base, ""):
            if not key.startswith(prefix) or key <= start:
                continue
            # AWS counts Keys + CommonPrefixes toward MaxKeys
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    common.add(prefix + rest.split(delimiter, 1)[0] +
                               delimiter)
                    continue
            contents.append((key, e))

        root = ET.Element("ListBucketResult", xmlns=S3_NS)
        _elem(root, "Name", bucket)
        _elem(root, "Prefix", prefix)
        _elem(root, "MaxKeys", max_keys)
        _elem(root, "KeyCount", len(contents) + len(common))
        _elem(root, "IsTruncated", "true" if truncated else "false")
        if truncated:
            token_key = contents[-1][0] if contents else \
                (sorted(common)[-1] if common else "")
            if token_key:
                _elem(root, "NextContinuationToken", token_key)
        for key, e in contents:
            c = _elem(root, "Contents")
            _elem(c, "Key", key)
            _elem(c, "LastModified", _iso(e.attributes.mtime))
            _elem(c, "ETag", f'"{e.extended.get("etag", "")}"')
            _elem(c, "Size", total_size(e.chunks))
            _elem(c, "StorageClass", "STANDARD")
        for p in sorted(common):
            cp = _elem(root, "CommonPrefixes")
            _elem(cp, "Prefix", p)
        return 200, (_xml(root), "application/xml")

    # -- multipart (filer_multipart.go) -----------------------------------

    def _uploads_path(self, bucket: str, upload_id: str) -> str:
        return f"{self._bucket_path(bucket)}{UPLOADS_DIR}/{upload_id}"

    def _initiate_multipart(self, req: Request, bucket: str,
                            key: str):
        from .policy import resource_arn
        from .sse import (SseError, parse_sse_c_headers,
                          parse_sse_kms_headers)
        upload_id = uuid.uuid4().hex
        marker = Entry(self._uploads_path(bucket, upload_id),
                       is_directory=True)
        marker.extended["key"] = key
        # SSE intent binds at initiation (s3api_object_multipart.go):
        # SSE-C remembers only MD5(key) — each UploadPart must present
        # the key again; SSE-KMS mints the data key NOW so every part
        # encrypts under one key (per-part IVs)
        lower = {k.lower(): v for k, v in req.headers.items()}
        try:
            sse_c = parse_sse_c_headers(lower)
            sse_kms = parse_sse_kms_headers(lower)
        except SseError as e:
            return _error(e.status, e.code, str(e))
        if sse_c is None and sse_kms is None:
            # bucket-default encryption binds at initiation too (AWS
            # applies PutBucketEncryption defaults to multipart)
            sse_kms = self._default_encryption(bucket)
        if sse_c is not None:
            marker.extended["sseKeyMd5"] = sse_c[1]
        elif sse_kms is not None:
            if self.kms is None:
                return _error(501, "NotImplemented",
                              "no KMS configured on this gateway")
            from .sse import kms_encrypt
            try:
                # encrypt an empty body just to mint+seal a data key
                _, sse_ext = kms_encrypt(
                    self.kms, sse_kms[0], sse_kms[1],
                    resource_arn(bucket, key), b"")
            except SseError as e:
                return _error(e.status, e.code, str(e))
            sse_ext.pop("sseIv", None)
            marker.extended.update(sse_ext)
        self.filer.create_entry(marker)
        root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
        _elem(root, "Bucket", bucket)
        _elem(root, "Key", key)
        _elem(root, "UploadId", upload_id)
        return 200, (_xml(root), "application/xml")

    def _multipart_op(self, req: Request, bucket: str, key: str):
        upload_id = req.query["uploadId"]
        updir = self._uploads_path(bucket, upload_id)
        marker = self.filer.find_entry(updir)
        if marker is None:
            return _error(404, "NoSuchUpload", upload_id)
        if req.method == "PUT":
            from .sse import (SseError, encrypt,
                              parse_sse_c_headers)
            part = int(req.query["partNumber"])
            body = req.body
            etag = hashlib.md5(body).hexdigest()
            part_iv = ""
            if not (marker.extended.get("sseKeyMd5") or
                    marker.extended.get("sseKmsBlob")) and any(
                    k.lower().startswith(
                        "x-amz-server-side-encryption")
                    for k in req.headers):
                # SSE headers on a part of a NON-SSE upload: refusing
                # beats silently storing plaintext the client believes
                # is encrypted (AWS rejects the mismatch too)
                return _error(400, "InvalidRequest",
                              "upload was not initiated with SSE")
            if marker.extended.get("sseKeyMd5"):
                # SSE-C upload: the part must present the SAME key
                lower = {k.lower(): v
                         for k, v in req.headers.items()}
                try:
                    sse = parse_sse_c_headers(lower)
                except SseError as e:
                    return _error(e.status, e.code, str(e))
                if sse is None or sse[1] !=                         marker.extended["sseKeyMd5"]:
                    return _error(400, "InvalidRequest",
                                  "UploadPart needs the initiate-"
                                  "time SSE-C key")
                body, part_iv = encrypt(sse[0], body)
            elif marker.extended.get("sseKmsBlob"):
                if self.kms is None:
                    return _error(501, "NotImplemented",
                                  "SSE-KMS upload but no KMS here")
                from ..iam.kms import KmsError
                from .policy import resource_arn
                try:
                    dk = self.kms.decrypt(
                        marker.extended["sseKmsBlob"],
                        {"aws:s3:arn": resource_arn(
                            bucket, marker.extended.get("key", key))})
                except KmsError as e:
                    return _error(403, "AccessDenied", str(e))
                body, part_iv = encrypt(dk["Plaintext"], body)
            e = self.filer.write_file(f"{updir}/{part:05d}.part",
                                      body)
            e.extended["etag"] = etag
            if part_iv:
                e.extended["sseIv"] = part_iv
            self.filer.create_entry(e)
            return 200, (b"", {"ETag": f'"{etag}"'})
        if req.method == "GET":
            root = ET.Element("ListPartsResult", xmlns=S3_NS)
            _elem(root, "Bucket", bucket)
            _elem(root, "Key", key)
            _elem(root, "UploadId", upload_id)
            for e in self.filer.list_directory(updir):
                if e.name.endswith(".part"):
                    p = _elem(root, "Part")
                    _elem(p, "PartNumber", int(e.name.split(".")[0]))
                    _elem(p, "ETag",
                          f'"{e.extended.get("etag", "")}"')
                    _elem(p, "Size", total_size(e.chunks))
            return 200, (_xml(root), "application/xml")
        if req.method == "DELETE":
            self.filer.delete_entry(updir, recursive=True)
            return 204, b""
        if req.method == "POST":
            # CompleteMultipartUpload: stitch the parts the CLIENT's
            # manifest commits (strays from retried attempts are
            # dropped), without copying data (filer_multipart.go)
            manifest: list[int] | None = None
            if req.body.strip():
                manifest = sorted(
                    int(el.text) for el in ET.fromstring(req.body).iter()
                    if el.tag.endswith("PartNumber"))
            parts = sorted(
                (e for e in self.filer.list_directory(updir)
                 if e.name.endswith(".part")),
                key=lambda e: int(e.name.split(".")[0]))
            if manifest is not None:
                parts = [p for p in parts
                         if int(p.name.split(".")[0]) in manifest]
            chunks = []
            offset = 0
            etags = b""
            sse_parts = []
            for p in parts:
                if p.extended.get("sseIv"):
                    sse_parts.append({"offset": offset,
                                      "iv": p.extended["sseIv"]})
                for c in p.chunks:
                    chunks.append(type(c)(c.file_id,
                                          offset + c.offset, c.size,
                                          c.e_tag, c.mtime_ns))
                offset += total_size(p.chunks)
                etags += bytes.fromhex(p.extended.get("etag", ""))
            final_path = f"{self._bucket_path(bucket)}/{key}"
            mp_state = self._versioning_state(bucket)
            # the assembled object is a new version: bucket-default
            # retention applies here too, or multipart becomes a
            # retention-policy bypass
            lock_ext = self._lock_for_put(req, bucket, mp_state)
            if not isinstance(lock_ext, dict):
                return lock_ext
            with self._path_lock(final_path):
                vid = self._pre_write_archive(final_path, mp_state)
                final = Entry(final_path, chunks=chunks)
                final_etag = (hashlib.md5(etags).hexdigest() +
                              f"-{len(parts)}")
                final.extended["etag"] = final_etag
                if sse_parts:
                    import json as _json
                    final.extended["sseParts"] = \
                        _json.dumps(sse_parts)
                    for k in ("sseKeyMd5", "sseAlgorithm",
                              "sseKmsKeyId", "sseKmsBlob"):
                        if marker.extended.get(k):
                            final.extended[k] = marker.extended[k]
                final.extended.update(lock_ext)
                if vid is not None:
                    final.extended["versionId"] = vid
                self.filer.create_entry(final)
            self.filer.delete_entry(updir, recursive=True,
                                    delete_chunks=False)
            root = ET.Element("CompleteMultipartUploadResult",
                              xmlns=S3_NS)
            _elem(root, "Bucket", bucket)
            _elem(root, "Key", key)
            _elem(root, "ETag", f'"{final_etag}"')
            resp = 200, (_xml(root), "application/xml")
            return _with_headers(resp, {"x-amz-version-id": vid}) \
                if vid else resp
        return _error(405, "MethodNotAllowed", req.method)
