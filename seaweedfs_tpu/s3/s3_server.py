"""S3 REST gateway over the filer (weed/s3api/s3api_server.go and
handler files; buckets live under /buckets/<name> as in the reference's
filer layout).

Implemented surface (the core the reference's s3tests exercise first):
  ListBuckets, Create/Delete/Head bucket, Put/Get/Head/Delete object,
  batch DeleteObjects, ListObjectsV2 (prefix/delimiter/continuation),
  multipart (initiate/uploadPart/complete/abort/listParts), SigV4 auth.
"""

from __future__ import annotations

import hashlib
import time
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from ..filer import Entry, Filer
from ..filer.filechunks import total_size
from ..server.httpd import HttpServer, Request
from .auth import SigV4Verifier

BUCKETS_ROOT = "/buckets"
UPLOADS_DIR = "/.uploads"
S3_NS = "http://s3.amazonaws.com/doc/2006-03-01/"


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + \
        ET.tostring(root)


def _elem(parent, tag, text=None):
    e = ET.SubElement(parent, tag)
    if text is not None:
        e.text = str(text)
    return e


def _error(status: int, code: str, message: str):
    root = ET.Element("Error")
    _elem(root, "Code", code)
    _elem(root, "Message", message)
    return status, (_xml(root), "application/xml")


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%S.000Z", time.gmtime(ts))


class S3ApiServer:
    def __init__(self, filer: Filer, host: str = "127.0.0.1",
                 port: int = 0,
                 credentials: dict[str, str] | None = None):
        self.filer = filer
        self.verifier = SigV4Verifier(credentials) if credentials else None
        self.http = HttpServer(host, port)
        self.http.fallback = self._dispatch

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- dispatch ---------------------------------------------------------

    def _dispatch(self, req: Request):
        if self.verifier is not None:
            ok, who = self.verifier.verify(
                req.method, req.path, req.query,
                {k.lower(): v for k, v in req.headers.items()},
                req.body)
            if not ok:
                return _error(403, "AccessDenied", who)
        parts = req.path.lstrip("/").split("/", 1)
        bucket = urllib.parse.unquote(parts[0]) if parts[0] else ""
        key = urllib.parse.unquote(parts[1]) if len(parts) > 1 else ""
        if not bucket:
            if req.method == "GET":
                return self._list_buckets()
            return _error(405, "MethodNotAllowed", req.method)
        if not key:
            return self._bucket_op(req, bucket)
        return self._object_op(req, bucket, key)

    # -- buckets ----------------------------------------------------------

    def _bucket_path(self, bucket: str) -> str:
        return f"{BUCKETS_ROOT}/{bucket}"

    def _list_buckets(self):
        root = ET.Element("ListAllMyBucketsResult", xmlns=S3_NS)
        owner = _elem(root, "Owner")
        _elem(owner, "ID", "seaweedfs-tpu")
        buckets = _elem(root, "Buckets")
        for e in self.filer.list_directory(BUCKETS_ROOT):
            if e.is_directory:
                b = _elem(buckets, "Bucket")
                _elem(b, "Name", e.name)
                _elem(b, "CreationDate", _iso(e.attributes.crtime))
        return 200, (_xml(root), "application/xml")

    def _bucket_op(self, req: Request, bucket: str):
        path = self._bucket_path(bucket)
        if req.method == "PUT":
            self.filer.create_entry(Entry(path, is_directory=True))
            return 200, b""
        if req.method == "HEAD":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            return 200, b""
        if req.method == "DELETE":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            # only the reserved .uploads scratch dir is not bucket content
            children = self.filer.list_directory(path, limit=1000)
            if any(c.name != UPLOADS_DIR[1:] for c in children):
                return _error(409, "BucketNotEmpty", bucket)
            self.filer.delete_entry(path, recursive=True)
            return 204, b""
        if req.method == "GET":
            if self.filer.find_entry(path) is None:
                return _error(404, "NoSuchBucket", bucket)
            return self._list_objects(req, bucket)
        if req.method == "POST" and "delete" in req.query:
            return self._delete_objects(req, bucket)
        return _error(405, "MethodNotAllowed", req.method)

    # -- objects ----------------------------------------------------------

    def _object_op(self, req: Request, bucket: str, key: str):
        if self.filer.find_entry(self._bucket_path(bucket)) is None:
            return _error(404, "NoSuchBucket", bucket)
        if "uploads" in req.query and req.method == "POST":
            return self._initiate_multipart(bucket, key)
        if "uploadId" in req.query:
            return self._multipart_op(req, bucket, key)
        path = f"{self._bucket_path(bucket)}/{key}"
        if req.method == "PUT":
            src = req.headers.get("x-amz-copy-source")
            if src:
                return self._copy_object(req, src, path)
            etag = hashlib.md5(req.body).hexdigest()
            entry = self.filer.write_file(
                path, req.body,
                mime=req.headers.get("Content-Type", ""))
            entry.extended["etag"] = etag
            amz = {k: v for k, v in req.headers.items()
                   if k.lower().startswith("x-amz-meta-")}
            entry.extended.update(amz)
            self.filer.create_entry(entry)
            return 200, (b"", {"ETag": f'"{etag}"'})
        entry = self.filer.find_entry(path)
        if req.method in ("GET", "HEAD"):
            if entry is None or entry.is_directory:
                return _error(404, "NoSuchKey", key)
            data = b"" if req.method == "HEAD" else \
                self.filer.read_file(path)
            etag = entry.extended.get("etag", "")
            mime = entry.attributes.mime or "application/octet-stream"
            return 200, (data, {"Content-Type": mime,
                                "ETag": f'"{etag}"',
                                "Content-Length":
                                    str(total_size(entry.chunks)),
                                "Last-Modified": _iso(
                                    entry.attributes.mtime)})
        if req.method == "DELETE":
            if entry is not None:
                self.filer.delete_entry(path)
                self._prune_empty_dirs(path, bucket)
            return 204, b""
        return _error(405, "MethodNotAllowed", req.method)

    def _prune_empty_dirs(self, path: str, bucket: str) -> None:
        """Remove now-empty parent directories up to the bucket root
        (S3 has no directories — an emptied prefix must disappear;
        s3api/s3api_object_handlers_delete.go doDeleteEmptyDirectories)."""
        stop = self._bucket_path(bucket)
        parent = path.rsplit("/", 1)[0]
        while parent != stop and parent.startswith(stop + "/"):
            if self.filer.list_directory(parent, limit=1):
                break
            try:
                self.filer.delete_entry(parent)
            except IsADirectoryError:
                break  # concurrent PUT repopulated it — keep it
            parent = parent.rsplit("/", 1)[0]

    def _copy_object(self, req: Request, src: str, dst_path: str):
        src = urllib.parse.unquote(src.lstrip("/"))
        src_path = f"{BUCKETS_ROOT}/{src}"
        entry = self.filer.find_entry(src_path)
        if entry is None:
            return _error(404, "NoSuchKey", src)
        data = self.filer.read_file(src_path)
        etag = hashlib.md5(data).hexdigest()
        new = self.filer.write_file(dst_path, data,
                                    mime=entry.attributes.mime)
        new.extended["etag"] = etag
        self.filer.create_entry(new)
        root = ET.Element("CopyObjectResult", xmlns=S3_NS)
        _elem(root, "ETag", f'"{etag}"')
        _elem(root, "LastModified", _iso(time.time()))
        return 200, (_xml(root), "application/xml")

    def _delete_objects(self, req: Request, bucket: str):
        """POST /bucket?delete — batch delete."""
        root = ET.fromstring(req.body)
        result = ET.Element("DeleteResult", xmlns=S3_NS)
        for obj in root.iter():
            if obj.tag.endswith("Key"):
                key = obj.text or ""
                path = f"{self._bucket_path(bucket)}/{key}"
                self.filer.delete_entry(path)
                self._prune_empty_dirs(path, bucket)
                d = _elem(result, "Deleted")
                _elem(d, "Key", key)
        return 200, (_xml(result), "application/xml")

    # -- ListObjectsV2 (s3api_objects_list_handlers.go) -------------------

    def _list_objects(self, req: Request, bucket: str):
        prefix = req.query.get("prefix", "")
        delimiter = req.query.get("delimiter", "")
        max_keys = int(req.query.get("max-keys", 1000))
        token = req.query.get("continuation-token", "")
        start_after = req.query.get("start-after", "")
        start = max(token, start_after)
        base = self._bucket_path(bucket)

        contents: list[tuple[str, Entry]] = []
        common: set[str] = set()

        def walk_sorted(dir_path: str, key_prefix: str):
            """Yield (key, entry) in global lexicographic key order.

            Children sort by their *effective* key start (name for
            files, name + "/" for directories — "a!" must come before
            "a/b"); each directory pages through the store so listings
            beyond one page are never dropped.
            """
            # prune: subtree can't contain the prefix, or every key in
            # it (all sharing key_prefix) sorts <= start
            if prefix and not (key_prefix.startswith(prefix) or
                               prefix.startswith(key_prefix)):
                return
            if start and key_prefix and key_prefix < start and \
                    not start.startswith(key_prefix):
                return
            page: list = []
            last = ""
            while True:
                batch = self.filer.list_directory(
                    dir_path, start_file=last, limit=1000)
                page.extend(batch)
                if len(batch) < 1000:
                    break
                last = batch[-1].name
            def eff(e):
                return e.name + ("/" if e.is_directory else "")
            for e in sorted(page, key=eff):
                if e.is_directory:
                    # hide only the reserved multipart scratch dir at the
                    # bucket root; dot-prefixed path segments are legal
                    # S3 keys (e.g. ".well-known/acme")
                    if not (key_prefix == "" and
                            e.name == UPLOADS_DIR[1:]):
                        yield from walk_sorted(
                            f"{dir_path}/{e.name}",
                            key_prefix + e.name + "/")
                    continue
                yield key_prefix + e.name, e

        truncated = False
        for key, e in walk_sorted(base, ""):
            if not key.startswith(prefix) or key <= start:
                continue
            # AWS counts Keys + CommonPrefixes toward MaxKeys
            if len(contents) + len(common) >= max_keys:
                truncated = True
                break
            if delimiter:
                rest = key[len(prefix):]
                if delimiter in rest:
                    common.add(prefix + rest.split(delimiter, 1)[0] +
                               delimiter)
                    continue
            contents.append((key, e))

        root = ET.Element("ListBucketResult", xmlns=S3_NS)
        _elem(root, "Name", bucket)
        _elem(root, "Prefix", prefix)
        _elem(root, "MaxKeys", max_keys)
        _elem(root, "KeyCount", len(contents) + len(common))
        _elem(root, "IsTruncated", "true" if truncated else "false")
        if truncated:
            token_key = contents[-1][0] if contents else \
                (sorted(common)[-1] if common else "")
            if token_key:
                _elem(root, "NextContinuationToken", token_key)
        for key, e in contents:
            c = _elem(root, "Contents")
            _elem(c, "Key", key)
            _elem(c, "LastModified", _iso(e.attributes.mtime))
            _elem(c, "ETag", f'"{e.extended.get("etag", "")}"')
            _elem(c, "Size", total_size(e.chunks))
            _elem(c, "StorageClass", "STANDARD")
        for p in sorted(common):
            cp = _elem(root, "CommonPrefixes")
            _elem(cp, "Prefix", p)
        return 200, (_xml(root), "application/xml")

    # -- multipart (filer_multipart.go) -----------------------------------

    def _uploads_path(self, bucket: str, upload_id: str) -> str:
        return f"{self._bucket_path(bucket)}{UPLOADS_DIR}/{upload_id}"

    def _initiate_multipart(self, bucket: str, key: str):
        upload_id = uuid.uuid4().hex
        marker = Entry(self._uploads_path(bucket, upload_id),
                       is_directory=True)
        marker.extended["key"] = key
        self.filer.create_entry(marker)
        root = ET.Element("InitiateMultipartUploadResult", xmlns=S3_NS)
        _elem(root, "Bucket", bucket)
        _elem(root, "Key", key)
        _elem(root, "UploadId", upload_id)
        return 200, (_xml(root), "application/xml")

    def _multipart_op(self, req: Request, bucket: str, key: str):
        upload_id = req.query["uploadId"]
        updir = self._uploads_path(bucket, upload_id)
        marker = self.filer.find_entry(updir)
        if marker is None:
            return _error(404, "NoSuchUpload", upload_id)
        if req.method == "PUT":
            part = int(req.query["partNumber"])
            etag = hashlib.md5(req.body).hexdigest()
            e = self.filer.write_file(f"{updir}/{part:05d}.part",
                                      req.body)
            e.extended["etag"] = etag
            self.filer.create_entry(e)
            return 200, (b"", {"ETag": f'"{etag}"'})
        if req.method == "GET":
            root = ET.Element("ListPartsResult", xmlns=S3_NS)
            _elem(root, "Bucket", bucket)
            _elem(root, "Key", key)
            _elem(root, "UploadId", upload_id)
            for e in self.filer.list_directory(updir):
                if e.name.endswith(".part"):
                    p = _elem(root, "Part")
                    _elem(p, "PartNumber", int(e.name.split(".")[0]))
                    _elem(p, "ETag",
                          f'"{e.extended.get("etag", "")}"')
                    _elem(p, "Size", total_size(e.chunks))
            return 200, (_xml(root), "application/xml")
        if req.method == "DELETE":
            self.filer.delete_entry(updir, recursive=True)
            return 204, b""
        if req.method == "POST":
            # CompleteMultipartUpload: stitch the parts the CLIENT's
            # manifest commits (strays from retried attempts are
            # dropped), without copying data (filer_multipart.go)
            manifest: list[int] | None = None
            if req.body.strip():
                manifest = sorted(
                    int(el.text) for el in ET.fromstring(req.body).iter()
                    if el.tag.endswith("PartNumber"))
            parts = sorted(
                (e for e in self.filer.list_directory(updir)
                 if e.name.endswith(".part")),
                key=lambda e: int(e.name.split(".")[0]))
            if manifest is not None:
                parts = [p for p in parts
                         if int(p.name.split(".")[0]) in manifest]
            chunks = []
            offset = 0
            etags = b""
            for p in parts:
                for c in p.chunks:
                    chunks.append(type(c)(c.file_id,
                                          offset + c.offset, c.size,
                                          c.e_tag, c.mtime_ns))
                offset += total_size(p.chunks)
                etags += bytes.fromhex(p.extended.get("etag", ""))
            final = Entry(f"{self._bucket_path(bucket)}/{key}",
                          chunks=chunks)
            final_etag = (hashlib.md5(etags).hexdigest() +
                          f"-{len(parts)}")
            final.extended["etag"] = final_etag
            self.filer.create_entry(final)
            self.filer.delete_entry(updir, recursive=True,
                                    delete_chunks=False)
            root = ET.Element("CompleteMultipartUploadResult",
                              xmlns=S3_NS)
            _elem(root, "Bucket", bucket)
            _elem(root, "Key", key)
            _elem(root, "ETag", f'"{final_etag}"')
            return 200, (_xml(root), "application/xml")
        return _error(405, "MethodNotAllowed", req.method)
