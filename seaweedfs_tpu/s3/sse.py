"""SSE-C: server-side encryption with customer-provided keys
(weed/s3api/s3_sse_c.go).

The client supplies a 256-bit key per request; the server encrypts the
object with AES-256-CTR under a random IV (stored in entry metadata,
never the key), remembers only MD5(key) to verify later requests, and
requires the SAME key headers on every GET/HEAD:

  x-amz-server-side-encryption-customer-algorithm: AES256
  x-amz-server-side-encryption-customer-key:      base64(32-byte key)
  x-amz-server-side-encryption-customer-key-MD5:  base64(md5(key))
"""

from __future__ import annotations

import base64
import hashlib
import os

ALGO_HEADER = "x-amz-server-side-encryption-customer-algorithm"
KEY_HEADER = "x-amz-server-side-encryption-customer-key"
KEY_MD5_HEADER = "x-amz-server-side-encryption-customer-key-md5"


class SseError(ValueError):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code


def parse_sse_c_headers(headers: dict) -> "tuple[bytes, str] | None":
    """Returns (key, key_md5_b64) or None when no SSE-C headers.
    Raises SseError on malformed/mismatched headers
    (s3_sse_c.go validateSSECHeaders)."""
    algo = headers.get(ALGO_HEADER, "")
    key_b64 = headers.get(KEY_HEADER, "")
    md5_b64 = headers.get(KEY_MD5_HEADER, "")
    if not (algo or key_b64 or md5_b64):
        return None
    if algo != "AES256":
        raise SseError(400, "InvalidArgument",
                       f"unsupported SSE-C algorithm {algo!r}")
    try:
        key = base64.b64decode(key_b64)
    except ValueError:
        raise SseError(400, "InvalidArgument", "bad SSE-C key encoding")
    if len(key) != 32:
        raise SseError(400, "InvalidArgument",
                       "SSE-C key must be 256 bits")
    want_md5 = base64.b64encode(hashlib.md5(key).digest()).decode()
    if md5_b64 != want_md5:
        raise SseError(400, "InvalidArgument", "SSE-C key MD5 mismatch")
    return key, md5_b64


def encrypt(key: bytes, plaintext: bytes) -> "tuple[bytes, str]":
    """AES-256-CTR under a fresh IV; returns (ciphertext, iv_hex)."""
    from cryptography.hazmat.primitives.ciphers import (Cipher,
                                                        algorithms,
                                                        modes)
    iv = os.urandom(16)
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return enc.update(plaintext) + enc.finalize(), iv.hex()


def decrypt(key: bytes, iv_hex: str, ciphertext: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers import (Cipher,
                                                        algorithms,
                                                        modes)
    dec = Cipher(algorithms.AES(key),
                 modes.CTR(bytes.fromhex(iv_hex))).decryptor()
    return dec.update(ciphertext) + dec.finalize()


def decrypt_entry(key: bytes, entry_extended: dict,
                  data: bytes) -> bytes:
    """Decrypt an object body with entry-level SSE metadata: either a
    single IV (plain PUT) or the per-part IV table a multipart
    completion records (each part was encrypted separately, CTR keeps
    lengths so ciphertext offsets == plaintext offsets)."""
    import json as _json
    parts = entry_extended.get("sseParts")
    if not parts:
        return decrypt(key, entry_extended["sseIv"], data)
    table = _json.loads(parts)
    out = bytearray(len(data))
    for i, p in enumerate(table):
        start = int(p["offset"])
        stop = int(table[i + 1]["offset"]) if i + 1 < len(table) \
            else len(data)
        out[start:stop] = decrypt(key, p["iv"], data[start:stop])
    return bytes(out)


SSE_HEADER = "x-amz-server-side-encryption"
SSE_KMS_KEY_HEADER = "x-amz-server-side-encryption-aws-kms-key-id"
DEFAULT_KMS_ALIAS = "aws/s3"   # SSE-S3 (AES256) rides a default key


def parse_sse_kms_headers(headers: dict
                          ) -> "tuple[str, str] | None":
    """Returns (mode, key_identifier) for SSE-KMS / SSE-S3 requests:
    mode is "aws:kms" or "AES256"; key id may be empty (default key).
    Raises on SSE-C + SSE-KMS on one request (mutually exclusive,
    s3_sse_kms.go validation)."""
    mode = headers.get(SSE_HEADER, "")
    if not mode:
        return None
    if mode not in ("aws:kms", "AES256"):
        raise SseError(400, "InvalidArgument",
                       f"unsupported SSE algorithm {mode!r}")
    if headers.get(KEY_HEADER):
        raise SseError(400, "InvalidArgument",
                       "SSE-C and SSE-KMS are mutually exclusive")
    return mode, headers.get(SSE_KMS_KEY_HEADER, "")


def kms_encrypt(kms, mode: str, key_identifier: str, arn: str,
                plaintext: bytes) -> "tuple[bytes, dict]":
    """Envelope-encrypt an object body: fresh data key from the KMS,
    AES-256-CTR over the body, sealed blob + IV into entry metadata
    (kms/envelope.go + s3_sse_kms.go).  The object ARN binds the
    encryption context."""
    from ..iam.kms import KmsError
    if not key_identifier:
        key_identifier = DEFAULT_KMS_ALIAS
        # probe once per provider instance: the result never changes
        # after first success, and a remote KMS would otherwise pay
        # an extra DescribeKey round-trip on EVERY default-key PUT
        if not getattr(kms, "_default_key_ok", False):
            try:
                kms.get_key_id(key_identifier)
            except KmsError as e:
                if "NotFound" not in str(e):
                    # a 503/AccessDenied is NOT a missing key —
                    # misreporting it would tell the operator to
                    # provision a key that already exists
                    raise SseError(503, "ServiceUnavailable", str(e))
                if not hasattr(kms, "create_key"):
                    # remote KMS providers don't auto-mint: the
                    # default key is provisioned out of band
                    raise SseError(400, "InvalidArgument",
                                   f"no default key "
                                   f"({DEFAULT_KMS_ALIAS}) on the "
                                   f"KMS")
                kms.create_key(alias=key_identifier,
                               description="default S3 key")
            kms._default_key_ok = True
    try:
        dk = kms.generate_data_key(key_identifier,
                                   {"aws:s3:arn": arn})
    except KmsError as e:
        # bad/disabled key ids are client errors, not gateway crashes
        raise SseError(400, "InvalidArgument", str(e))
    ciphertext, iv_hex = encrypt(dk["Plaintext"], plaintext)
    return ciphertext, {
        "sseAlgorithm": mode,
        "sseKmsKeyId": dk["KeyId"],
        "sseKmsBlob": dk["CiphertextBlob"],
        "sseIv": iv_hex,
    }


def kms_decrypt(kms, entry_extended: dict, arn: str,
                ciphertext: bytes) -> bytes:
    from ..iam.kms import KmsError
    try:
        dk = kms.decrypt(entry_extended["sseKmsBlob"],
                         {"aws:s3:arn": arn})
    except KmsError as e:
        raise SseError(403, "AccessDenied", str(e))
    return decrypt_entry(dk["Plaintext"], entry_extended, ciphertext)


def kms_response_headers(entry_extended: dict) -> dict:
    if not entry_extended.get("sseKmsBlob"):
        return {}
    h = {SSE_HEADER: entry_extended.get("sseAlgorithm", "aws:kms")}
    if h[SSE_HEADER] == "aws:kms":
        h[SSE_KMS_KEY_HEADER] = entry_extended.get("sseKmsKeyId", "")
    return h


def check_read_key(entry_extended: dict, headers: dict
                   ) -> "bytes | None":
    """For a GET/HEAD of an object: returns the key to decrypt with,
    None for unencrypted objects.  Raises SseError when the object is
    encrypted and the request's key is absent or wrong
    (s3_sse_c.go: 400 without key, 403 on mismatch)."""
    stored_md5 = entry_extended.get("sseKeyMd5", "")
    provided = parse_sse_c_headers(headers)
    if not stored_md5:
        if provided is not None:
            raise SseError(400, "InvalidArgument",
                           "object is not SSE-C encrypted")
        return None
    if provided is None:
        raise SseError(
            400, "InvalidRequest",
            "object was stored with SSE-C; the key headers are "
            "required to read it")
    key, md5_b64 = provided
    if md5_b64 != stored_md5:
        raise SseError(403, "AccessDenied", "SSE-C key does not match")
    return key
