"""Bucket CORS configuration + request evaluation
(weed/s3api/cors/ — PutBucketCors/GetBucketCors + the middleware that
answers preflights and decorates responses).

Config is the standard XML:
  <CORSConfiguration><CORSRule>
    <AllowedOrigin>https://a.example</AllowedOrigin>
    <AllowedMethod>GET</AllowedMethod>
    <AllowedHeader>*</AllowedHeader>
    <ExposeHeader>ETag</ExposeHeader>
    <MaxAgeSeconds>3000</MaxAgeSeconds>
  </CORSRule>...</CORSConfiguration>
Stored per bucket; evaluated per request Origin/method.
"""

from __future__ import annotations

import fnmatch
import xml.etree.ElementTree as ET
from dataclasses import dataclass, field


@dataclass
class CorsRule:
    allowed_origins: list[str] = field(default_factory=list)
    allowed_methods: list[str] = field(default_factory=list)
    allowed_headers: list[str] = field(default_factory=list)
    expose_headers: list[str] = field(default_factory=list)
    max_age_seconds: int | None = None

    def matches_origin(self, origin: str) -> bool:
        return any(fnmatch.fnmatchcase(origin, pat)
                   for pat in self.allowed_origins)

    def allows_headers(self, req_headers: list[str]) -> bool:
        for h in req_headers:
            h = h.strip().lower()
            if not h:
                continue
            if not any(fnmatch.fnmatchcase(h, pat.lower())
                       for pat in self.allowed_headers):
                return False
        return True


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def parse_cors_config(xml_bytes: bytes) -> list[CorsRule]:
    """Raises ValueError on malformed config (PutBucketCors validates
    before storing)."""
    root = ET.fromstring(xml_bytes)
    rules = []
    for rule_el in root:
        if _local(rule_el.tag) != "CORSRule":
            continue
        rule = CorsRule()
        for el in rule_el:
            tag, text = _local(el.tag), (el.text or "").strip()
            if tag == "AllowedOrigin":
                rule.allowed_origins.append(text)
            elif tag == "AllowedMethod":
                rule.allowed_methods.append(text.upper())
            elif tag == "AllowedHeader":
                rule.allowed_headers.append(text)
            elif tag == "ExposeHeader":
                rule.expose_headers.append(text)
            elif tag == "MaxAgeSeconds":
                rule.max_age_seconds = int(text)
        if not rule.allowed_origins or not rule.allowed_methods:
            raise ValueError(
                "CORSRule needs AllowedOrigin and AllowedMethod")
        rules.append(rule)
    if not rules:
        raise ValueError("no CORSRule in configuration")
    return rules


def evaluate(rules: list[CorsRule], origin: str, method: str,
             request_headers: str = "") -> dict | None:
    """Returns the CORS response headers for a matching rule, or None.
    `method` is the actual method (simple requests) or the preflight's
    Access-Control-Request-Method."""
    req_hdrs = [h for h in request_headers.split(",") if h.strip()] \
        if request_headers else []
    for rule in rules:
        if not rule.matches_origin(origin):
            continue
        if method.upper() not in rule.allowed_methods:
            continue
        if req_hdrs and not rule.allows_headers(req_hdrs):
            continue
        headers = {
            "Access-Control-Allow-Origin":
                "*" if rule.allowed_origins == ["*"] else origin,
            "Access-Control-Allow-Methods":
                ", ".join(rule.allowed_methods),
            "Vary": "Origin",
        }
        if req_hdrs:
            headers["Access-Control-Allow-Headers"] = request_headers
        if rule.expose_headers:
            headers["Access-Control-Expose-Headers"] = \
                ", ".join(rule.expose_headers)
        if rule.max_age_seconds is not None:
            headers["Access-Control-Max-Age"] = \
                str(rule.max_age_seconds)
        return headers
    return None
