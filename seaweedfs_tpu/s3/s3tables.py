"""S3 Tables (Iceberg table-bucket) surface — the reference's
weed/s3api/s3tables/ package re-designed over our filer interface.

Wire protocol (handler.go:88): POST / with an `X-Amz-Target:
S3Tables.<Operation>` header and a JSON body; errors are JSON
`{"__type": code, "message": ...}`.  22 operations over three
resource levels:

    table bucket   /buckets/<bucket>            (extended s3tables.tableBucket)
    namespace      /buckets/<bucket>/<ns>       (extended s3tables.namespace)
    table          /buckets/<bucket>/<ns>/<tbl> (extended s3tables.metadata)

plus resource policies (s3tables.policy) and tags (s3tables.tags) on
bucket/table entries, version-token optimistic concurrency on table
mutations (utils.go generateVersionToken), and the Iceberg file-layout
validator (iceberg_layout.go) the object path applies to writes into
table buckets.

ARNs follow the reference (utils.go buildARN):
    arn:aws:s3tables:<region>:<account>:bucket/<name>
    arn:aws:s3tables:<region>:<account>:bucket/<name>/table/<ns>/<tbl>
"""

from __future__ import annotations

import json
import re
import secrets
import time

from ..filer.entry import Entry

DEFAULT_ACCOUNT = "000000000000"
DEFAULT_REGION = "us-east-1"
BUCKETS_ROOT = "/buckets"

X_TABLE_BUCKET = "s3tables.tableBucket"
X_NAMESPACE = "s3tables.namespace"
X_METADATA = "s3tables.metadata"
X_POLICY = "s3tables.policy"
X_TAGS = "s3tables.tags"

# utils.go validateBucketName: 3-63 chars, lowercase alnum + hyphen,
# alnum at both ends.  validateNamespacePart/validateTableName: 1-255
# chars, lowercase alnum + underscore, alnum at both ends.
_BUCKET_RE = re.compile(
    r"[a-z0-9](?:[a-z0-9\-]{1,61}[a-z0-9])?")
_PART_RE = re.compile(r"[a-z0-9](?:[a-z0-9_]{0,253}[a-z0-9])?")
_TAG_RE = re.compile(r"^[\w .:/=+\-@]+$")
_UUID = r"[a-f0-9]{8}-[a-f0-9]{4}-[a-f0-9]{4}-[a-f0-9]{4}-[a-f0-9]{12}"

# iceberg_layout.go: the two allowed table subtrees and their file
# shapes.  metadata/: versioned table metadata, snapshot manifest
# lists, manifests, version hint, stats.  data/: columnar files,
# optionally under partition directories (year=2024/...).
_META_FILES = [re.compile(p) for p in (
    r"^v\d+\.metadata\.json$",
    rf"^snap-\d+-\d+-{_UUID}\.avro$",
    rf"^{_UUID}-m\d+\.avro$",
    rf"^{_UUID}\.avro$",
    r"^version-hint\.text$",
    rf"^{_UUID}\.metadata\.json$",
    r"^[^/]+\.stats$",
)]
_DATA_FILES = [re.compile(p) for p in (
    r"^[^/]+\.parquet$", r"^[^/]+\.orc$", r"^[^/]+\.avro$")]
_PARTITION_DIR = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*=[^/]+$")


class S3TablesError(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code
        self.message = message


def _bad(msg: str) -> S3TablesError:
    return S3TablesError(400, "InvalidRequest", msg)


def _not_found(kind: str, what: str) -> S3TablesError:
    return S3TablesError(404, "NotFoundException",
                         f"{kind} {what} not found")


def _validate_name(name: str, kind: str) -> None:
    if kind == "bucket":
        if not name or len(name) < 3 or \
                not _BUCKET_RE.fullmatch(name):
            raise _bad(f"invalid bucket name {name!r} (3-63 chars, "
                       "lowercase alnum/hyphen, alnum ends)")
        return
    if not name or not _PART_RE.fullmatch(name):
        raise _bad(f"invalid {kind} name {name!r} (1-255 chars, "
                   "lowercase alnum/underscore, alnum ends)")


def _validate_tags(tags: dict) -> None:
    if len(tags) > 10:
        raise _bad(f"{len(tags)} tags; max 10")
    for k, v in tags.items():
        if not k or len(k) > 128 or not _TAG_RE.match(k):
            raise _bad(f"bad tag key {k!r}")
        if len(v) > 256 or (v and not _TAG_RE.match(v)):
            raise _bad(f"bad tag value {v!r}")


def validate_iceberg_key(key: str) -> "str | None":
    """None when `key` (namespace/table/...) is a valid write into an
    Iceberg table subtree; else the reason (iceberg_layout.go).  The
    caller has already resolved namespace and table existence."""
    parts = key.split("/")
    if len(parts) < 3:
        return ("objects in a table bucket live under "
                "<namespace>/<table>/{metadata,data}/...")
    subtree, rest = parts[2], parts[3:]
    if subtree not in ("metadata", "data"):
        return f"directory {subtree!r} not allowed (metadata|data)"
    if not rest:
        return "missing file name"
    fname = rest[-1]
    if subtree == "metadata":
        if len(rest) != 1:
            return "metadata/ holds files directly, no subdirs"
        if not any(p.match(fname) for p in _META_FILES):
            return f"{fname!r} is not a recognized metadata file"
        return None
    for d in rest[:-1]:
        if not _PARTITION_DIR.match(d) and \
                not re.fullmatch(r"[a-zA-Z0-9_\-]+", d):
            return f"bad partition directory {d!r}"
    if not any(p.match(fname) for p in _DATA_FILES):
        return f"{fname!r} is not a data file (parquet|orc|avro)"
    return None


def bucket_arn(name: str, region: str = DEFAULT_REGION,
               account: str = DEFAULT_ACCOUNT) -> str:
    return f"arn:aws:s3tables:{region}:{account}:bucket/{name}"


def table_arn(bucket: str, ns: str, table: str,
              region: str = DEFAULT_REGION,
              account: str = DEFAULT_ACCOUNT) -> str:
    return (f"arn:aws:s3tables:{region}:{account}:bucket/{bucket}"
            f"/table/{ns}/{table}")


def parse_bucket_arn(arn: str) -> str:
    """ARN or bare name -> bucket name (utils.go
    parseBucketNameFromARN accepts both)."""
    if not arn.startswith("arn:"):
        return arn
    tail = arn.split(":", 5)[-1]
    if not tail.startswith("bucket/"):
        raise _bad(f"not a table-bucket ARN: {arn}")
    return tail.split("/")[1]


def parse_table_arn(arn: str) -> tuple[str, str, str]:
    tail = arn.split(":", 5)[-1]
    m = re.fullmatch(r"bucket/([^/]+)/table/([^/]+)/([^/]+)", tail)
    if not m:
        raise _bad(f"not a table ARN: {arn}")
    return m.group(1), m.group(2), m.group(3)


def _iso(ts: float) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ts))


def is_table_bucket(entry) -> bool:
    """bucket_metadata.go IsTableBucketEntry: the marker attribute
    separates table buckets from object-store buckets sharing
    /buckets."""
    return entry is not None and X_TABLE_BUCKET in \
        getattr(entry, "extended", {})


class S3TablesStore:
    """All 22 operations against a Filer-shaped backend (in-process
    Filer or FilerClient both work — find_entry/create_entry/
    list_directory/delete_entry)."""

    def __init__(self, filer, region: str = DEFAULT_REGION,
                 account: str = DEFAULT_ACCOUNT):
        self.filer = filer
        self.region = region
        self.account = account

    # -- entry helpers ----------------------------------------------------

    def _mkdir(self, path: str, extended: dict) -> None:
        e = Entry(path, is_directory=True)
        e.extended.update(extended)
        self.filer.create_entry(e)

    def _get(self, path: str):
        return self.filer.find_entry(path)

    def _patch(self, entry, **extended) -> None:
        for k, v in extended.items():
            if v is None:
                entry.extended.pop(k, None)
            else:
                entry.extended[k] = v
        self.filer.create_entry(entry, create_parents=False)

    def _list_all(self, directory: str, start_file: str = "",
                  prefix: str = ""):
        """PAGINATED directory walk: a flat list_directory(limit=N)
        call silently truncates past N children and the result looks
        complete — every S3Tables listing iterates through this."""
        last = start_file
        while True:
            batch = self.filer.list_directory(
                directory, start_file=last, limit=500, prefix=prefix)
            yield from batch
            if len(batch) < 500:
                return
            last = batch[-1].name

    def _bucket_entry(self, name: str):
        e = self._get(f"{BUCKETS_ROOT}/{name}")
        if e is None or not is_table_bucket(e):
            raise _not_found("table bucket", name)
        return e

    def _ns_entry(self, bucket: str, ns: str):
        self._bucket_entry(bucket)
        e = self._get(f"{BUCKETS_ROOT}/{bucket}/{ns}")
        if e is None or X_NAMESPACE not in e.extended:
            raise _not_found("namespace", f"{bucket}/{ns}")
        return e

    def _table_entry(self, bucket: str, ns: str, table: str):
        self._ns_entry(bucket, ns)
        e = self._get(f"{BUCKETS_ROOT}/{bucket}/{ns}/{table}")
        if e is None or X_METADATA not in e.extended:
            raise _not_found("table", f"{bucket}/{ns}/{table}")
        return e

    @staticmethod
    def _meta(entry, key: str) -> dict:
        raw = entry.extended.get(key, "")
        if isinstance(raw, bytes):
            raw = raw.decode()
        return json.loads(raw) if raw else {}

    # -- table buckets ----------------------------------------------------

    def create_table_bucket(self, name: str, owner: str = "",
                            tags: "dict | None" = None) -> dict:
        _validate_name(name, "bucket")
        if tags:
            _validate_tags(tags)
        existing = self._get(f"{BUCKETS_ROOT}/{name}")
        if existing is not None:
            code = "BucketAlreadyExists"
            kind = "table bucket" if is_table_bucket(existing) \
                else "object-store bucket"
            raise S3TablesError(409, code,
                                f"{kind} {name} already exists")
        meta = {"name": name, "createdAt": _iso(time.time()),
                "ownerAccountId": owner or self.account}
        ext = {X_TABLE_BUCKET: json.dumps(meta)}
        if tags:
            ext[X_TAGS] = json.dumps(tags)
        self._mkdir(f"{BUCKETS_ROOT}/{name}", ext)
        return {"arn": bucket_arn(name, self.region, self.account)}

    def get_table_bucket(self, arn: str) -> dict:
        name = parse_bucket_arn(arn)
        meta = self._meta(self._bucket_entry(name), X_TABLE_BUCKET)
        return {"arn": bucket_arn(name, self.region, self.account),
                "name": name,
                "ownerAccountId": meta.get("ownerAccountId",
                                           self.account),
                "createdAt": meta.get("createdAt", "")}

    def list_table_buckets(self, prefix: str = "",
                           continuation: str = "",
                           max_buckets: int = 0) -> dict:
        out, token = [], ""
        limit = max_buckets or 100
        for e in self._list_all(BUCKETS_ROOT, continuation, prefix):
            if not is_table_bucket(e):
                continue
            if len(out) >= limit:
                token = out[-1]["name"]
                break
            meta = self._meta(e, X_TABLE_BUCKET)
            out.append({"arn": bucket_arn(e.name, self.region,
                                          self.account),
                        "name": e.name,
                        "createdAt": meta.get("createdAt", "")})
        resp = {"tableBuckets": out}
        if token:
            resp["continuationToken"] = token
        return resp

    def delete_table_bucket(self, arn: str) -> dict:
        name = parse_bucket_arn(arn)
        self._bucket_entry(name)
        kids = self.filer.list_directory(f"{BUCKETS_ROOT}/{name}",
                                         limit=2)
        if kids:
            raise S3TablesError(
                409, "BucketNotEmpty",
                f"table bucket {name} still has namespaces")
        self.filer.delete_entry(f"{BUCKETS_ROOT}/{name}",
                                recursive=True)
        return {}

    # -- namespaces -------------------------------------------------------

    def create_namespace(self, bucket_arn_: str, namespace: list,
                         owner: str = "",
                         properties: "dict | None" = None) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        self._bucket_entry(bucket)
        if not namespace or len(namespace) != 1:
            raise _bad("namespace must be a single-element list")
        ns = namespace[0]
        _validate_name(ns, "namespace")
        if self._get(f"{BUCKETS_ROOT}/{bucket}/{ns}") is not None:
            raise S3TablesError(409, "NamespaceAlreadyExists",
                                f"namespace {ns} already exists")
        meta = {"namespace": [ns], "createdAt": _iso(time.time()),
                "ownerAccountId": owner or self.account}
        if properties:
            meta["properties"] = properties
        self._mkdir(f"{BUCKETS_ROOT}/{bucket}/{ns}",
                    {X_NAMESPACE: json.dumps(meta)})
        return {"namespace": [ns],
                "tableBucketARN": bucket_arn(bucket, self.region,
                                             self.account)}

    def get_namespace(self, bucket_arn_: str, namespace: list) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        ns = namespace[0] if namespace else ""
        meta = self._meta(self._ns_entry(bucket, ns), X_NAMESPACE)
        return {"namespace": [ns],
                "createdAt": meta.get("createdAt", ""),
                "ownerAccountId": meta.get("ownerAccountId",
                                           self.account),
                **({"properties": meta["properties"]}
                   if meta.get("properties") else {})}

    def list_namespaces(self, bucket_arn_: str, prefix: str = "",
                        continuation: str = "",
                        max_namespaces: int = 0) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        self._bucket_entry(bucket)
        out, token = [], ""
        limit = max_namespaces or 100
        for e in self._list_all(f"{BUCKETS_ROOT}/{bucket}",
                                continuation, prefix):
            if X_NAMESPACE not in e.extended:
                continue
            if len(out) >= limit:
                token = out[-1]["namespace"][0]
                break
            meta = self._meta(e, X_NAMESPACE)
            out.append({"namespace": [e.name],
                        "createdAt": meta.get("createdAt", "")})
        resp = {"namespaces": out}
        if token:
            resp["continuationToken"] = token
        return resp

    def delete_namespace(self, bucket_arn_: str,
                         namespace: list) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        ns = namespace[0] if namespace else ""
        self._ns_entry(bucket, ns)
        kids = self.filer.list_directory(
            f"{BUCKETS_ROOT}/{bucket}/{ns}", limit=2)
        if kids:
            raise S3TablesError(409, "NamespaceNotEmpty",
                                f"namespace {ns} still has tables")
        self.filer.delete_entry(f"{BUCKETS_ROOT}/{bucket}/{ns}",
                                recursive=True)
        return {}

    # -- tables -----------------------------------------------------------

    def create_table(self, bucket_arn_: str, namespace: list,
                     name: str, fmt: str = "ICEBERG",
                     metadata: "dict | None" = None,
                     metadata_location: str = "",
                     owner: str = "",
                     tags: "dict | None" = None) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        ns = namespace[0] if namespace else ""
        self._ns_entry(bucket, ns)
        _validate_name(name, "table")
        if fmt and fmt.upper() != "ICEBERG":
            raise _bad(f"unsupported table format {fmt!r}")
        if tags:
            _validate_tags(tags)
        path = f"{BUCKETS_ROOT}/{bucket}/{ns}/{name}"
        if self._get(path) is not None:
            raise S3TablesError(409, "TableAlreadyExists",
                                f"table {name} already exists")
        now = _iso(time.time())
        token = secrets.token_hex(16)
        internal = {"name": name, "namespace": ns,
                    "format": "ICEBERG", "createdAt": now,
                    "modifiedAt": now,
                    "ownerAccountId": owner or self.account,
                    "versionToken": token, "metadataVersion": 1,
                    "metadataLocation": metadata_location,
                    "metadata": metadata}
        ext = {X_METADATA: json.dumps(internal)}
        if tags:
            ext[X_TAGS] = json.dumps(tags)
        self._mkdir(path, ext)
        # the Iceberg subtrees exist from birth so clients can write
        # metadata/v1.metadata.json immediately
        self._mkdir(path + "/metadata", {})
        self._mkdir(path + "/data", {})
        arn = table_arn(bucket, ns, name, self.region, self.account)
        resp = {"tableARN": arn, "versionToken": token}
        if metadata_location:
            resp["metadataLocation"] = metadata_location
        return resp

    def get_table(self, bucket_arn_: str = "", namespace=None,
                  name: str = "", table_arn_: str = "") -> dict:
        if table_arn_:
            bucket, ns, name = parse_table_arn(table_arn_)
        else:
            bucket = parse_bucket_arn(bucket_arn_)
            ns = namespace[0] if namespace else ""
        meta = self._meta(self._table_entry(bucket, ns, name),
                          X_METADATA)
        return {"name": name,
                "tableARN": table_arn(bucket, ns, name, self.region,
                                      self.account),
                "namespace": [ns], "format": "ICEBERG",
                "createdAt": meta.get("createdAt", ""),
                "modifiedAt": meta.get("modifiedAt", ""),
                "ownerAccountId": meta.get("ownerAccountId",
                                           self.account),
                "metadataLocation": meta.get("metadataLocation", ""),
                "versionToken": meta.get("versionToken", ""),
                "metadataVersion": meta.get("metadataVersion", 1),
                **({"metadata": meta["metadata"]}
                   if meta.get("metadata") else {})}

    def list_tables(self, bucket_arn_: str, namespace=None,
                    prefix: str = "", continuation: str = "",
                    max_tables: int = 0) -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        self._bucket_entry(bucket)
        if namespace:
            spaces = [namespace[0]]
        else:
            # enumerate EVERY namespace dir (paginated — the capped
            # list_namespaces API call would silently drop tables of
            # namespaces past its page size)
            spaces = [e.name for e in
                      self._list_all(f"{BUCKETS_ROOT}/{bucket}")
                      if X_NAMESPACE in e.extended]
        # the continuation token is namespace-QUALIFIED ("ns/table"):
        # a bare table name applied as start_file to every namespace
        # would silently skip any later namespace's tables that sort
        # below it
        cont_ns, _, cont_name = continuation.partition("/")
        out, token = [], ""
        limit = max_tables or 100
        for ns in spaces:
            if continuation and ns < cont_ns:
                continue
            start = cont_name if continuation and ns == cont_ns \
                else ""
            if token:
                break           # page full: no more listing calls
            for e in self._list_all(f"{BUCKETS_ROOT}/{bucket}/{ns}",
                                    start, prefix):
                if X_METADATA not in e.extended:
                    continue
                if len(out) >= limit:
                    token = f"{ns}/{out[-1]['name']}" \
                        if out and out[-1]["namespace"] == [ns] \
                        else f"{ns}/"
                    break
                meta = self._meta(e, X_METADATA)
                out.append({
                    "name": e.name,
                    "tableARN": table_arn(bucket, ns, e.name,
                                          self.region, self.account),
                    "namespace": [ns],
                    "createdAt": meta.get("createdAt", ""),
                    "modifiedAt": meta.get("modifiedAt", ""),
                    "metadataLocation":
                        meta.get("metadataLocation", "")})
        resp = {"tables": out}
        if token:
            resp["continuationToken"] = token
        return resp

    def update_table(self, bucket_arn_: str, namespace: list,
                     name: str, version_token: str = "",
                     metadata: "dict | None" = None,
                     metadata_location: str = "") -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        ns = namespace[0] if namespace else ""
        entry = self._table_entry(bucket, ns, name)
        meta = self._meta(entry, X_METADATA)
        if version_token and \
                version_token != meta.get("versionToken"):
            raise S3TablesError(409, "ConflictException",
                                "version token mismatch")
        new_token = secrets.token_hex(16)
        meta["versionToken"] = new_token
        meta["modifiedAt"] = _iso(time.time())
        meta["metadataVersion"] = meta.get("metadataVersion", 1) + 1
        if metadata is not None:
            meta["metadata"] = metadata
        if metadata_location:
            meta["metadataLocation"] = metadata_location
        self._patch(entry, **{X_METADATA: json.dumps(meta)})
        resp = {"tableARN": table_arn(bucket, ns, name, self.region,
                                      self.account),
                "versionToken": new_token}
        if meta.get("metadataLocation"):
            resp["metadataLocation"] = meta["metadataLocation"]
        return resp

    def delete_table(self, bucket_arn_: str, namespace: list,
                     name: str, version_token: str = "") -> dict:
        bucket = parse_bucket_arn(bucket_arn_)
        ns = namespace[0] if namespace else ""
        entry = self._table_entry(bucket, ns, name)
        meta = self._meta(entry, X_METADATA)
        if version_token and \
                version_token != meta.get("versionToken"):
            raise S3TablesError(409, "ConflictException",
                                "version token mismatch")
        self.filer.delete_entry(
            f"{BUCKETS_ROOT}/{bucket}/{ns}/{name}", recursive=True)
        return {}

    # -- resource policies ------------------------------------------------

    def _policy_target(self, bucket_arn_: str = "", namespace=None,
                       name: str = ""):
        if name:
            bucket = parse_bucket_arn(bucket_arn_)
            return self._table_entry(
                bucket, namespace[0] if namespace else "", name)
        return self._bucket_entry(parse_bucket_arn(bucket_arn_))

    def put_policy(self, policy: str, **target) -> dict:
        try:
            json.loads(policy)
        except ValueError:
            raise _bad("resourcePolicy is not valid JSON")
        entry = self._policy_target(**target)
        self._patch(entry, **{X_POLICY: policy})
        return {}

    def get_policy(self, **target) -> dict:
        entry = self._policy_target(**target)
        raw = entry.extended.get(X_POLICY, "")
        if isinstance(raw, bytes):
            raw = raw.decode()
        if not raw:
            raise _not_found("policy", "resource policy")
        return {"resourcePolicy": raw}

    def delete_policy(self, **target) -> dict:
        entry = self._policy_target(**target)
        self._patch(entry, **{X_POLICY: None})
        return {}

    # -- tags -------------------------------------------------------------

    def _arn_entry(self, arn: str):
        tail = arn.split(":", 5)[-1] if arn.startswith("arn:") else ""
        if "/table/" in tail:
            bucket, ns, table = parse_table_arn(arn)
            return self._table_entry(bucket, ns, table)
        return self._bucket_entry(parse_bucket_arn(arn))

    def tag_resource(self, arn: str, tags: dict) -> dict:
        entry = self._arn_entry(arn)
        merged = self._meta(entry, X_TAGS)
        merged.update(tags or {})
        _validate_tags(merged)
        self._patch(entry, **{X_TAGS: json.dumps(merged)})
        return {}

    def list_tags(self, arn: str) -> dict:
        return {"tags": self._meta(self._arn_entry(arn), X_TAGS)}

    def untag_resource(self, arn: str, keys: list) -> dict:
        entry = self._arn_entry(arn)
        tags = self._meta(entry, X_TAGS)
        for k in keys or []:
            tags.pop(k, None)
        self._patch(entry, **{X_TAGS: json.dumps(tags) if tags
                              else None})
        return {}


# -- HTTP dispatch ---------------------------------------------------------

def handle_request(store: S3TablesStore, operation: str,
                   body: dict) -> dict:
    """X-Amz-Target operation name -> store call (handler.go:106's
    switch).  Raises S3TablesError for protocol errors."""
    ops = {
        "CreateTableBucket": lambda: store.create_table_bucket(
            body.get("name", ""), tags=body.get("tags")),
        "GetTableBucket": lambda: store.get_table_bucket(
            body.get("tableBucketARN", "")),
        "ListTableBuckets": lambda: store.list_table_buckets(
            body.get("prefix", ""), body.get("continuationToken", ""),
            int(body.get("maxBuckets") or 0)),
        "DeleteTableBucket": lambda: store.delete_table_bucket(
            body.get("tableBucketARN", "")),
        "PutTableBucketPolicy": lambda: store.put_policy(
            body.get("resourcePolicy", ""),
            bucket_arn_=body.get("tableBucketARN", "")),
        "GetTableBucketPolicy": lambda: store.get_policy(
            bucket_arn_=body.get("tableBucketARN", "")),
        "DeleteTableBucketPolicy": lambda: store.delete_policy(
            bucket_arn_=body.get("tableBucketARN", "")),
        "CreateNamespace": lambda: store.create_namespace(
            body.get("tableBucketARN", ""),
            body.get("namespace") or [],
            properties=body.get("properties")),
        "GetNamespace": lambda: store.get_namespace(
            body.get("tableBucketARN", ""),
            body.get("namespace") or []),
        "ListNamespaces": lambda: store.list_namespaces(
            body.get("tableBucketARN", ""), body.get("prefix", ""),
            body.get("continuationToken", ""),
            int(body.get("maxNamespaces") or 0)),
        "DeleteNamespace": lambda: store.delete_namespace(
            body.get("tableBucketARN", ""),
            body.get("namespace") or []),
        "CreateTable": lambda: store.create_table(
            body.get("tableBucketARN", ""),
            body.get("namespace") or [], body.get("name", ""),
            body.get("format", "ICEBERG"), body.get("metadata"),
            body.get("metadataLocation", ""),
            tags=body.get("tags")),
        "GetTable": lambda: store.get_table(
            body.get("tableBucketARN", ""), body.get("namespace"),
            body.get("name", ""), body.get("tableARN", "")),
        "ListTables": lambda: store.list_tables(
            body.get("tableBucketARN", ""), body.get("namespace"),
            body.get("prefix", ""),
            body.get("continuationToken", ""),
            int(body.get("maxTables") or 0)),
        "UpdateTable": lambda: store.update_table(
            body.get("tableBucketARN", ""),
            body.get("namespace") or [], body.get("name", ""),
            body.get("versionToken", ""), body.get("metadata"),
            body.get("metadataLocation", "")),
        "DeleteTable": lambda: store.delete_table(
            body.get("tableBucketARN", ""),
            body.get("namespace") or [], body.get("name", ""),
            body.get("versionToken", "")),
        "PutTablePolicy": lambda: store.put_policy(
            body.get("resourcePolicy", ""),
            bucket_arn_=body.get("tableBucketARN", ""),
            namespace=body.get("namespace"),
            name=body.get("name", "")),
        "GetTablePolicy": lambda: store.get_policy(
            bucket_arn_=body.get("tableBucketARN", ""),
            namespace=body.get("namespace"),
            name=body.get("name", "")),
        "DeleteTablePolicy": lambda: store.delete_policy(
            bucket_arn_=body.get("tableBucketARN", ""),
            namespace=body.get("namespace"),
            name=body.get("name", "")),
        "TagResource": lambda: store.tag_resource(
            body.get("resourceArn", ""), body.get("tags") or {}),
        "ListTagsForResource": lambda: store.list_tags(
            body.get("resourceArn", "")),
        "UntagResource": lambda: store.untag_resource(
            body.get("resourceArn", ""), body.get("tagKeys") or []),
    }
    fn = ops.get(operation)
    if fn is None:
        raise _bad(f"unknown S3Tables operation {operation!r}")
    return fn()
