"""Bucket lifecycle configuration (reference:
s3api_bucket_handlers.go lifecycle handlers + the shell enforcement
pass s3.clean.uploads / filer TTL mapping).

Supported rule shape (the expiration core of AWS's schema):

    <LifecycleConfiguration>
      <Rule>
        <ID>...</ID>
        <Filter><Prefix>logs/</Prefix></Filter>   (or bare <Prefix>)
        <Status>Enabled</Status>
        <Expiration><Days>30</Days></Expiration>  (or <Date>)
        <AbortIncompleteMultipartUpload>
          <DaysAfterInitiation>7</DaysAfterInitiation>
        </AbortIncompleteMultipartUpload>
      </Rule>
    </LifecycleConfiguration>
"""

from __future__ import annotations

import time
import xml.etree.ElementTree as ET
from dataclasses import dataclass
from datetime import datetime, timezone


class LifecycleError(ValueError):
    pass


@dataclass
class Rule:
    rule_id: str
    prefix: str
    enabled: bool
    expire_days: "int | None" = None
    expire_date: "float | None" = None
    abort_mpu_days: "int | None" = None

    def expires_before(self, now: float) -> "float | None":
        """Cutoff mtime: objects older than this are expired."""
        if self.expire_days is not None:
            return now - self.expire_days * 86400
        if self.expire_date is not None and now >= self.expire_date:
            return now
        return None


def _tag(el) -> str:
    return el.tag.rsplit("}", 1)[-1]


def _child_text(parent, name: str) -> "str | None":
    for el in parent:
        if _tag(el) == name and el.text and el.text.strip():
            return el.text.strip()
    return None


def parse_lifecycle(doc: bytes) -> "list[Rule]":
    """Element-SCOPED parsing: a <Days> inside <Transition> must not
    read as Expiration, and unsupported actions/filters are REJECTED
    rather than silently dropped (misreading either turns a
    non-destructive config into data deletion)."""
    try:
        root = ET.fromstring(doc)
    except ET.ParseError as e:
        raise LifecycleError(f"undecodable lifecycle XML: {e}")
    rules = []
    for rule_el in root:
        if not _tag(rule_el).endswith("Rule"):
            continue
        rule_id = _child_text(rule_el, "ID") or ""
        status = _child_text(rule_el, "Status") or ""
        if status not in ("Enabled", "Disabled"):
            raise LifecycleError(f"Rule needs Status "
                                 f"Enabled|Disabled, got {status!r}")
        prefix = _child_text(rule_el, "Prefix") or ""
        expire_days = expire_date = abort_days = None
        for el in rule_el:
            tag = _tag(el)
            if tag in ("ID", "Status", "Prefix"):
                continue
            if tag == "Filter":
                for f in el:
                    if _tag(f) == "Prefix":
                        prefix = (f.text or "").strip()
                    else:
                        raise LifecycleError(
                            f"unsupported Filter element "
                            f"{_tag(f)!r} (only Prefix)")
                continue
            if tag == "Expiration":
                days = _child_text(el, "Days")
                date = _child_text(el, "Date")
                try:
                    if days is not None:
                        expire_days = int(days)
                        if expire_days <= 0:
                            raise LifecycleError(
                                "Expiration Days must be > 0")
                    if date is not None:
                        expire_date = datetime.fromisoformat(
                            date.replace("Z", "+00:00")).astimezone(
                            timezone.utc).timestamp()
                except ValueError as e:
                    raise LifecycleError(str(e))
                continue
            if tag == "AbortIncompleteMultipartUpload":
                raw = _child_text(el, "DaysAfterInitiation")
                try:
                    abort_days = int(raw) if raw is not None else None
                except ValueError as e:
                    raise LifecycleError(str(e))
                if abort_days is None or abort_days <= 0:
                    raise LifecycleError(
                        "DaysAfterInitiation must be > 0")
                continue
            # Transition / NoncurrentVersionExpiration / unknown:
            # refusing beats misinterpreting a non-destructive action
            raise LifecycleError(f"unsupported Rule element {tag!r}")
        if expire_days is None and expire_date is None and \
                abort_days is None:
            raise LifecycleError(
                "Rule needs an Expiration or "
                "AbortIncompleteMultipartUpload action")
        rules.append(Rule(rule_id, prefix, status == "Enabled",
                          expire_days, expire_date, abort_days))
    if not rules:
        raise LifecycleError("no Rule elements")
    return rules


def apply_lifecycle(filer, bucket_path: str, rules: "list[Rule]",
                    now: "float | None" = None) -> "tuple[int, int]":
    """One enforcement pass over a bucket: delete expired objects and
    abort stale multipart uploads.  Returns (objects_deleted,
    uploads_aborted).  Mirrors the reference's shell-driven
    enforcement (lifecycle is applied by a maintenance pass, not
    inline on reads)."""
    now = now or time.time()
    deleted = aborted = 0
    for rule in rules:
        if not rule.enabled:
            continue
        cutoff = rule.expires_before(now)
        if cutoff is not None:
            deleted += _expire_tree(filer, bucket_path, bucket_path,
                                    rule.prefix, cutoff)
        if rule.abort_mpu_days is not None:
            updir = f"{bucket_path}/.uploads"
            mpu_cutoff = now - rule.abort_mpu_days * 86400
            for e in filer.list_directory(updir, limit=10000):
                # the marker records the upload's target key: the
                # rule's prefix filter applies to it (AWS semantics —
                # aborting out-of-scope uploads loses parts)
                target = e.extended.get("key", "")
                if rule.prefix and not target.startswith(rule.prefix):
                    continue
                if e.is_directory and \
                        e.attributes.crtime < mpu_cutoff:
                    filer.delete_entry(e.full_path, recursive=True)
                    aborted += 1
    return deleted, aborted


def _expire_tree(filer, bucket_path: str, directory: str,
                 prefix: str, cutoff: float) -> int:
    deleted = 0
    last = ""
    while True:
        batch = filer.list_directory(directory, start_file=last,
                                     limit=500)
        if not batch:
            break
        for e in batch:
            rel = e.full_path[len(bucket_path):].lstrip("/")
            if e.is_directory:
                if e.name.startswith(".") or \
                        e.name.endswith(".versions"):
                    # .uploads scratch + "<key>.versions" archives:
                    # Expiration must never hard-delete version
                    # history (that is NoncurrentVersionExpiration,
                    # unsupported -> untouched)
                    continue
                # descend only if the prefix could match inside
                if not prefix or prefix.startswith(rel + "/") or \
                        rel.startswith(prefix):
                    deleted += _expire_tree(filer, bucket_path,
                                            e.full_path, prefix,
                                            cutoff)
                continue
            if prefix and not rel.startswith(prefix):
                continue
            if e.attributes.mtime < cutoff:
                filer.delete_entry(e.full_path)
                deleted += 1
        if len(batch) < 500:
            break
        last = batch[-1].name
    return deleted
