"""AWS IAM-compatible REST API + STS endpoint (reference:
weed/iamapi/iamapi_server.go + iamapi_management_handlers.go, and the
AssumeRole surface of weed/iam/sts/).

Form-encoded `Action=...` POSTs, XML responses, mutating the shared
IdentityStore the S3 gateway authorizes against.  Management actions
require a SigV4 signature from an admin identity; AssumeRole accepts
any enabled identity (the role's trust list decides).

Policy translation mirrors iamapi GetActions: IAM policy documents are
compressed to the coarse identity actions ("Read:bucket/prefix", ...)
that auth_credentials.go CanDo evaluates.
"""

from __future__ import annotations

import json
import secrets
import string
import urllib.parse
import uuid
import xml.etree.ElementTree as ET

from ..s3.auth import SigV4Verifier
from ..server.httpd import HttpServer, Request
from .identity import (ACTION_ADMIN, ACTION_LIST, ACTION_READ,
                       ACTION_TAGGING, ACTION_WRITE, Credential,
                       Identity, IdentityStore)
from .sts import StsError, StsService


class IamError(Exception):
    def __init__(self, status: int, code: str, message: str):
        super().__init__(message)
        self.status = status
        self.code = code


def policy_to_actions(doc: str) -> list[str]:
    """iamapi_management_handlers.go GetActions: statements of an IAM
    policy document -> coarse identity actions.  Unknown actions raise
    (the reference rejects invalid documents at Put time)."""
    try:
        policy = json.loads(doc)
        statements = policy["Statement"]
    except (ValueError, KeyError, TypeError):
        raise IamError(400, "MalformedPolicyDocument",
                       "undecodable policy document")
    if isinstance(statements, dict):
        statements = [statements]
    out: list[str] = []
    for st in statements:
        if st.get("Effect") != "Allow":
            raise IamError(400, "MalformedPolicyDocument",
                           "only Effect=Allow is supported here")
        actions = st.get("Action", [])
        resources = st.get("Resource", [])
        if isinstance(actions, str):
            actions = [actions]
        if isinstance(resources, str):
            resources = [resources]
        for res in resources:
            prefix = "arn:aws:s3:::"
            if not res.startswith(prefix):
                raise IamError(400, "MalformedPolicyDocument",
                               f"unsupported resource {res}")
            scope = res[len(prefix):].rstrip("*").rstrip("/")
            for act in actions:
                coarse = _statement_action(act)
                if scope in ("", "*"):
                    out.append(coarse)
                else:
                    out.append(f"{coarse}:{scope}")
    return sorted(set(out))


def _statement_action(act: str) -> str:
    a = act.removeprefix("s3:")
    if a == "*":
        return ACTION_ADMIN
    if "Tagging" in a:
        return ACTION_TAGGING
    if a.startswith("List"):
        return ACTION_LIST
    if a.startswith(("Get", "Head")) or a == "Read":
        return ACTION_READ
    if a.startswith(("Put", "Delete", "Abort", "Restore", "Create")):
        return ACTION_WRITE
    raise IamError(400, "MalformedPolicyDocument",
                   f"unsupported action {act}")


def _xml(root: ET.Element) -> bytes:
    return b'<?xml version="1.0" encoding="UTF-8"?>' + \
        ET.tostring(root)


def _response(action: str, fill) -> "tuple[int, tuple]":
    root = ET.Element(
        f"{action}Response",
        xmlns="https://iam.amazonaws.com/doc/2010-05-08/")
    result = ET.SubElement(root, f"{action}Result")
    fill(result)
    meta = ET.SubElement(root, "ResponseMetadata")
    ET.SubElement(meta, "RequestId").text = str(uuid.uuid4())
    return 200, (_xml(root), "application/xml")


def _error_xml(status: int, code: str, message: str):
    root = ET.Element("ErrorResponse")
    err = ET.SubElement(root, "Error")
    ET.SubElement(err, "Code").text = code
    ET.SubElement(err, "Message").text = message
    return status, (_xml(root), "application/xml")


def _user_xml(parent: ET.Element, ident: Identity) -> None:
    u = ET.SubElement(parent, "User")
    ET.SubElement(u, "UserName").text = ident.name
    ET.SubElement(u, "UserId").text = ident.name
    ET.SubElement(u, "Arn").text = ident.principal_arn


class IamApiServer:
    """One HTTP server exposing the IAM management API and AssumeRole,
    sharing the identity store with the S3 gateway."""

    def __init__(self, store: IdentityStore,
                 sts: StsService | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store
        self.sts = sts
        self.verifier = SigV4Verifier(store.secrets_view(), sts=sts)
        self.http = HttpServer(host, port)
        self.http.route("POST", "/", self._handle)

    def start(self):
        self.http.start()
        # the reference exposes this plane as gRPC too (iam.proto
        # SeaweedIdentityAccessManagement, filer-hosted there); we
        # host it beside the REST API on the IAM server
        self.grpc_server, self.grpc_port = None, 0
        try:
            from ..pb.iam_service import start_iam_grpc
            self.grpc_server, self.grpc_port = start_iam_grpc(
                self.store, host=self.http.host)
        except ImportError:     # grpcio absent: HTTP-only mode
            pass
        except Exception as e:  # pragma: no cover — a real defect
            import sys
            print(f"iam {self.url}: gRPC plane failed to start: "
                  f"{e!r}", file=sys.stderr)
        return self

    def stop(self):
        if getattr(self, "grpc_server", None) is not None:
            self.grpc_server.stop(grace=0.5).wait()
            self.grpc_server = None
        self.http.stop()

    @property
    def url(self) -> str:
        return self.http.url

    # -- request plumbing --------------------------------------------------

    def _caller(self, req: Request) -> Identity | None:
        ok, who, ctx = self.verifier.verify(
            "POST", req.path, req.query,
            {k.lower(): v for k, v in req.headers.items()}, req.body)
        if not ok:
            return None
        if ctx is not None and ctx.sts_identity is not None:
            return ctx.sts_identity
        return self.store.by_access_key(who)

    def _handle(self, req: Request):
        form = {k: v[0] for k, v in
                urllib.parse.parse_qs(req.body.decode()).items()}
        action = form.get("Action", "")
        if action == "AssumeRoleWithWebIdentity":
            # the web-identity TOKEN is the credential — no SigV4
            # (AWS STS semantics; sts_service.go:431)
            return self._assume_role_with_web_identity(form)
        caller = self._caller(req)
        if caller is None:
            return _error_xml(403, "AccessDenied",
                              "request must be signed by a known "
                              "identity")
        try:
            if action == "AssumeRole":
                return self._assume_role(caller, form)
            if not caller.is_admin:
                return _error_xml(403, "AccessDenied",
                                  "management actions require an "
                                  "admin identity")
            fn = getattr(self, f"_do_{action}", None)
            if fn is None:
                return _error_xml(400, "InvalidAction", action)
            return fn(form)
        except IamError as e:
            return _error_xml(e.status, e.code, str(e))

    def _need_user(self, form: dict) -> Identity:
        name = form.get("UserName", "")
        ident = self.store.get(name)
        if ident is None:
            raise IamError(404, "NoSuchEntity", f"user {name}")
        return ident

    # -- user management ---------------------------------------------------

    def _do_CreateUser(self, form: dict):
        name = form.get("UserName", "")
        if not name:
            raise IamError(400, "InvalidInput", "UserName required")
        if self.store.get(name) is not None:
            raise IamError(409, "EntityAlreadyExists", name)
        ident = Identity(name, actions=[])
        self.store.put(ident)
        return _response("CreateUser",
                         lambda r: _user_xml(r, ident))

    def _do_GetUser(self, form: dict):
        ident = self._need_user(form)
        return _response("GetUser", lambda r: _user_xml(r, ident))

    def _do_UpdateUser(self, form: dict):
        ident = self._need_user(form)
        new_name = form.get("NewUserName", "")
        if new_name:
            if new_name != ident.name and \
                    self.store.get(new_name) is not None:
                raise IamError(409, "EntityAlreadyExists", new_name)
            self.store.delete(ident.name)
            ident.name = new_name
            ident.principal_arn = f"arn:aws:iam:::user/{new_name}"
            self.store.put(ident)
        return _response("UpdateUser", lambda r: _user_xml(r, ident))

    def _do_DeleteUser(self, form: dict):
        ident = self._need_user(form)
        self.store.delete(ident.name)
        return _response("DeleteUser", lambda r: None)

    def _do_ListUsers(self, form: dict):
        def fill(r):
            users = ET.SubElement(r, "Users")
            for ident in self.store:
                _user_xml(users, ident)
        return _response("ListUsers", fill)

    # -- access keys -------------------------------------------------------

    def _do_CreateAccessKey(self, form: dict):
        ident = self._need_user(form)
        alphabet = string.ascii_uppercase + string.digits
        access = "AKID" + "".join(secrets.choice(alphabet)
                                  for _ in range(16))
        secret = secrets.token_urlsafe(30)
        ident.credentials.append(Credential(access, secret))
        self.store.put(ident)

        def fill(r):
            k = ET.SubElement(r, "AccessKey")
            ET.SubElement(k, "UserName").text = ident.name
            ET.SubElement(k, "AccessKeyId").text = access
            ET.SubElement(k, "SecretAccessKey").text = secret
            ET.SubElement(k, "Status").text = "Active"
        return _response("CreateAccessKey", fill)

    def _do_DeleteAccessKey(self, form: dict):
        ident = self._need_user(form)
        key_id = form.get("AccessKeyId", "")
        before = len(ident.credentials)
        ident.credentials = [c for c in ident.credentials
                             if c.access_key != key_id]
        if len(ident.credentials) == before:
            raise IamError(404, "NoSuchEntity", key_id)
        self.store.put(ident)
        return _response("DeleteAccessKey", lambda r: None)

    def _do_ListAccessKeys(self, form: dict):
        ident = self._need_user(form)

        def fill(r):
            members = ET.SubElement(r, "AccessKeyMetadata")
            for c in ident.credentials:
                m = ET.SubElement(members, "member")
                ET.SubElement(m, "UserName").text = ident.name
                ET.SubElement(m, "AccessKeyId").text = c.access_key
                ET.SubElement(m, "Status").text = c.status
        return _response("ListAccessKeys", fill)

    # -- inline policies ---------------------------------------------------

    def _recompute_actions(self, ident: Identity) -> None:
        """static provisioned actions ∪ all inline policies
        (computeAggregatedActionsForUser) — never strips the static
        set, so attaching a policy to an admin can't drop Admin."""
        actions: set[str] = set(ident.static_actions)
        for doc in ident.policies.values():
            actions.update(policy_to_actions(doc))
        ident.actions = sorted(actions)

    def _do_PutUserPolicy(self, form: dict):
        ident = self._need_user(form)
        name = form.get("PolicyName", "")
        doc = form.get("PolicyDocument", "")
        policy_to_actions(doc)          # validate before storing
        ident.policies[name] = doc
        self._recompute_actions(ident)
        self.store.put(ident)
        return _response("PutUserPolicy", lambda r: None)

    def _do_GetUserPolicy(self, form: dict):
        ident = self._need_user(form)
        name = form.get("PolicyName", "")
        if name not in ident.policies:
            raise IamError(404, "NoSuchEntity", name)

        def fill(r):
            ET.SubElement(r, "UserName").text = ident.name
            ET.SubElement(r, "PolicyName").text = name
            ET.SubElement(r, "PolicyDocument").text = \
                urllib.parse.quote(ident.policies[name])
        return _response("GetUserPolicy", fill)

    def _do_DeleteUserPolicy(self, form: dict):
        ident = self._need_user(form)
        name = form.get("PolicyName", "")
        if ident.policies.pop(name, None) is None:
            raise IamError(404, "NoSuchEntity", name)
        self._recompute_actions(ident)
        self.store.put(ident)
        return _response("DeleteUserPolicy", lambda r: None)

    def _do_ListUserPolicies(self, form: dict):
        ident = self._need_user(form)

        def fill(r):
            names = ET.SubElement(r, "PolicyNames")
            for n in ident.policies:
                ET.SubElement(names, "member").text = n
        return _response("ListUserPolicies", fill)

    # -- STS ---------------------------------------------------------------

    @staticmethod
    def _parse_assume_form(form: dict):
        """(role, session, duration) shared by both AssumeRole
        flavors; raises IamError on bad input."""
        role = form.get("RoleArn", "") or form.get("RoleName", "")
        role = role.rsplit("/", 1)[-1]
        session = form.get("RoleSessionName", "session")
        try:
            duration = int(form.get("DurationSeconds", "3600"))
        except ValueError:
            raise IamError(400, "InvalidInput",
                           "DurationSeconds must be an integer")
        return role, session, duration

    @staticmethod
    def _credentials_response(action: str, creds: dict):
        import time as _time

        def fill(r):
            c = ET.SubElement(r, "Credentials")
            for tag in ("AccessKeyId", "SecretAccessKey",
                        "SessionToken"):
                ET.SubElement(c, tag).text = str(creds[tag])
            # AWS wire format: ISO 8601, not a raw epoch
            ET.SubElement(c, "Expiration").text = _time.strftime(
                "%Y-%m-%dT%H:%M:%SZ",
                _time.gmtime(int(creds["Expiration"])))
            ET.SubElement(r, "AssumedRoleUser")
        return _response(action, fill)

    def _assume_role_with_web_identity(self, form: dict):
        if self.sts is None:
            return _error_xml(400, "InvalidAction",
                              "no STS service configured")
        try:
            role, session, duration = self._parse_assume_form(form)
            creds = self.sts.assume_role_with_web_identity(
                form.get("WebIdentityToken", ""), role, session,
                duration)
        except IamError as e:
            return _error_xml(e.status, e.code, str(e))
        except StsError as e:
            return _error_xml(403, "AccessDenied", str(e))
        return self._credentials_response("AssumeRoleWithWebIdentity",
                                          creds)

    def _assume_role(self, caller: Identity, form: dict):
        if self.sts is None:
            return _error_xml(400, "InvalidAction",
                              "no STS service configured")
        try:
            role, session, duration = self._parse_assume_form(form)
            creds = self.sts.assume_role(caller, role, session,
                                         duration)
        except IamError as e:
            return _error_xml(e.status, e.code, str(e))
        except StsError as e:
            return _error_xml(403, "AccessDenied", str(e))
        return self._credentials_response("AssumeRole", creds)
