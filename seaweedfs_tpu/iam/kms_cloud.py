"""Cloud KMS providers: GCP KMS, Azure Key Vault, OpenBao/Vault
transit (reference: weed/kms/gcp/, kms/azure/, kms/openbao/).

All three expose the same KMSProvider surface as LocalKms/AwsKms
(get_key_id / describe_key / generate_data_key / decrypt) over each
service's REST wire protocol — no SDKs.  Data-key envelopes follow
each reference provider's shape:

- GCP has no GenerateDataKey: the data key is minted locally and
  sealed through cryptoKeys/...:encrypt (gcp_kms.go does the same).
- Azure Key Vault wraps the locally-minted key via keys/.../wrapkey.
- OpenBao transit mints server-side via v1/transit/datakey/plaintext.

Ciphertext blobs are self-describing JSON naming the provider, so
Decrypt needs no out-of-band key reference.  Auth is a bearer token
(static or file-sourced) — the air-gapped test environment drives the
wire protocols against the Fake*Server twins below.
"""

from __future__ import annotations

import base64
import json
import secrets

from ..server.httpd import HttpServer, http_bytes
from .kms import KmsError


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _b64url(b: bytes) -> str:
    return base64.urlsafe_b64encode(b).rstrip(b"=").decode()


def _unb64url(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


def _post_json(url: str, payload: dict, headers: dict) -> dict:
    try:
        st, resp, _ = http_bytes(
            "POST", url, json.dumps(payload).encode(),
            dict(headers, **{"Content-Type": "application/json"}))
    except OSError as e:
        raise KmsError(f"kms endpoint unreachable: {e}")
    try:
        doc = json.loads(resp) if resp else {}
    except ValueError:
        raise KmsError(f"kms: undecodable response ({st})")
    if st >= 300:
        msg = doc.get("error", doc)
        raise KmsError(f"kms: {st} {msg}")
    return doc


class GcpKms:
    """gcp_kms.go: envelope through cryptoKeys encrypt/decrypt."""

    def __init__(self, endpoint: str, key_name: str, token: str = ""):
        self.endpoint = endpoint.rstrip("/")
        self.key_name = key_name.strip("/")
        self.token = token

    def _hdrs(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} \
            if self.token else {}

    def get_key_id(self, identifier: str) -> str:
        return identifier or self.key_name

    def describe_key(self, identifier: str) -> dict:
        return {"KeyId": self.get_key_id(identifier), "Enabled": True}

    def generate_data_key(self, identifier: str,
                          context: dict | None = None) -> dict:
        key = self.get_key_id(identifier)
        plaintext = secrets.token_bytes(32)
        aad = json.dumps(context or {}, sort_keys=True).encode()
        d = _post_json(
            f"{self.endpoint}/v1/{key}:encrypt",
            {"plaintext": _b64(plaintext),
             "additionalAuthenticatedData": _b64(aad)},
            self._hdrs())
        blob = json.dumps({"provider": "gcp", "key": key,
                           "ciphertext": d["ciphertext"]}).encode()
        return {"KeyId": key, "Plaintext": plaintext,
                "CiphertextBlob": _b64(blob)}

    def decrypt(self, ciphertext_blob: str,
                context: dict | None = None) -> dict:
        try:
            blob = json.loads(base64.b64decode(ciphertext_blob))
            key, ct = blob["key"], blob["ciphertext"]
        except (ValueError, KeyError, TypeError):
            raise KmsError("InvalidCiphertextException: undecodable "
                           "blob")
        aad = json.dumps(context or {}, sort_keys=True).encode()
        d = _post_json(
            f"{self.endpoint}/v1/{key}:decrypt",
            {"ciphertext": ct,
             "additionalAuthenticatedData": _b64(aad)},
            self._hdrs())
        return {"KeyId": key,
                "Plaintext": base64.b64decode(d["plaintext"])}


class AzureKms:
    """azure_kms.go: envelope through Key Vault wrapkey/unwrapkey."""

    API = "api-version=7.4"

    def __init__(self, vault_url: str, key_name: str,
                 token: str = "", key_version: str = ""):
        self.vault = vault_url.rstrip("/")
        self.key_name = key_name
        self.key_version = key_version
        self.token = token

    def _hdrs(self) -> dict:
        return {"Authorization": f"Bearer {self.token}"} \
            if self.token else {}

    def _key_path(self, name: str) -> str:
        ver = f"/{self.key_version}" if self.key_version else "/"
        return f"/keys/{name}{ver}".rstrip("/")

    def get_key_id(self, identifier: str) -> str:
        return identifier or self.key_name

    def describe_key(self, identifier: str) -> dict:
        return {"KeyId": self.get_key_id(identifier), "Enabled": True}

    def generate_data_key(self, identifier: str,
                          context: dict | None = None) -> dict:
        name = self.get_key_id(identifier)
        plaintext = secrets.token_bytes(32)
        d = _post_json(
            f"{self.vault}{self._key_path(name)}/wrapkey?{self.API}",
            {"alg": "RSA-OAEP-256", "value": _b64url(plaintext)},
            self._hdrs())
        blob = json.dumps({"provider": "azure", "key": name,
                           "wrapped": d["value"],
                           "kid": d.get("kid", "")}).encode()
        return {"KeyId": name, "Plaintext": plaintext,
                "CiphertextBlob": _b64(blob)}

    def decrypt(self, ciphertext_blob: str,
                context: dict | None = None) -> dict:
        try:
            blob = json.loads(base64.b64decode(ciphertext_blob))
            name, wrapped = blob["key"], blob["wrapped"]
        except (ValueError, KeyError, TypeError):
            raise KmsError("InvalidCiphertextException: undecodable "
                           "blob")
        d = _post_json(
            f"{self.vault}{self._key_path(name)}/unwrapkey?{self.API}",
            {"alg": "RSA-OAEP-256", "value": wrapped}, self._hdrs())
        return {"KeyId": name, "Plaintext": _unb64url(d["value"])}


class OpenBaoKms:
    """openbao_kms.go: transit engine datakey/decrypt."""

    def __init__(self, addr: str, key_name: str, token: str = ""):
        self.addr = addr.rstrip("/")
        self.key_name = key_name
        self.token = token

    def _hdrs(self) -> dict:
        return {"X-Vault-Token": self.token} if self.token else {}

    def get_key_id(self, identifier: str) -> str:
        return identifier or self.key_name

    def describe_key(self, identifier: str) -> dict:
        return {"KeyId": self.get_key_id(identifier), "Enabled": True}

    def generate_data_key(self, identifier: str,
                          context: dict | None = None) -> dict:
        name = self.get_key_id(identifier)
        body = {}
        if context:
            body["context"] = _b64(json.dumps(
                context, sort_keys=True).encode())
        d = _post_json(
            f"{self.addr}/v1/transit/datakey/plaintext/{name}",
            body, self._hdrs())["data"]
        blob = json.dumps({"provider": "openbao", "key": name,
                           "ciphertext": d["ciphertext"]}).encode()
        return {"KeyId": name,
                "Plaintext": base64.b64decode(d["plaintext"]),
                "CiphertextBlob": _b64(blob)}

    def decrypt(self, ciphertext_blob: str,
                context: dict | None = None) -> dict:
        try:
            blob = json.loads(base64.b64decode(ciphertext_blob))
            name, ct = blob["key"], blob["ciphertext"]
        except (ValueError, KeyError, TypeError):
            raise KmsError("InvalidCiphertextException: undecodable "
                           "blob")
        body = {"ciphertext": ct}
        if context:
            body["context"] = _b64(json.dumps(
                context, sort_keys=True).encode())
        d = _post_json(f"{self.addr}/v1/transit/decrypt/{name}",
                       body, self._hdrs())["data"]
        return {"KeyId": name,
                "Plaintext": base64.b64decode(d["plaintext"])}


# -- wire-faithful fakes (tests / air-gapped dev) -------------------------

class _FakeBase:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 token: str = "testtoken"):
        self.token = token
        self.http = HttpServer(host, port)
        self.http.fallback = self._route
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
        self._aesgcm = AESGCM(secrets.token_bytes(32))

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self) -> str:
        return f"http://{self.http.url}"

    def _seal(self, plaintext: bytes) -> str:
        nonce = secrets.token_bytes(12)
        return _b64(nonce + self._aesgcm.encrypt(nonce, plaintext,
                                                 b""))

    def _unseal(self, ct: str) -> bytes:
        raw = base64.b64decode(ct)
        return self._aesgcm.decrypt(raw[:12], raw[12:], b"")


class FakeGcpKmsServer(_FakeBase):
    def _route(self, req):
        if req.headers.get("Authorization") != f"Bearer {self.token}":
            return 401, {"error": "unauthenticated"}
        body = json.loads(req.body or b"{}")
        if req.path.endswith(":encrypt"):
            pt = base64.b64decode(body["plaintext"])
            return 200, {"ciphertext": self._seal(pt)}
        if req.path.endswith(":decrypt"):
            try:
                return 200, {"plaintext": _b64(
                    self._unseal(body["ciphertext"]))}
            except Exception:
                return 400, {"error": "decryption failed"}
        return 404, {"error": req.path}


class FakeAzureKeyVaultServer(_FakeBase):
    def _route(self, req):
        if req.headers.get("Authorization") != f"Bearer {self.token}":
            return 401, {"error": "unauthenticated"}
        body = json.loads(req.body or b"{}")
        if req.path.endswith("/wrapkey"):
            pt = _unb64url(body["value"])
            return 200, {"kid": req.path, "value": _b64url(
                self._seal(pt).encode())}
        if req.path.endswith("/unwrapkey"):
            try:
                sealed = _unb64url(body["value"]).decode()
                return 200, {"value": _b64url(self._unseal(sealed))}
            except Exception:
                return 400, {"error": "unwrap failed"}
        return 404, {"error": req.path}


class FakeOpenBaoServer(_FakeBase):
    def _route(self, req):
        if req.headers.get("X-Vault-Token") != self.token:
            return 403, {"error": "permission denied"}
        body = json.loads(req.body or b"{}")
        if "/transit/datakey/plaintext/" in req.path:
            pt = secrets.token_bytes(32)
            return 200, {"data": {
                "plaintext": _b64(pt),
                "ciphertext": "vault:v1:" + self._seal(pt)}}
        if "/transit/decrypt/" in req.path:
            ct = body.get("ciphertext", "")
            if not ct.startswith("vault:v1:"):
                return 400, {"error": "bad ciphertext"}
            try:
                return 200, {"data": {"plaintext": _b64(
                    self._unseal(ct[len("vault:v1:"):]))}}
            except Exception:
                return 400, {"error": "decryption failed"}
        return 404, {"error": req.path}
