"""IAM subsystem (reference: weed/iam/, weed/iamapi/, weed/kms/,
weed/credential/, plus the identity model of
weed/s3api/auth_credentials.go).

- identity:  Identity/Account/Credential model, coarse S3 actions,
             JSON identity store (the reference's s3.json /
             /etc/iam/identity.json config shape)
- sts:       stateless temporary credentials — session-token JWTs the
             S3 gateway verifies with no shared session state
             (iam/sts/sts_service.go design)
- iamapi:    AWS IAM-compatible REST API (Action=CreateUser... form
             posts, XML responses) mutating the identity store
             (iamapi/iamapi_management_handlers.go)
- kms:       local KMS provider + envelope encryption for SSE-KMS
             (kms/local/, kms/envelope.go)
"""

from .identity import (Account, Credential, Identity, IdentityStore,
                       coarse_action)
from .sts import StsService

__all__ = ["Account", "Credential", "Identity", "IdentityStore",
           "StsService", "coarse_action"]
