"""AWS KMS wire-protocol provider (reference: weed/kms/aws/
aws_kms.go): the same KMSProvider surface as LocalKms, but speaking
the real AWS KMS JSON protocol (X-Amz-Target: TrentService.*, SigV4
service "kms") to ANY compatible endpoint — a real region, LocalStack,
or the stub the tests run.

Gives deployments an external-KMS option without bundling an SDK:
the protocol is ~three POSTs."""

from __future__ import annotations

import base64
import json

from ..s3.auth import sign_request
from ..server.httpd import http_bytes
from .kms import KmsError


class AwsKms:
    def __init__(self, endpoint: str, access_key: str,
                 secret_key: str, region: str = "us-east-1"):
        self.endpoint = endpoint.removeprefix("http://")
        self.access_key = access_key
        self.secret_key = secret_key
        self.region = region

    def _call(self, target: str, body: dict) -> dict:
        payload = json.dumps(body).encode()
        headers = {
            "content-type": "application/x-amz-json-1.1",
            "x-amz-target": f"TrentService.{target}",
        }
        signed = sign_request("POST", self.endpoint, "/", {},
                              headers, payload, self.access_key,
                              self.secret_key, region=self.region,
                              service="kms")
        try:
            st, resp, _ = http_bytes("POST", f"{self.endpoint}/",
                                     payload, signed)
        except OSError as e:
            # transport failure must surface as a KmsError so the S3
            # gateway maps it to an S3 XML error, not a raw 500
            raise KmsError(f"kms {target}: endpoint unreachable "
                           f"({e})")
        try:
            doc = json.loads(resp) if resp else {}
        except ValueError:
            raise KmsError(f"kms {target}: undecodable response "
                           f"({st})")
        if st != 200:
            raise KmsError(doc.get("__type",
                                   f"kms {target}: {st}") +
                           (": " + doc["message"]
                            if doc.get("message") else ""))
        return doc

    # -- KMSProvider surface (kms.go) -------------------------------------

    def get_key_id(self, identifier: str) -> str:
        return self.describe_key(identifier)["KeyId"]

    def describe_key(self, identifier: str) -> dict:
        d = self._call("DescribeKey", {"KeyId": identifier})
        meta = d.get("KeyMetadata", {})
        return {"KeyId": meta.get("KeyId", identifier),
                "Arn": meta.get("Arn", ""),
                "Enabled": meta.get("Enabled", True),
                "Description": meta.get("Description", "")}

    def generate_data_key(self, identifier: str,
                          context: dict | None = None) -> dict:
        d = self._call("GenerateDataKey", {
            "KeyId": identifier, "KeySpec": "AES_256",
            "EncryptionContext": context or {}})
        return {"KeyId": d["KeyId"],
                "Plaintext": base64.b64decode(d["Plaintext"]),
                "CiphertextBlob": d["CiphertextBlob"]}

    def decrypt(self, ciphertext_blob: str,
                context: dict | None = None) -> dict:
        d = self._call("Decrypt", {
            "CiphertextBlob": ciphertext_blob,
            "EncryptionContext": context or {}})
        return {"KeyId": d.get("KeyId", ""),
                "Plaintext": base64.b64decode(d["Plaintext"])}


class KmsStubServer:
    """A wire-faithful KMS endpoint over LocalKms — what the tests
    (and a laptop deployment) point AwsKms at, the way the reference
    tests aws_kms.go against LocalStack."""

    def __init__(self, local_kms, host: str = "127.0.0.1",
                 port: int = 0, access_key: str = "AK",
                 secret_key: str = "SK"):
        from ..server.httpd import HttpServer
        self.kms = local_kms
        self.credentials = {access_key: secret_key}
        self.http = HttpServer(host, port)
        self.http.route("POST", "/", self._handle)

    def start(self):
        self.http.start()
        return self

    def stop(self):
        self.http.stop()

    @property
    def url(self):
        return self.http.url

    def _handle(self, req):
        # wire-faithful includes AUTH: verify the SigV4 signature
        # (service scope "kms") like a real endpoint would
        from ..s3.auth import SigV4Verifier
        ok, who, _ = SigV4Verifier(self.credentials).verify(
            "POST", req.path, req.query,
            {k.lower(): v for k, v in req.headers.items()}, req.body)
        if not ok:
            return 403, {"__type": "IncompleteSignatureException",
                         "message": who}
        target = req.headers.get("X-Amz-Target", "").split(".")[-1]
        body = req.json()
        try:
            if target == "DescribeKey":
                meta = self.kms.describe_key(body["KeyId"])
                return 200, {"KeyMetadata": meta}
            if target == "GenerateDataKey":
                dk = self.kms.generate_data_key(
                    body["KeyId"], body.get("EncryptionContext"))
                return 200, {
                    "KeyId": dk["KeyId"],
                    "Plaintext": base64.b64encode(
                        dk["Plaintext"]).decode(),
                    "CiphertextBlob": dk["CiphertextBlob"]}
            if target == "Decrypt":
                out = self.kms.decrypt(
                    body["CiphertextBlob"],
                    body.get("EncryptionContext"))
                return 200, {
                    "KeyId": out["KeyId"],
                    "Plaintext": base64.b64encode(
                        out["Plaintext"]).decode()}
            return 400, {"__type": "UnknownOperationException"}
        except KmsError as e:
            code = str(e).split(":")[0]
            return 400, {"__type": code, "message": str(e)}
