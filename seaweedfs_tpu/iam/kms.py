"""Local KMS provider + envelope encryption (reference: weed/kms/kms.go
provider interface, weed/kms/local/local_kms.go, weed/kms/envelope.go).

Master keys live in a JSON keystore (key id -> 256-bit material);
per-object DATA keys are minted fresh, returned in plaintext for the
gateway to encrypt with, and stored only as a ciphertext blob sealed
under the master key with AES-GCM (the encryption context is bound as
GCM AAD, so a blob decrypts only with the same context — kms.go
EncryptionContext semantics)."""

from __future__ import annotations

import base64
import json
import os
import secrets
import threading
import time

class KmsError(Exception):
    pass


def _aesgcm():
    """Lazy optional import: the `cryptography` wheel is only needed
    when envelope crypto actually runs.  Importing this module (for
    KmsError, key metadata, the store plumbing every gateway wires up)
    must work on a box without the wheel — sse.py, oidc.py and
    kms_cloud.py already follow the same rule."""
    try:
        from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    except ImportError as e:  # pragma: no cover — environment gap
        raise KmsError(
            "the `cryptography` package is required for KMS envelope "
            "encryption (pip install cryptography)") from e
    return AESGCM


class LocalKms:
    """kms/local/local_kms.go: file-backed key store, no external
    dependency.  Aliases resolve like the reference's GetKeyID."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.Lock()
        self._keys: dict[str, dict] = {}
        self._aliases: dict[str, str] = {}
        if path and os.path.exists(path):
            with open(path) as f:
                doc = json.load(f)
            self._keys = doc.get("keys", {})
            self._aliases = doc.get("aliases", {})

    def _save(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"keys": self._keys, "aliases": self._aliases},
                      f, indent=1)
        os.replace(tmp, self.path)

    # -- key management ----------------------------------------------------

    def create_key(self, alias: str = "",
                   description: str = "") -> str:
        with self._lock:
            key_id = secrets.token_hex(16)
            self._keys[key_id] = {
                "material": secrets.token_hex(32),
                "description": description,
                "enabled": True,
                "created": int(time.time()),
            }
            if alias:
                self._aliases[alias.removeprefix("alias/")] = key_id
            self._save()
            return key_id

    def get_key_id(self, identifier: str) -> str:
        """Resolve alias/ARN/id to the bare key id (kms.go GetKeyID)."""
        ident = identifier.rsplit("/", 1)[-1] \
            if identifier.startswith("arn:") else identifier
        ident = ident.removeprefix("alias/")
        if ident in self._keys:
            return ident
        if ident in self._aliases:
            return self._aliases[ident]
        raise KmsError(f"NotFoundException: key {identifier}")

    def describe_key(self, identifier: str) -> dict:
        key_id = self.get_key_id(identifier)
        meta = self._keys[key_id]
        return {"KeyId": key_id,
                "Arn": f"arn:aws:kms:::key/{key_id}",
                "Enabled": meta["enabled"],
                "Description": meta["description"],
                "CreationDate": meta["created"]}

    def disable_key(self, identifier: str) -> None:
        with self._lock:
            self._keys[self.get_key_id(identifier)]["enabled"] = False
            self._save()

    def _master(self, key_id: str) -> bytes:
        meta = self._keys.get(key_id)
        if meta is None:
            raise KmsError(f"NotFoundException: key {key_id}")
        if not meta["enabled"]:
            raise KmsError(f"DisabledException: key {key_id}")
        return bytes.fromhex(meta["material"])

    # -- data keys (envelope.go) ------------------------------------------

    @staticmethod
    def _aad(context: dict | None) -> bytes:
        return json.dumps(context or {}, sort_keys=True,
                          separators=(",", ":")).encode()

    def generate_data_key(self, identifier: str,
                          context: dict | None = None) -> dict:
        """GenerateDataKey: (plaintext 32-byte key, sealed blob).  The
        blob embeds the key id so Decrypt needs no key argument —
        kms.go CiphertextBlob format."""
        key_id = self.get_key_id(identifier)
        master = self._master(key_id)
        plaintext = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        sealed = _aesgcm()(master).encrypt(nonce, plaintext,
                                           self._aad(context))
        blob = json.dumps({
            "keyId": key_id,
            "nonce": base64.b64encode(nonce).decode(),
            "sealed": base64.b64encode(sealed).decode(),
        }).encode()
        return {"KeyId": key_id, "Plaintext": plaintext,
                "CiphertextBlob": base64.b64encode(blob).decode()}

    def decrypt(self, ciphertext_blob: str,
                context: dict | None = None) -> dict:
        try:
            blob = json.loads(base64.b64decode(ciphertext_blob))
            nonce = base64.b64decode(blob["nonce"])
            sealed = base64.b64decode(blob["sealed"])
            key_id = blob["keyId"]
        except (ValueError, KeyError, TypeError):
            raise KmsError("InvalidCiphertextException: undecodable "
                           "blob")
        master = self._master(key_id)
        aesgcm = _aesgcm()
        try:
            plaintext = aesgcm(master).decrypt(nonce, sealed,
                                               self._aad(context))
        except Exception:
            raise KmsError("InvalidCiphertextException: seal or "
                           "context mismatch")
        return {"KeyId": key_id, "Plaintext": plaintext}
