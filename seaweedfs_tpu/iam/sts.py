"""Stateless STS (reference: weed/iam/sts/sts_service.go,
token_utils.go).

The reference's design point — kept here — is that NO session state is
stored anywhere: the session token is a signed JWT carrying the whole
session (principal, role, expiry), and the temporary SECRET key is
derived deterministically from the session id with the STS signing
key.  Any gateway holding the signing key can therefore verify a
SigV4 request made with temporary credentials: it reads the session
token from x-amz-security-token, validates the JWT, re-derives the
secret, and runs normal SigV4 verification.

Roles live in a small JSON store (iam/integration/role_store.go):
name -> {actions, trust: [identity names or * patterns]}.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import os
import secrets
import threading
import time

from ..security import JwtError, decode_jwt, gen_jwt
from .identity import Identity

ACCESS_KEY_PREFIX = "STS"          # temp keys are recognizable by shape
DEFAULT_DURATION = 3600
MAX_DURATION = 12 * 3600


class StsError(Exception):
    pass


class RoleStore:
    """iam/integration/role_store.go: role name -> definition."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._roles: dict[str, dict] = {}
        self._lock = threading.Lock()
        if path and os.path.exists(path):
            with open(path) as f:
                self._roles = json.load(f)

    def put(self, name: str, actions: list[str],
            trust: list[str] | None = None) -> None:
        with self._lock:
            self._roles[name] = {"actions": actions,
                                 "trust": trust or ["*"]}
            self._save()

    def get(self, name: str) -> dict | None:
        return self._roles.get(name)

    def delete(self, name: str) -> None:
        with self._lock:
            self._roles.pop(name, None)
            self._save()

    def _save(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self._roles, f, indent=1)
        os.replace(tmp, self.path)

    def __iter__(self):
        return iter(self._roles.items())


class StsService:
    """Mint and verify temporary credentials."""

    def __init__(self, signing_key: str, roles: RoleStore | None = None,
                 issuer: str = "seaweedfs-sts"):
        if not signing_key:
            raise ValueError("sts requires a signing key")
        self.signing_key = signing_key
        self.roles = roles or RoleStore()
        self.issuer = issuer
        self.providers: dict[str, object] = {}  # OIDC by name

    def add_provider(self, provider) -> None:
        """Register an identity provider (iam/oidc.OidcProvider) for
        AssumeRoleWithWebIdentity."""
        self.providers[provider.name] = provider

    # -- minting -----------------------------------------------------------

    def assume_role(self, caller: Identity, role_name: str,
                    session_name: str = "session",
                    duration: int = DEFAULT_DURATION,
                    external: bool = False) -> dict:
        """sts_service.go AssumeRoleWithCredentials: the caller must be
        trusted by the role; returns AWS-shaped Credentials.

        `external` marks federated (web-identity) callers: they are
        admitted ONLY by trust entries that explicitly name the
        federation namespace ("oidc:..."), never by a bare "*" — the
        wildcard predates federation and means "any AUTHENTICATED
        LOCAL identity"; letting any IdP token satisfy it would be a
        silent privilege escalation."""
        role = self.roles.get(role_name)
        if role is None:
            raise StsError(f"no such role {role_name}")
        import fnmatch
        trust = role.get("trust", [])
        if external:
            trust = [p for p in trust if p.startswith("oidc:")]
        if not any(fnmatch.fnmatchcase(caller.name, pat)
                   for pat in trust):
            raise StsError(
                f"identity {caller.name} not trusted by {role_name}")
        duration = max(900, min(int(duration), MAX_DURATION))
        session_id = secrets.token_hex(8)
        access_key = f"{ACCESS_KEY_PREFIX}{session_id}"
        now = int(time.time())
        token = gen_jwt(self.signing_key, {
            "iss": self.issuer,
            "sub": caller.name,
            "role": role_name,
            "sessionName": session_name,
            "accessKey": access_key,
            "actions": role["actions"],
            "principalArn": (f"arn:aws:sts:::assumed-role/"
                             f"{role_name}/{session_name}"),
            "iat": now,
        }, expires_sec=duration)
        return {
            "AccessKeyId": access_key,
            "SecretAccessKey": self._derive_secret(access_key),
            "SessionToken": token,
            "Expiration": now + duration,
        }

    def assume_role_with_web_identity(self, token: str,
                                      role_name: str,
                                      session_name: str = "web",
                                      duration: int = DEFAULT_DURATION
                                      ) -> dict:
        """sts_service.go:431 AssumeRoleWithWebIdentity: validate the
        OIDC id token against every registered provider; the role's
        trust list must admit the external principal
        (oidc:<provider>#<sub>, wildcards allowed)."""
        from .oidc import OidcError
        reasons = []
        for name, provider in self.providers.items():
            try:
                ext = provider.validate(token)
            except OidcError as e:
                reasons.append(f"{name}: {e}")
                continue
            caller = Identity(ext.principal, actions=[])
            return self.assume_role(caller, role_name,
                                    session_name, duration,
                                    external=True)
        raise StsError("web identity rejected: " + (
            "; ".join(reasons) or "no identity providers registered"))

    def _derive_secret(self, access_key: str) -> str:
        """token_utils.go: secret = KDF(signing key, access key) —
        deterministic, so verification needs no session store."""
        mac = hmac.new(self.signing_key.encode(),
                       b"sts-secret:" + access_key.encode(),
                       hashlib.sha256).digest()
        return base64.urlsafe_b64encode(mac).decode().rstrip("=")

    # -- verification (gateway side) --------------------------------------

    def resolve(self, access_key: str, session_token: str
                ) -> tuple[str, Identity] | None:
        """Validate the session token and return (secret, ephemeral
        Identity) — or None if the token is invalid, expired, or does
        not belong to `access_key`."""
        if not access_key.startswith(ACCESS_KEY_PREFIX) or \
                not session_token:
            return None
        try:
            claims = decode_jwt(self.signing_key, session_token)
        except JwtError:
            return None
        if claims.get("accessKey") != access_key or \
                claims.get("iss") != self.issuer:
            return None
        ident = Identity(
            f"{claims.get('sub', '?')}@{claims.get('role', '?')}",
            actions=list(claims.get("actions", [])),
            principal_arn=claims.get("principalArn", ""))
        return self._derive_secret(access_key), ident
