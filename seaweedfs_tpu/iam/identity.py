"""Identity model + store (reference: weed/s3api/auth_credentials.go
Identity/Account/Credential and its s3.json config format, plus
weed/credential/ store archetypes).

Identities carry COARSE actions ("Admin", "Read:bucket/prefix",
"Write:bucket", ...) — the reference's first authorization layer,
evaluated before (and independently of) bucket-policy documents.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading

# s3_constants/s3_actions.go
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"
ACTION_DELETE_BUCKET = "DeleteBucket"
ACTION_READ_ACP = "ReadAcp"
ACTION_WRITE_ACP = "WriteAcp"
ACTION_BYPASS_GOVERNANCE = "BypassGovernanceRetention"

# auth_credentials.go:1534 CanDo consults these in order:
#   exact action, then "<Action>:<bucket[/key]>" patterns with
#   wildcards, then Admin-scoped equivalents.


def coarse_action(s3_action: str, method: str = "",
                  query: dict | None = None) -> str:
    """Map the fine-grained s3:* action names (policy engine
    vocabulary) onto the reference's coarse identity actions — the
    mapping s3api_server.go encodes by wrapping each route in
    iam.Auth(handler, ACTION_X)."""
    q = query or {}
    a = s3_action.removeprefix("s3:")
    if a in ("GetObjectRetention", "GetObjectLegalHold"):
        return ACTION_READ
    if a in ("PutObjectRetention", "PutObjectLegalHold"):
        return ACTION_WRITE
    if "Tagging" in a:
        return ACTION_TAGGING
    if a.endswith("Acl"):
        return ACTION_READ_ACP if a.startswith("Get") else \
            ACTION_WRITE_ACP
    if a == "DeleteBucket":
        return ACTION_DELETE_BUCKET
    if a.startswith("List"):
        return ACTION_LIST
    if a in ("GetObject", "GetObjectVersion", "HeadObject"):
        return ACTION_READ
    if a in ("PutObject", "DeleteObject", "DeleteObjectVersion",
             "AbortMultipartUpload", "RestoreObject"):
        return ACTION_WRITE
    if a == "CreateBucket":
        return ACTION_ADMIN
    # bucket configuration subresources (policy/cors/versioning/
    # object-lock/encryption/...) are admin-plane
    return ACTION_ADMIN


class Credential:
    def __init__(self, access_key: str, secret_key: str,
                 status: str = "Active"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.status = status

    def to_json(self) -> dict:
        return {"accessKey": self.access_key,
                "secretKey": self.secret_key, "status": self.status}

    @classmethod
    def from_json(cls, d: dict) -> "Credential":
        return cls(d["accessKey"], d["secretKey"],
                   d.get("status", "Active"))


class Account:
    """auth_credentials.go Account: the ownership principal S3 ACLs
    name.  The three canned accounts mirror the reference."""

    def __init__(self, acc_id: str, display_name: str = "",
                 email: str = ""):
        self.id = acc_id
        self.display_name = display_name or acc_id
        self.email = email

    def to_json(self) -> dict:
        return {"id": self.id, "displayName": self.display_name,
                "emailAddress": self.email}

    @classmethod
    def from_json(cls, d: dict) -> "Account":
        return cls(d.get("id", ""), d.get("displayName", ""),
                   d.get("emailAddress", ""))


ACCOUNT_ADMIN = Account("admin", "admin")
ACCOUNT_ANONYMOUS = Account("anonymous", "anonymous")


class Identity:
    def __init__(self, name: str,
                 credentials: list[Credential] | None = None,
                 actions: list[str] | None = None,
                 account: Account | None = None,
                 disabled: bool = False,
                 principal_arn: str = ""):
        self.name = name
        self.credentials = credentials or []
        self.actions = actions or []
        self.account = account or ACCOUNT_ADMIN
        self.disabled = disabled
        self.principal_arn = principal_arn or \
            f"arn:aws:iam:::user/{name}"
        # inline IAM policy documents by name (iamapi PutUserPolicy);
        # identity.actions holds static_actions ∪ their translation
        self.policies: dict[str, str] = {}
        # grants inherited through group membership (iam.proto Group
        # policy_names, evaluated in auth_credentials.go
        # evaluateIAMPolicies) — maintained by the IdentityStore, not
        # serialized: they are derived state, recomputed on every
        # group/policy mutation so detaching a policy from a group
        # revokes it from every member atomically
        self.group_actions: list[str] = []
        # actions provisioned directly (identities JSON / operator) —
        # policy recomputation must never strip these, or attaching a
        # policy to the admin identity would drop Admin (lockout)
        self.static_actions: list[str] = list(actions or [])

    @property
    def is_admin(self) -> bool:
        return ACTION_ADMIN in self.actions or \
            ACTION_ADMIN in self.group_actions

    def granted_actions(self) -> list[str]:
        """Own actions ∪ group-inherited ones — the set CanDo
        consults (reference: identity actions + group policy
        evaluation are independent allow paths)."""
        if not self.group_actions:
            return self.actions
        return list(self.actions) + [a for a in self.group_actions
                                     if a not in self.actions]

    def can_do(self, action: str, bucket: str, key: str = "") -> bool:
        """auth_credentials.go:1534 CanDo: exact action grants the
        whole system; otherwise match "<Action>:<bucket[/key]>"
        entries (wildcards allowed) with Admin:<scope> as superset."""
        if self.disabled:
            return False
        if self.is_admin:
            return True
        granted = self.granted_actions()
        if action in granted:
            return True
        if not bucket:
            return False
        full = bucket + ("/" + key.lstrip("/") if key else "")
        targets = (f"{action}:{full}", f"{ACTION_ADMIN}:{full}")
        for a in granted:
            if ":" not in a:
                continue
            if "*" in a or "?" in a:
                # wildcard entries match the fully-qualified target
                # (auth_credentials.go MatchesWildcard branch)
                if any(fnmatch.fnmatchcase(t, a) for t in targets):
                    return True
                continue
            act, _, scope = a.partition(":")
            if act not in (action, ACTION_ADMIN):
                continue
            # exact scope, bucket-limited scope, or path-prefix scope
            if scope in (full, bucket) or \
                    full.startswith(scope.rstrip("/") + "/"):
                return True
        return False

    def to_json(self) -> dict:
        return {"name": self.name,
                "credentials": [c.to_json() for c in self.credentials],
                "actions": list(self.actions),
                "staticActions": list(self.static_actions),
                "account": self.account.to_json(),
                "disabled": self.disabled,
                "principalArn": self.principal_arn,
                "policies": dict(self.policies)}

    @classmethod
    def from_json(cls, d: dict) -> "Identity":
        ident = cls(d["name"],
                    [Credential.from_json(c)
                     for c in d.get("credentials", [])],
                    list(d.get("actions", [])),
                    Account.from_json(d["account"])
                    if d.get("account") else None,
                    d.get("disabled", False),
                    d.get("principalArn", ""))
        ident.policies = dict(d.get("policies", {}))
        if "staticActions" in d:
            ident.static_actions = list(d["staticActions"])
        elif ident.policies:
            # migration: an older save serialized actions as
            # static ∪ policy-derived; re-deriving and subtracting
            # keeps policy grants revocable (DeleteUserPolicy must
            # not leave them baked into the static set forever).
            # Ambiguity: a static grant that COINCIDES with a policy
            # grant is also subtracted and will disappear when that
            # policy is deleted — fail-closed (losing a grant) is
            # preferred over fail-open (an irrevocable one); re-grant
            # via UpdateUser if that static action was intended
            try:
                from .iamapi import IamError, policy_to_actions
                derived = set()
                for doc in ident.policies.values():
                    derived.update(policy_to_actions(doc))
                ident.static_actions = [a for a in ident.actions
                                        if a not in derived]
            except (IamError, AttributeError, KeyError, TypeError,
                    ValueError):
                pass     # undecodable legacy doc: keep all actions
        # else: a hand-written identities JSON — its actions ARE the
        # static provisioned set (the cls(...) call captured them)
        return ident


class IdentityStore:
    """The s3.json identities config as a mutable, persistent store
    (credential/credential_store.go role).  Backing file is optional —
    gateways can run with a purely in-memory store for tests."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._identities: dict[str, Identity] = {}
        self._by_access_key: dict[str, Identity] = {}
        # managed policies (iam_pb.Policy name -> JSON content) and
        # groups (iam_pb.Group), the filer-propagated config the
        # reference carries in S3ApiConfiguration
        self._policies: dict[str, str] = {}
        self._groups: dict[str, dict] = {}
        # service accounts (iam.proto ServiceAccount: application
        # credentials parented to a user, optionally restricted to a
        # subset of its actions, optionally expiring)
        self._service_accounts: dict[str, dict] = {}
        self._sa_by_key: dict[str, dict] = {}
        self._mtime = 0.0
        if path and os.path.exists(path):
            with self._lock:
                self._reload()

    def _reload(self) -> None:
        """Caller holds the lock."""
        with open(self.path) as f:
            self.load_json(json.load(f))
        self._mtime = os.stat(self.path).st_mtime

    def _maybe_reload(self) -> None:
        """An `iam` server process and an `s3` gateway process share
        the store through its JSON file; the reference propagates
        config through the filer (credential/propagating_store.go) —
        here an mtime check on lookup keeps readers current."""
        if not self.path:
            return
        try:
            m = os.stat(self.path).st_mtime
        except OSError:
            return
        if m != self._mtime:
            with self._lock:
                if m != self._mtime:
                    self._reload()

    # -- config IO ---------------------------------------------------------

    def load_json(self, doc: dict) -> None:
        """Build fresh maps, then swap the references atomically —
        lock-free readers (every request thread) must never observe
        the cleared-but-not-rebuilt intermediate state."""
        identities: dict[str, Identity] = {}
        by_key: dict[str, Identity] = {}
        for d in doc.get("identities", []):
            ident = Identity.from_json(d)
            identities[ident.name] = ident
            for c in ident.credentials:
                by_key[c.access_key] = ident
        sas = {sa["id"]: sa for sa in doc.get("serviceAccounts", [])}
        with self._lock:
            self._identities = identities
            self._by_access_key = by_key
            self._policies = dict(doc.get("policies", {}))
            self._groups = dict(doc.get("groups", {}))
            self._service_accounts = sas
            self._sa_by_key = {
                sa["credential"]["accessKey"]: sa
                for sa in sas.values() if sa.get("credential")}
            self._recompute_group_grants()

    def to_json(self) -> dict:
        with self._lock:
            out = {"identities": [i.to_json()
                                  for i in self._identities.values()]}
            if self._policies:
                out["policies"] = dict(self._policies)
            if self._groups:
                out["groups"] = dict(self._groups)
            if self._service_accounts:
                out["serviceAccounts"] = \
                    list(self._service_accounts.values())
            return out

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.to_json(), f, indent=1)
            os.replace(tmp, self.path)
            self._mtime = os.stat(self.path).st_mtime

    def _index(self, ident: Identity) -> None:
        """Caller holds the lock."""
        self._identities[ident.name] = ident
        for c in ident.credentials:
            self._by_access_key[c.access_key] = ident

    # -- lookups -----------------------------------------------------------

    def get(self, name: str) -> Identity | None:
        self._maybe_reload()
        return self._identities.get(name)

    def by_access_key(self, access_key: str) -> Identity | None:
        self._maybe_reload()
        ident = self._by_access_key.get(access_key)
        if ident is not None:
            return ident
        sa = self._sa_by_key.get(access_key)
        if sa is not None:
            return self._sa_identity(sa)
        return None

    def _sa_identity(self, sa: dict) -> Identity | None:
        """Synthesize the auth-time Identity for a service-account
        credential (auth_credentials.go loads ServiceAccounts into
        the same access-key index).  Acts AS the parent user (bucket
        ownership, policy principal) but with the SA's restricted
        action set when one was given; dead if the SA is disabled /
        expired or the parent is gone / disabled."""
        parent = self._identities.get(sa.get("parentUser", ""))
        if parent is None:
            return None
        import time as _t
        exp = sa.get("expiration", 0)
        dead = (sa.get("disabled", False) or parent.disabled or
                (exp and exp < _t.time()))
        restricted = list(sa.get("actions") or ())
        if restricted:
            # the subset invariant is enforced at AUTH time, not just
            # at creation: revoking a grant from the parent must also
            # revoke it from every service account that named it —
            # otherwise an operator auditing the parent sees no
            # access while the SA's writes keep landing
            kept = []
            for a in restricted:
                act, _, scope = a.partition(":")
                bucket, _, key = scope.partition("/")
                if parent.can_do(act, bucket, key):
                    kept.append(a)
            restricted = kept or ["__none__"]   # all revoked: dead
        ident = Identity(
            parent.name,
            [Credential.from_json(sa["credential"])],
            restricted or list(parent.actions),
            parent.account, disabled=bool(dead),
            principal_arn=parent.principal_arn)
        if not sa.get("actions"):
            # unrestricted SA inherits the parent's group grants too;
            # a restricted one is capped at exactly its action list
            ident.group_actions = list(parent.group_actions)
        return ident

    def secret_for(self, access_key: str) -> str | None:
        ident = self.by_access_key(access_key)
        if ident is None or ident.disabled:
            return None
        for c in ident.credentials:
            if c.access_key == access_key and c.status == "Active":
                return c.secret_key
        return None

    def anonymous(self) -> Identity | None:
        """auth_credentials.go: an identity literally named
        "anonymous" grants unauthenticated requests its actions."""
        return self.get("anonymous")

    def __iter__(self):
        with self._lock:
            return iter(list(self._identities.values()))

    # -- mutation (iamapi writes through these) ---------------------------

    def put(self, ident: Identity) -> None:
        with self._lock:
            old = self._identities.get(ident.name)
            if old is not None:
                for c in old.credentials:
                    self._by_access_key.pop(c.access_key, None)
            self._index(ident)
            self._recompute_group_grants()
            self.save()

    def delete(self, name: str) -> None:
        with self._lock:
            old = self._identities.pop(name, None)
            if old is not None:
                for c in old.credentials:
                    self._by_access_key.pop(c.access_key, None)
                self.save()

    # -- managed policies + groups (iam.proto Policy/Group) ---------------

    def put_policy(self, name: str, content: str) -> None:
        with self._lock:
            self._policies[name] = content
            self._recompute_group_grants()
            self.save()

    def get_policy(self, name: str) -> "str | None":
        self._maybe_reload()
        return self._policies.get(name)

    def list_policies(self) -> "dict[str, str]":
        self._maybe_reload()
        with self._lock:
            return dict(self._policies)

    def delete_policy(self, name: str) -> None:
        with self._lock:
            self._policies.pop(name, None)
            self._recompute_group_grants()
            self.save()

    def _recompute_group_grants(self) -> None:
        """Refresh every identity's derived group_actions from group
        membership × attached managed policies.  Caller holds the
        lock (or is single-threaded startup).  Translation uses the
        same policy→coarse-action mapping the IAM API applies to
        inline user policies, so a grant means the same thing
        whichever path attached it."""
        derived: dict[str, set] = {}
        if self._groups:
            try:
                from .iamapi import IamError, policy_to_actions
            except Exception:
                return
            for gname, g in self._groups.items():
                # FAIL CLOSED per group: a malformed entry (non-dict
                # group, non-list members/policyNames, unhashable
                # member...) drops THAT group's grant and logs —
                # raising here would abort mid-recompute and leave a
                # half-updated grant map where some identities carry
                # stale group actions and others none
                try:
                    if g.get("disabled"):
                        continue
                    acts: set = set()
                    for pname in g.get("policyNames", []):
                        doc = self._policies.get(pname)
                        if doc:
                            try:
                                acts.update(policy_to_actions(doc))
                            except (IamError, AttributeError, KeyError,
                                    TypeError, ValueError):
                                continue   # malformed doc grants nothing
                    if not acts:
                        continue
                    for member in g.get("members", []):
                        derived.setdefault(str(member),
                                           set()).update(acts)
                except (AttributeError, KeyError, TypeError,
                        ValueError) as e:
                    from ..util import wlog
                    wlog.warning(
                        "iam group %r malformed; its grant is "
                        "dropped: %s", gname, e, component="iam")
                    continue
        for ident in self._identities.values():
            ident.group_actions = sorted(derived.get(ident.name, ()))

    def put_group(self, name: str, group: dict) -> None:
        with self._lock:
            self._groups[name] = group
            self._recompute_group_grants()
            self.save()

    def get_group(self, name: str) -> "dict | None":
        self._maybe_reload()
        return self._groups.get(name)

    def list_groups(self) -> "dict[str, dict]":
        self._maybe_reload()
        with self._lock:
            return dict(self._groups)

    def delete_group(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)
            self._recompute_group_grants()
            self.save()

    # -- service accounts (iam.proto ServiceAccount) -----------------------

    def put_service_account(self, sa: dict) -> None:
        with self._lock:
            old = self._service_accounts.get(sa["id"])
            if old is not None and old.get("credential"):
                self._sa_by_key.pop(
                    old["credential"]["accessKey"], None)
            self._service_accounts[sa["id"]] = sa
            if sa.get("credential"):
                self._sa_by_key[sa["credential"]["accessKey"]] = sa
            self.save()

    def get_service_account(self, sa_id: str) -> "dict | None":
        self._maybe_reload()
        return self._service_accounts.get(sa_id)

    def list_service_accounts(self, parent: str = "") -> list[dict]:
        self._maybe_reload()
        with self._lock:
            return [sa for sa in self._service_accounts.values()
                    if not parent or sa.get("parentUser") == parent]

    def delete_service_account(self, sa_id: str) -> None:
        with self._lock:
            old = self._service_accounts.pop(sa_id, None)
            if old is not None:
                if old.get("credential"):
                    self._sa_by_key.pop(
                        old["credential"]["accessKey"], None)
                self.save()

    # -- SigV4Verifier adapter --------------------------------------------

    class _SecretsView:
        def __init__(self, store: "IdentityStore"):
            self.store = store

        def get(self, access_key: str) -> str | None:
            return self.store.secret_for(access_key)

    def secrets_view(self):
        """Mapping-shaped live view for SigV4Verifier (which only
        calls .get) — mutations through the store are visible to the
        verifier immediately, unlike a copied dict."""
        return IdentityStore._SecretsView(self)
