"""Identity model + store (reference: weed/s3api/auth_credentials.go
Identity/Account/Credential and its s3.json config format, plus
weed/credential/ store archetypes).

Identities carry COARSE actions ("Admin", "Read:bucket/prefix",
"Write:bucket", ...) — the reference's first authorization layer,
evaluated before (and independently of) bucket-policy documents.
"""

from __future__ import annotations

import fnmatch
import json
import os
import threading

# s3_constants/s3_actions.go
ACTION_READ = "Read"
ACTION_WRITE = "Write"
ACTION_LIST = "List"
ACTION_TAGGING = "Tagging"
ACTION_ADMIN = "Admin"
ACTION_DELETE_BUCKET = "DeleteBucket"
ACTION_READ_ACP = "ReadAcp"
ACTION_WRITE_ACP = "WriteAcp"
ACTION_BYPASS_GOVERNANCE = "BypassGovernanceRetention"

# auth_credentials.go:1534 CanDo consults these in order:
#   exact action, then "<Action>:<bucket[/key]>" patterns with
#   wildcards, then Admin-scoped equivalents.


def coarse_action(s3_action: str, method: str = "",
                  query: dict | None = None) -> str:
    """Map the fine-grained s3:* action names (policy engine
    vocabulary) onto the reference's coarse identity actions — the
    mapping s3api_server.go encodes by wrapping each route in
    iam.Auth(handler, ACTION_X)."""
    q = query or {}
    a = s3_action.removeprefix("s3:")
    if a in ("GetObjectRetention", "GetObjectLegalHold"):
        return ACTION_READ
    if a in ("PutObjectRetention", "PutObjectLegalHold"):
        return ACTION_WRITE
    if "Tagging" in a:
        return ACTION_TAGGING
    if a.endswith("Acl"):
        return ACTION_READ_ACP if a.startswith("Get") else \
            ACTION_WRITE_ACP
    if a == "DeleteBucket":
        return ACTION_DELETE_BUCKET
    if a.startswith("List"):
        return ACTION_LIST
    if a in ("GetObject", "GetObjectVersion", "HeadObject"):
        return ACTION_READ
    if a in ("PutObject", "DeleteObject", "DeleteObjectVersion",
             "AbortMultipartUpload", "RestoreObject"):
        return ACTION_WRITE
    if a == "CreateBucket":
        return ACTION_ADMIN
    # bucket configuration subresources (policy/cors/versioning/
    # object-lock/encryption/...) are admin-plane
    return ACTION_ADMIN


class Credential:
    def __init__(self, access_key: str, secret_key: str,
                 status: str = "Active"):
        self.access_key = access_key
        self.secret_key = secret_key
        self.status = status

    def to_json(self) -> dict:
        return {"accessKey": self.access_key,
                "secretKey": self.secret_key, "status": self.status}

    @classmethod
    def from_json(cls, d: dict) -> "Credential":
        return cls(d["accessKey"], d["secretKey"],
                   d.get("status", "Active"))


class Account:
    """auth_credentials.go Account: the ownership principal S3 ACLs
    name.  The three canned accounts mirror the reference."""

    def __init__(self, acc_id: str, display_name: str = "",
                 email: str = ""):
        self.id = acc_id
        self.display_name = display_name or acc_id
        self.email = email

    def to_json(self) -> dict:
        return {"id": self.id, "displayName": self.display_name,
                "emailAddress": self.email}

    @classmethod
    def from_json(cls, d: dict) -> "Account":
        return cls(d.get("id", ""), d.get("displayName", ""),
                   d.get("emailAddress", ""))


ACCOUNT_ADMIN = Account("admin", "admin")
ACCOUNT_ANONYMOUS = Account("anonymous", "anonymous")


class Identity:
    def __init__(self, name: str,
                 credentials: list[Credential] | None = None,
                 actions: list[str] | None = None,
                 account: Account | None = None,
                 disabled: bool = False,
                 principal_arn: str = ""):
        self.name = name
        self.credentials = credentials or []
        self.actions = actions or []
        self.account = account or ACCOUNT_ADMIN
        self.disabled = disabled
        self.principal_arn = principal_arn or \
            f"arn:aws:iam:::user/{name}"
        # inline IAM policy documents by name (iamapi PutUserPolicy);
        # identity.actions holds static_actions ∪ their translation
        self.policies: dict[str, str] = {}
        # actions provisioned directly (identities JSON / operator) —
        # policy recomputation must never strip these, or attaching a
        # policy to the admin identity would drop Admin (lockout)
        self.static_actions: list[str] = list(actions or [])

    @property
    def is_admin(self) -> bool:
        return ACTION_ADMIN in self.actions

    def can_do(self, action: str, bucket: str, key: str = "") -> bool:
        """auth_credentials.go:1534 CanDo: exact action grants the
        whole system; otherwise match "<Action>:<bucket[/key]>"
        entries (wildcards allowed) with Admin:<scope> as superset."""
        if self.disabled:
            return False
        if self.is_admin:
            return True
        if action in self.actions:
            return True
        if not bucket:
            return False
        full = bucket + ("/" + key.lstrip("/") if key else "")
        targets = (f"{action}:{full}", f"{ACTION_ADMIN}:{full}")
        for a in self.actions:
            if ":" not in a:
                continue
            if "*" in a or "?" in a:
                # wildcard entries match the fully-qualified target
                # (auth_credentials.go MatchesWildcard branch)
                if any(fnmatch.fnmatchcase(t, a) for t in targets):
                    return True
                continue
            granted, _, scope = a.partition(":")
            if granted not in (action, ACTION_ADMIN):
                continue
            # exact scope, bucket-limited scope, or path-prefix scope
            if scope in (full, bucket) or \
                    full.startswith(scope.rstrip("/") + "/"):
                return True
        return False

    def to_json(self) -> dict:
        return {"name": self.name,
                "credentials": [c.to_json() for c in self.credentials],
                "actions": list(self.actions),
                "staticActions": list(self.static_actions),
                "account": self.account.to_json(),
                "disabled": self.disabled,
                "principalArn": self.principal_arn,
                "policies": dict(self.policies)}

    @classmethod
    def from_json(cls, d: dict) -> "Identity":
        ident = cls(d["name"],
                    [Credential.from_json(c)
                     for c in d.get("credentials", [])],
                    list(d.get("actions", [])),
                    Account.from_json(d["account"])
                    if d.get("account") else None,
                    d.get("disabled", False),
                    d.get("principalArn", ""))
        ident.policies = dict(d.get("policies", {}))
        if "staticActions" in d:
            ident.static_actions = list(d["staticActions"])
        elif ident.policies:
            # migration: an older save serialized actions as
            # static ∪ policy-derived; re-deriving and subtracting
            # keeps policy grants revocable (DeleteUserPolicy must
            # not leave them baked into the static set forever).
            # Ambiguity: a static grant that COINCIDES with a policy
            # grant is also subtracted and will disappear when that
            # policy is deleted — fail-closed (losing a grant) is
            # preferred over fail-open (an irrevocable one); re-grant
            # via UpdateUser if that static action was intended
            try:
                from .iamapi import policy_to_actions
                derived = set()
                for doc in ident.policies.values():
                    derived.update(policy_to_actions(doc))
                ident.static_actions = [a for a in ident.actions
                                        if a not in derived]
            except Exception:    # undecodable legacy doc: keep all
                pass
        # else: a hand-written identities JSON — its actions ARE the
        # static provisioned set (the cls(...) call captured them)
        return ident


class IdentityStore:
    """The s3.json identities config as a mutable, persistent store
    (credential/credential_store.go role).  Backing file is optional —
    gateways can run with a purely in-memory store for tests."""

    def __init__(self, path: str | None = None):
        self.path = path
        self._lock = threading.RLock()
        self._identities: dict[str, Identity] = {}
        self._by_access_key: dict[str, Identity] = {}
        # managed policies (iam_pb.Policy name -> JSON content) and
        # groups (iam_pb.Group), the filer-propagated config the
        # reference carries in S3ApiConfiguration
        self._policies: dict[str, str] = {}
        self._groups: dict[str, dict] = {}
        self._mtime = 0.0
        if path and os.path.exists(path):
            self._reload()

    def _reload(self) -> None:
        with open(self.path) as f:
            self.load_json(json.load(f))
        self._mtime = os.stat(self.path).st_mtime

    def _maybe_reload(self) -> None:
        """An `iam` server process and an `s3` gateway process share
        the store through its JSON file; the reference propagates
        config through the filer (credential/propagating_store.go) —
        here an mtime check on lookup keeps readers current."""
        if not self.path:
            return
        try:
            m = os.stat(self.path).st_mtime
        except OSError:
            return
        if m != self._mtime:
            with self._lock:
                if m != self._mtime:
                    self._reload()

    # -- config IO ---------------------------------------------------------

    def load_json(self, doc: dict) -> None:
        """Build fresh maps, then swap the references atomically —
        lock-free readers (every request thread) must never observe
        the cleared-but-not-rebuilt intermediate state."""
        identities: dict[str, Identity] = {}
        by_key: dict[str, Identity] = {}
        for d in doc.get("identities", []):
            ident = Identity.from_json(d)
            identities[ident.name] = ident
            for c in ident.credentials:
                by_key[c.access_key] = ident
        with self._lock:
            self._identities = identities
            self._by_access_key = by_key
            self._policies = dict(doc.get("policies", {}))
            self._groups = dict(doc.get("groups", {}))

    def to_json(self) -> dict:
        with self._lock:
            out = {"identities": [i.to_json()
                                  for i in self._identities.values()]}
            if self._policies:
                out["policies"] = dict(self._policies)
            if self._groups:
                out["groups"] = dict(self._groups)
            return out

    def save(self) -> None:
        if not self.path:
            return
        with self._lock:
            tmp = self.path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(self.to_json(), f, indent=1)
            os.replace(tmp, self.path)
            self._mtime = os.stat(self.path).st_mtime

    def _index(self, ident: Identity) -> None:
        self._identities[ident.name] = ident
        for c in ident.credentials:
            self._by_access_key[c.access_key] = ident

    # -- lookups -----------------------------------------------------------

    def get(self, name: str) -> Identity | None:
        self._maybe_reload()
        return self._identities.get(name)

    def by_access_key(self, access_key: str) -> Identity | None:
        self._maybe_reload()
        return self._by_access_key.get(access_key)

    def secret_for(self, access_key: str) -> str | None:
        ident = self.by_access_key(access_key)
        if ident is None or ident.disabled:
            return None
        for c in ident.credentials:
            if c.access_key == access_key and c.status == "Active":
                return c.secret_key
        return None

    def anonymous(self) -> Identity | None:
        """auth_credentials.go: an identity literally named
        "anonymous" grants unauthenticated requests its actions."""
        return self.get("anonymous")

    def __iter__(self):
        with self._lock:
            return iter(list(self._identities.values()))

    # -- mutation (iamapi writes through these) ---------------------------

    def put(self, ident: Identity) -> None:
        with self._lock:
            old = self._identities.get(ident.name)
            if old is not None:
                for c in old.credentials:
                    self._by_access_key.pop(c.access_key, None)
            self._index(ident)
            self.save()

    def delete(self, name: str) -> None:
        with self._lock:
            old = self._identities.pop(name, None)
            if old is not None:
                for c in old.credentials:
                    self._by_access_key.pop(c.access_key, None)
                self.save()

    # -- managed policies + groups (iam.proto Policy/Group) ---------------

    def put_policy(self, name: str, content: str) -> None:
        with self._lock:
            self._policies[name] = content
            self.save()

    def get_policy(self, name: str) -> "str | None":
        self._maybe_reload()
        return self._policies.get(name)

    def list_policies(self) -> "dict[str, str]":
        self._maybe_reload()
        with self._lock:
            return dict(self._policies)

    def delete_policy(self, name: str) -> None:
        with self._lock:
            self._policies.pop(name, None)
            self.save()

    def put_group(self, name: str, group: dict) -> None:
        with self._lock:
            self._groups[name] = group
            self.save()

    def get_group(self, name: str) -> "dict | None":
        self._maybe_reload()
        return self._groups.get(name)

    def list_groups(self) -> "dict[str, dict]":
        self._maybe_reload()
        with self._lock:
            return dict(self._groups)

    def delete_group(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)
            self.save()

    # -- SigV4Verifier adapter --------------------------------------------

    class _SecretsView:
        def __init__(self, store: "IdentityStore"):
            self.store = store

        def get(self, access_key: str) -> str | None:
            return self.store.secret_for(access_key)

    def secrets_view(self):
        """Mapping-shaped live view for SigV4Verifier (which only
        calls .get) — mutations through the store are visible to the
        verifier immediately, unlike a copied dict."""
        return IdentityStore._SecretsView(self)
