"""OIDC identity provider (reference: weed/iam/oidc/oidc_provider.go
+ providers/provider.go).

Validates OIDC ID tokens (JWTs) against a configured issuer,
audience, and key set — RS256 with PEM public keys or HS256 with a
shared secret (the reference fetches JWKS over HTTP; this image has
zero egress, so keys are provisioned in the provider config, which
its mock/test providers do too).  A validated token becomes an
ExternalIdentity that STS trust policies can admit via
AssumeRoleWithWebIdentity."""

from __future__ import annotations

import base64
import hashlib
import hmac as hmac_mod
import json
import time


class OidcError(Exception):
    pass


def _b64url_decode(s: str) -> bytes:
    return base64.urlsafe_b64decode(s + "=" * (-len(s) % 4))


class ExternalIdentity:
    """providers/provider.go ExternalIdentity."""

    def __init__(self, provider: str, sub: str, email: str = "",
                 groups: "list[str] | None" = None,
                 claims: "dict | None" = None):
        self.provider = provider
        self.sub = sub
        self.email = email
        self.groups = groups or []
        self.claims = claims or {}

    @property
    def principal(self) -> str:
        """The trust-policy name: oidc:<provider>#<sub>."""
        return f"oidc:{self.provider}#{self.sub}"


class OidcProvider:
    def __init__(self, name: str, issuer: str, audience: str = "",
                 rsa_public_keys_pem: "list[bytes] | None" = None,
                 hs256_secret: str = ""):
        self.name = name
        self.issuer = issuer
        self.audience = audience
        self.hs256_secret = hs256_secret
        self._rsa_keys = []
        for pem in rsa_public_keys_pem or []:
            from cryptography.hazmat.primitives import serialization
            self._rsa_keys.append(
                serialization.load_pem_public_key(pem))

    # -- token validation (oidc_provider.go ValidateToken) ----------------

    def validate(self, token: str) -> ExternalIdentity:
        parts = token.split(".")
        if len(parts) != 3:
            raise OidcError("malformed id token")
        try:
            header = json.loads(_b64url_decode(parts[0]))
            claims = json.loads(_b64url_decode(parts[1]))
            sig = _b64url_decode(parts[2])
        except (ValueError, TypeError):
            raise OidcError("undecodable id token")
        if not isinstance(header, dict) or \
                not isinstance(claims, dict):
            # valid JSON that is not an object (e.g. "[1]") must be a
            # 403-class rejection, not an AttributeError-500
            raise OidcError("undecodable id token")
        signing_input = f"{parts[0]}.{parts[1]}".encode()
        alg = header.get("alg", "")
        if alg == "RS256":
            self._verify_rs256(signing_input, sig)
        elif alg == "HS256" and self.hs256_secret:
            want = hmac_mod.new(self.hs256_secret.encode(),
                                signing_input,
                                hashlib.sha256).digest()
            if not hmac_mod.compare_digest(want, sig):
                raise OidcError("bad token signature")
        else:
            raise OidcError(f"unsupported token alg {alg!r}")
        # issuer / audience / expiry (oidc_provider.go claim checks)
        if claims.get("iss") != self.issuer:
            raise OidcError(
                f"issuer mismatch: {claims.get('iss')!r}")
        if self.audience:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                raise OidcError("audience mismatch")
        now = time.time()
        try:
            exp = float(claims["exp"])   # exp is REQUIRED (OIDC core)
        except KeyError:
            raise OidcError("id token carries no exp")
        except (TypeError, ValueError):
            raise OidcError("id token exp undecodable")
        if now > exp:
            raise OidcError("id token expired")
        if "nbf" in claims:
            try:
                if now < float(claims["nbf"]):
                    raise OidcError("id token not yet valid")
            except (TypeError, ValueError):
                raise OidcError("id token nbf undecodable")
        sub = claims.get("sub", "")
        if not sub:
            raise OidcError("id token carries no sub")
        groups = claims.get("groups", [])
        if not isinstance(groups, list):
            groups = [str(groups)] if groups else []
        return ExternalIdentity(
            self.name, str(sub), str(claims.get("email", "") or ""),
            [str(g) for g in groups], claims)

    def _verify_rs256(self, signing_input: bytes,
                      sig: bytes) -> None:
        from cryptography.exceptions import InvalidSignature
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        if not self._rsa_keys:
            raise OidcError("no RS256 keys configured")
        for key in self._rsa_keys:
            try:
                key.verify(sig, signing_input, padding.PKCS1v15(),
                           hashes.SHA256())
                return
            except InvalidSignature:
                continue
        raise OidcError("bad token signature")


def mint_test_token(claims: dict, hs256_secret: str = "",
                    rsa_private_key=None) -> str:
    """Token minting for tests/tools (the reference ships
    oidc/mock_provider.go for the same reason)."""
    alg = "HS256" if hs256_secret else "RS256"
    header = base64.urlsafe_b64encode(json.dumps(
        {"alg": alg, "typ": "JWT"}).encode()).rstrip(b"=")
    payload = base64.urlsafe_b64encode(json.dumps(
        claims, sort_keys=True).encode()).rstrip(b"=")
    signing_input = header + b"." + payload
    if hs256_secret:
        sig = hmac_mod.new(hs256_secret.encode(), signing_input,
                           hashlib.sha256).digest()
    else:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        sig = rsa_private_key.sign(signing_input, padding.PKCS1v15(),
                                   hashes.SHA256())
    return (signing_input + b"." +
            base64.urlsafe_b64encode(sig).rstrip(b"=")).decode()
