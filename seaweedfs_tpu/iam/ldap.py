"""LDAP identity provider (weed/iam/ldap/ldap_provider.go).

Authenticates users against an external LDAP v3 server by simple bind
and maps directory attributes onto an identity, the way the
reference's provider does with go-ldap: resolve the user's DN (direct
template or subtree search), bind with the supplied password, read the
mapped attributes.  No LDAP library exists in this environment, so the
wire protocol (RFC 4511 over BER) is implemented here directly —
exactly the subset the provider needs: BindRequest/Response,
SearchRequest (equality filter) / SearchResultEntry / Done, and
UnbindRequest.
"""

from __future__ import annotations

import socket
import threading

class LdapError(RuntimeError):
    pass


# -- BER (X.690) minimal codec -------------------------------------------


def ber_len(n: int) -> bytes:
    if n < 0x80:
        return bytes([n])
    body = n.to_bytes((n.bit_length() + 7) // 8, "big")
    return bytes([0x80 | len(body)]) + body


def tlv(tag: int, body: bytes) -> bytes:
    return bytes([tag]) + ber_len(len(body)) + body


def ber_int(v: int, tag: int = 0x02) -> bytes:
    if v == 0:
        return tlv(tag, b"\x00")
    body = v.to_bytes((v.bit_length() // 8) + 1, "big")
    return tlv(tag, body)


def ber_str(s: "str | bytes", tag: int = 0x04) -> bytes:
    return tlv(tag, s.encode() if isinstance(s, str) else s)


def ber_seq(body: bytes, tag: int = 0x30) -> bytes:
    return tlv(tag, body)


class BerReader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def eof(self) -> bool:
        return self.pos >= len(self.buf)

    def read_tlv(self) -> "tuple[int, bytes]":
        # malformed/truncated BER from a misbehaving peer must raise a
        # protocol error the callers handle (LdapError), never an
        # IndexError that kills the calling thread
        if self.pos + 2 > len(self.buf):
            raise LdapError("truncated BER element")
        tag = self.buf[self.pos]
        self.pos += 1
        first = self.buf[self.pos]
        self.pos += 1
        if first < 0x80:
            n = first
        else:
            k = first & 0x7F
            if self.pos + k > len(self.buf):
                raise LdapError("truncated BER length")
            n = int.from_bytes(self.buf[self.pos:self.pos + k], "big")
            self.pos += k
        if self.pos + n > len(self.buf):
            raise LdapError("BER length exceeds message")
        body = self.buf[self.pos:self.pos + n]
        self.pos += n
        return tag, body


MAX_MESSAGE = 1 << 20  # a bind/search reply is tiny; a peer claiming
# multi-MB (or GB) frames is hostile or not LDAP at all


def read_message(sock_file) -> "tuple[int, int, bytes]":
    """One LDAPMessage: returns (message_id, op_tag, op_body)."""
    head = sock_file.read(2)
    if len(head) < 2:
        raise OSError("ldap: connection closed")
    first = head[1]
    if first < 0x80:
        total = first
        prefix = b""
    else:
        k = first & 0x7F
        if k > 4:
            raise LdapError("ldap: absurd length-of-length")
        prefix = sock_file.read(k)
        total = int.from_bytes(prefix, "big")
    if total > MAX_MESSAGE:
        raise LdapError(f"ldap: message claims {total} bytes "
                        f"(cap {MAX_MESSAGE})")
    body = sock_file.read(total)
    if len(body) < total:
        raise OSError("ldap: short message")
    r = BerReader(body)
    tag, mid_body = r.read_tlv()
    mid = int.from_bytes(mid_body, "big") if mid_body else 0
    op_tag, op_body = r.read_tlv()
    return mid, op_tag, op_body


# -- client ---------------------------------------------------------------

class LdapClient:
    """One connection; bind/search/unbind (RFC 4511 subset).
    `use_tls` wraps the connection in TLS (ldaps) — simple binds carry
    the password in cleartext, so any non-loopback directory should be
    reached over TLS."""

    def __init__(self, host: str, port: int = 389,
                 timeout: float = 10.0, use_tls: bool = False,
                 tls_verify: bool = True):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        if use_tls:
            import ssl
            ctx = ssl.create_default_context()
            if not tls_verify:
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE
            self.sock = ctx.wrap_socket(self.sock,
                                        server_hostname=host)
        self.f = self.sock.makefile("rb")
        self._mid = 0

    def close(self) -> None:
        try:
            self.sock.sendall(ber_seq(
                ber_int(self._mid + 1) + tlv(0x42, b"")))  # unbind
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    def _send(self, op: bytes) -> int:
        self._mid += 1
        self.sock.sendall(ber_seq(ber_int(self._mid) + op))
        return self._mid

    def bind(self, dn: str, password: str) -> bool:
        """Simple bind; True on success, False on invalid
        credentials (resultCode 49); raises on anything else."""
        op = tlv(0x60, ber_int(3) + ber_str(dn) +
                 ber_str(password, tag=0x80))
        self._send(op)
        _mid, op_tag, body = read_message(self.f)
        if op_tag != 0x61:
            raise LdapError(f"unexpected bind reply tag {op_tag:#x}")
        r = BerReader(body)
        _t, code_b = r.read_tlv()
        code = int.from_bytes(code_b, "big") if code_b else 0
        if code == 0:
            return True
        if code == 49:  # invalidCredentials
            return False
        raise LdapError(f"bind failed: resultCode {code}")

    def search_one(self, base_dn: str, attr: str, value: str,
                   want_attrs: "list[str]"
                   ) -> "tuple[str, dict] | None":
        """Subtree search with an equality filter; returns
        (dn, {attr: [values]}) for the first entry, or None."""
        flt = tlv(0xA3, ber_str(attr) + ber_str(value))
        attrs = ber_seq(b"".join(ber_str(a) for a in want_attrs))
        op = tlv(0x63, ber_str(base_dn) +
                 ber_int(2, tag=0x0A) +      # scope wholeSubtree
                 ber_int(3, tag=0x0A) +      # derefAlways
                 ber_int(100) + ber_int(10) +  # size/time limits
                 tlv(0x01, b"\x00") +        # typesOnly FALSE
                 flt + attrs)
        self._send(op)
        found = None
        while True:
            _mid, op_tag, body = read_message(self.f)
            if op_tag == 0x64 and found is None:  # SearchResultEntry
                r = BerReader(body)
                _t, dn = r.read_tlv()
                attrs_out: dict = {}
                _t, attr_list = r.read_tlv()
                ar = BerReader(attr_list)
                while not ar.eof():
                    _t, one = ar.read_tlv()
                    er = BerReader(one)
                    _t, name = er.read_tlv()
                    _t, vals = er.read_tlv()
                    vr = BerReader(vals)
                    out = []
                    while not vr.eof():
                        _t, v = vr.read_tlv()
                        out.append(v.decode(errors="replace"))
                    attrs_out[name.decode()] = out
                found = (dn.decode(), attrs_out)
            elif op_tag == 0x65:  # SearchResultDone
                return found
            elif op_tag == 0x64:
                continue  # further entries: first wins
            else:
                raise LdapError(
                    f"unexpected search reply tag {op_tag:#x}")


class LdapProvider:
    """ldap_provider.go Authenticate: resolve DN, bind with the user's
    password, map attributes -> identity."""

    def __init__(self, host: str, port: int = 389,
                 base_dn: str = "",
                 user_dn_template: str = "",      # e.g. uid={},ou=...
                 bind_dn: str = "", bind_password: str = "",
                 user_attr: str = "uid",
                 attr_map: "dict[str, str] | None" = None,
                 use_tls: bool = False, tls_verify: bool = True):
        self.host, self.port = host, port
        self.use_tls = use_tls
        self.tls_verify = tls_verify
        self.base_dn = base_dn
        self.user_dn_template = user_dn_template
        self.bind_dn = bind_dn
        self.bind_password = bind_password
        self.user_attr = user_attr
        # identity field -> ldap attribute
        self.attr_map = attr_map or {"displayName": "cn",
                                     "email": "mail"}

    def authenticate(self, username: str, password: str
                     ) -> "dict | None":
        """None on bad credentials; raises LdapError on server
        problems (callers must not treat an outage as a rejection)."""
        if not password:
            return None  # RFC 4513: empty password would be an
            # unauthenticated bind that "succeeds"
        c = LdapClient(self.host, self.port, use_tls=self.use_tls,
                       tls_verify=self.tls_verify)
        try:
            if self.user_dn_template:
                dn = self.user_dn_template.replace("{}", username)
                attrs: dict = {}
            else:
                # service bind, then locate the user's entry
                if self.bind_dn and not c.bind(self.bind_dn,
                                               self.bind_password):
                    raise LdapError("service bind rejected")
                hit = c.search_one(self.base_dn, self.user_attr,
                                   username,
                                   list(self.attr_map.values()))
                if hit is None:
                    return None
                dn, attrs = hit
            if not c.bind(dn, password):
                return None
            ident = {"name": username, "dn": dn}
            for field, attr in self.attr_map.items():
                if attrs.get(attr):
                    ident[field] = attrs[attr][0]
            return ident
        finally:
            c.close()


# -- test/dev server ------------------------------------------------------

class MiniLdapServer:
    """A tiny LDAP v3 server for tests and air-gapped dev: a DN ->
    (password, attrs) table, simple bind + equality subtree search —
    enough to exercise every code path of the provider against a real
    socket (the role the reference's docker'd openldap plays in its
    integration tests)."""

    def __init__(self, users: "dict[str, tuple[str, dict]]",
                 host: str = "127.0.0.1", port: int = 0):
        self.users = users
        self.sock = socket.socket()
        self.sock.setsockopt(socket.SOL_SOCKET,
                             socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "MiniLdapServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self.sock.close()
        except OSError:
            pass

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _result(self, tag: int, code: int) -> bytes:
        return tlv(tag, ber_int(code, tag=0x0A) + ber_str("") +
                   ber_str(""))

    def _serve(self, conn) -> None:
        f = conn.makefile("rb")
        bound_dn = ""
        try:
            while True:
                mid, op_tag, body = read_message(f)
                if op_tag == 0x60:  # bind
                    r = BerReader(body)
                    r.read_tlv()               # version
                    _t, dn = r.read_tlv()
                    _t, pw = r.read_tlv()
                    dn_s, pw_s = dn.decode(), pw.decode()
                    rec = self.users.get(dn_s)
                    ok = rec is not None and pw_s and rec[0] == pw_s
                    code = 0 if ok else 49
                    if ok:
                        bound_dn = dn_s
                    conn.sendall(ber_seq(
                        ber_int(mid) + self._result(0x61, code)))
                elif op_tag == 0x63:  # search
                    r = BerReader(body)
                    _t, base = r.read_tlv()
                    r.read_tlv(); r.read_tlv()  # scope, deref
                    r.read_tlv(); r.read_tlv()  # size, time
                    r.read_tlv()               # typesOnly
                    ftag, fbody = r.read_tlv()
                    if ftag == 0xA3:
                        fr = BerReader(fbody)
                        _t, fattr = fr.read_tlv()
                        _t, fval = fr.read_tlv()
                        for dn_s, (_pw, attrs) in self.users.items():
                            if not dn_s.endswith(base.decode()):
                                continue
                            vals = attrs.get(fattr.decode(), [])
                            if fval.decode() not in vals:
                                continue
                            attr_body = b"".join(
                                ber_seq(ber_str(a) + tlv(0x31, b"".join(
                                    ber_str(v) for v in vs)))
                                for a, vs in attrs.items())
                            conn.sendall(ber_seq(ber_int(mid) + tlv(
                                0x64, ber_str(dn_s) +
                                ber_seq(attr_body))))
                            break
                    conn.sendall(ber_seq(
                        ber_int(mid) + self._result(0x65, 0)))
                elif op_tag == 0x42:  # unbind
                    return
        except OSError:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass
