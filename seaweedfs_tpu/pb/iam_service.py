"""gRPC IAM services — wire-compatible with the reference IAM API
(/root/reference/weed/pb/iam.proto SeaweedIdentityAccessManagement,
served by the filer there: filer_server_handlers_iam_grpc.go) and the
filer->s3 cache propagation service (s3.proto SeaweedS3IamCache).

Both operate the same IdentityStore the REST IAM API and the S3
gateway authenticate against, so a user created over gRPC can sign S3
requests immediately.
"""

from __future__ import annotations

import grpc

from ..iam.identity import Account, Credential, Identity
from . import iam_pb2 as ipb
from .rpc import make_service_handler, serve

IAM_SERVICE = "iam_pb.SeaweedIdentityAccessManagement"
IAM_METHODS = {
    "GetConfiguration": ("uu", ipb.GetConfigurationRequest,
                         ipb.GetConfigurationResponse),
    "PutConfiguration": ("uu", ipb.PutConfigurationRequest,
                         ipb.PutConfigurationResponse),
    "CreateUser": ("uu", ipb.CreateUserRequest, ipb.CreateUserResponse),
    "GetUser": ("uu", ipb.GetUserRequest, ipb.GetUserResponse),
    "UpdateUser": ("uu", ipb.UpdateUserRequest, ipb.UpdateUserResponse),
    "DeleteUser": ("uu", ipb.DeleteUserRequest, ipb.DeleteUserResponse),
    "ListUsers": ("uu", ipb.ListUsersRequest, ipb.ListUsersResponse),
    "CreateAccessKey": ("uu", ipb.CreateAccessKeyRequest,
                        ipb.CreateAccessKeyResponse),
    "DeleteAccessKey": ("uu", ipb.DeleteAccessKeyRequest,
                        ipb.DeleteAccessKeyResponse),
    "GetUserByAccessKey": ("uu", ipb.GetUserByAccessKeyRequest,
                           ipb.GetUserByAccessKeyResponse),
    "PutPolicy": ("uu", ipb.PutPolicyRequest, ipb.PutPolicyResponse),
    "GetPolicy": ("uu", ipb.GetPolicyRequest, ipb.GetPolicyResponse),
    "ListPolicies": ("uu", ipb.ListPoliciesRequest,
                     ipb.ListPoliciesResponse),
    "DeletePolicy": ("uu", ipb.DeletePolicyRequest,
                     ipb.DeletePolicyResponse),
}

S3_CACHE_SERVICE = "messaging_pb.SeaweedS3IamCache"
S3_CACHE_METHODS = {
    "PutIdentity": ("uu", ipb.PutIdentityRequest,
                    ipb.PutIdentityResponse),
    "RemoveIdentity": ("uu", ipb.RemoveIdentityRequest,
                       ipb.RemoveIdentityResponse),
    "PutPolicy": ("uu", ipb.PutPolicyRequest, ipb.PutPolicyResponse),
    "GetPolicy": ("uu", ipb.GetPolicyRequest, ipb.GetPolicyResponse),
    "ListPolicies": ("uu", ipb.ListPoliciesRequest,
                     ipb.ListPoliciesResponse),
    "DeletePolicy": ("uu", ipb.DeletePolicyRequest,
                     ipb.DeletePolicyResponse),
    "PutGroup": ("uu", ipb.PutGroupRequest, ipb.PutGroupResponse),
    "RemoveGroup": ("uu", ipb.RemoveGroupRequest,
                    ipb.RemoveGroupResponse),
}


def identity_to_pb(ident: Identity) -> ipb.Identity:
    out = ipb.Identity(name=ident.name, disabled=ident.disabled)
    for c in ident.credentials:
        out.credentials.add(access_key=c.access_key,
                            secret_key=c.secret_key, status=c.status)
    out.actions.extend(ident.actions)
    out.account.id = ident.account.id
    out.account.display_name = ident.account.display_name
    out.account.email_address = ident.account.email
    out.policy_names.extend(sorted(ident.policies))
    return out


def identity_from_pb(p: ipb.Identity) -> Identity:
    account = None
    if p.HasField("account") and p.account.id:
        account = Account(p.account.id, p.account.display_name,
                          p.account.email_address)
    return Identity(
        p.name,
        [Credential(c.access_key, c.secret_key,
                    c.status or "Active") for c in p.credentials],
        list(p.actions), account, p.disabled)


def _preserve_inline_policies(old: Identity, new: Identity) -> None:
    """The iam_pb.Identity wire shape carries policy NAMES only, not
    documents — a gRPC get-modify-put of an existing user must not
    wipe its inline policy docs (REST PutUserPolicy) or bake their
    derived actions into the static set forever (the revocability
    hazard identity.py's migration comment documents)."""
    new.policies = dict(old.policies)
    if new.policies:
        try:
            from ..iam.iamapi import IamError, policy_to_actions
            derived = set()
            for doc in new.policies.values():
                derived.update(policy_to_actions(doc))
            new.static_actions = [a for a in new.actions
                                  if a not in derived]
        except (IamError, AttributeError, KeyError, TypeError,
                ValueError):
            pass     # undecodable legacy doc: keep all static


class _PolicyMixin:
    """PutPolicy/GetPolicy/ListPolicies/DeletePolicy are identical in
    both services (same request/response types, same store)."""

    def PutPolicy(self, request, context):
        self.store.put_policy(request.name, request.content)
        return ipb.PutPolicyResponse()

    def GetPolicy(self, request, context):
        content = self.store.get_policy(request.name)
        if content is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"policy {request.name!r} not found")
        return ipb.GetPolicyResponse(name=request.name,
                                     content=content)

    def ListPolicies(self, request, context):
        out = ipb.ListPoliciesResponse()
        for name, content in sorted(self.store.list_policies().items()):
            out.policies.add(name=name, content=content)
        return out

    def DeletePolicy(self, request, context):
        self.store.delete_policy(request.name)
        return ipb.DeletePolicyResponse()


class IamServicer(_PolicyMixin):
    """iam_pb.SeaweedIdentityAccessManagement over an IdentityStore."""

    def __init__(self, store):
        self.store = store

    def GetConfiguration(self, request, context):
        out = ipb.GetConfigurationResponse()
        for ident in self.store:
            out.configuration.identities.append(identity_to_pb(ident))
        for name, content in sorted(self.store.list_policies().items()):
            out.configuration.policies.add(name=name, content=content)
        for name, g in sorted(self.store.list_groups().items()):
            out.configuration.groups.add(
                name=name, members=g.get("members", []),
                policy_names=g.get("policyNames", []),
                disabled=g.get("disabled", False))
        return out

    def PutConfiguration(self, request, context):
        """Full-config replace (credential_store shape): swap the
        identity set, policies AND groups atomically via load_json —
        a Get -> Put round-trip must be lossless."""
        doc = {"identities": [], "policies": {}, "groups": {}}
        for p in request.configuration.identities:
            doc["identities"].append(identity_from_pb(p).to_json())
        for pol in request.configuration.policies:
            doc["policies"][pol.name] = pol.content
        for g in request.configuration.groups:
            doc["groups"][g.name] = {
                "members": list(g.members),
                "policyNames": list(g.policy_names),
                "disabled": g.disabled}
        self.store.load_json(doc)
        self.store.save()
        return ipb.PutConfigurationResponse()

    def CreateUser(self, request, context):
        name = request.identity.name
        if not name:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "identity.name required")
        if self.store.get(name) is not None:
            context.abort(grpc.StatusCode.ALREADY_EXISTS,
                          f"user {name!r} exists")
        self.store.put(identity_from_pb(request.identity))
        return ipb.CreateUserResponse()

    def GetUser(self, request, context):
        ident = self.store.get(request.username)
        if ident is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"user {request.username!r} not found")
        return ipb.GetUserResponse(identity=identity_to_pb(ident))

    def UpdateUser(self, request, context):
        old = self.store.get(request.username)
        if old is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"user {request.username!r} not found")
        new = identity_from_pb(request.identity)
        _preserve_inline_policies(old, new)
        if request.username != new.name:
            # rename: drop the old record so credentials re-index
            self.store.delete(request.username)
        self.store.put(new)
        return ipb.UpdateUserResponse()

    def DeleteUser(self, request, context):
        self.store.delete(request.username)
        return ipb.DeleteUserResponse()

    def ListUsers(self, request, context):
        return ipb.ListUsersResponse(
            usernames=sorted(i.name for i in self.store))

    def CreateAccessKey(self, request, context):
        ident = self.store.get(request.username)
        if ident is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"user {request.username!r} not found")
        c = request.credential
        ident.credentials.append(Credential(
            c.access_key, c.secret_key, c.status or "Active"))
        self.store.put(ident)
        return ipb.CreateAccessKeyResponse()

    def DeleteAccessKey(self, request, context):
        ident = self.store.get(request.username)
        if ident is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"user {request.username!r} not found")
        before = len(ident.credentials)
        ident.credentials = [c for c in ident.credentials
                             if c.access_key != request.access_key]
        if len(ident.credentials) != before:
            # re-index through delete+put so the stale key lookup dies
            self.store.delete(ident.name)
            self.store.put(ident)
        return ipb.DeleteAccessKeyResponse()

    def GetUserByAccessKey(self, request, context):
        ident = self.store.by_access_key(request.access_key)
        if ident is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no user holds key {request.access_key!r}")
        return ipb.GetUserByAccessKeyResponse(
            identity=identity_to_pb(ident))


class S3IamCacheServicer(_PolicyMixin):
    """messaging_pb.SeaweedS3IamCache over the S3 gateway's
    IdentityStore (unidirectional filer -> s3 propagation: a filer
    pushes identity/policy/group changes into the gateway's live
    auth state without a restart)."""

    def __init__(self, store):
        self.store = store

    def PutIdentity(self, request, context):
        new = identity_from_pb(request.identity)
        old = self.store.get(new.name)
        if old is not None:
            _preserve_inline_policies(old, new)
        self.store.put(new)
        return ipb.PutIdentityResponse()

    def RemoveIdentity(self, request, context):
        self.store.delete(request.username)
        return ipb.RemoveIdentityResponse()

    def PutGroup(self, request, context):
        g = request.group
        self.store.put_group(g.name, {
            "members": list(g.members),
            "policyNames": list(g.policy_names),
            "disabled": g.disabled})
        return ipb.PutGroupResponse()

    def RemoveGroup(self, request, context):
        self.store.delete_group(request.group_name)
        return ipb.RemoveGroupResponse()


def start_iam_grpc(store, host: str = "127.0.0.1", port: int = 0):
    return serve([make_service_handler(IAM_SERVICE, IAM_METHODS,
                                       IamServicer(store),
                                       role="iam")],
                 host=host, port=port)


def start_s3_cache_grpc(store, host: str = "127.0.0.1", port: int = 0):
    return serve([make_service_handler(S3_CACHE_SERVICE,
                                       S3_CACHE_METHODS,
                                       S3IamCacheServicer(store),
                                       role="s3")],
                 host=host, port=port)
