"""gRPC mount control — wire-compatible with the reference's local
mount API (/root/reference/weed/pb/mount.proto SeaweedMount): a
running FUSE mount serves Configure so an operator can adjust the
collection capacity quota without remounting (`weed mount.configure`
drives this in the reference)."""

from __future__ import annotations

from . import mount_pb2 as mpb
from .rpc import make_service_handler, serve

MOUNT_SERVICE = "messaging_pb.SeaweedMount"
MOUNT_METHODS = {
    "Configure": ("uu", mpb.ConfigureRequest, mpb.ConfigureResponse),
}


class MountServicer:
    def __init__(self, weedfs):
        self.weedfs = weedfs

    def Configure(self, request, context):
        # takes effect on the next quota check (weedfs_quota.go role);
        # setting 0 lifts the limit
        self.weedfs.collection_capacity = request.collection_capacity
        self.weedfs._quota_checked = 0.0    # force a fresh poll
        return mpb.ConfigureResponse()


def start_mount_grpc(weedfs, host: str = "127.0.0.1", port: int = 0):
    return serve([make_service_handler(MOUNT_SERVICE, MOUNT_METHODS,
                                       MountServicer(weedfs))],
                 host=host, port=port)
