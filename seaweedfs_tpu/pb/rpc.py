"""Shared gRPC plumbing: generic-handler service builder + stub factory.

grpc_tools (the *_pb2_grpc.py generator) is not in the image, so the
method-handler tables are built by hand from the generated message
classes — the same objects the generated code would produce
(pb/grpc_client_server.go:34 is the reference analog of this dial/serve
funnel).  A method spec is (kind, request_cls, response_cls) where kind
is one of "uu", "us", "su", "ss" (unary/stream request x response).
"""

from __future__ import annotations

import json
from concurrent import futures

import grpc

from .. import faults as _faults
from ..util import deadline as _udeadline
from ..util import retry as _uretry

_KIND_TO_HANDLER = {
    "uu": grpc.unary_unary_rpc_method_handler,
    "us": grpc.unary_stream_rpc_method_handler,
    "su": grpc.stream_unary_rpc_method_handler,
    "ss": grpc.stream_stream_rpc_method_handler,
}


def _adopt_trace(context) -> "tuple[str, str]":
    """Adopt the caller's request id + trace parent from invocation
    metadata (the gRPC twin of the httpd middleware's header adoption;
    tracing.py) — returns (trace id, parent span id) for the server
    span."""
    from .. import tracing
    from ..util.request_id import ensure_request_id
    rid = tp = ""
    for k, v in context.invocation_metadata() or ():
        lk = k.lower()
        if lk == "x-request-id":
            rid = v
        elif lk == tracing.GRPC_METADATA_KEY:
            tp = v
    rid = ensure_request_id(rid)
    _, parent = tracing.parse_traceparent(tp)
    return rid, parent


def _adopt_deadline(context) -> "_udeadline.Deadline | None":
    """gRPC ingress half of the deadline plane (util/deadline): the
    wire already carries the budget as `grpc-timeout` (the client
    stub's `timeout=` kwarg), surfaced here as
    `context.time_remaining()` — adopt it into the contextvar so the
    servicer's outbound hops (HTTP and gRPC alike) inherit the
    shrinking budget.  Always binds (None included): executor threads
    are reused across RPCs."""
    rem = None
    try:
        rem = context.time_remaining()
    except Exception:  # noqa: BLE001 — a context without deadline
        rem = None     # support must not fail the RPC
    if rem is not None and rem > 1e6:
        # grpc encodes "no deadline" as a far-future int64 expiry on
        # some transports; ~11 days of budget means nobody is waiting
        rem = None
    return _udeadline.adopt_budget(rem, site="grpc")


def _traced_method(service_name: str, name: str, kind: str, fn,
                   role: str):
    """Wrap one servicer method in a server span.  Response-streaming
    methods return a generator — the span must stay open until the
    stream is exhausted, so those get a generator wrapper instead of a
    plain with-block."""
    from .. import tracing

    if kind in ("uu", "su"):
        def unary(request, context):
            rid, parent = _adopt_trace(context)
            dl = _adopt_deadline(context)
            with tracing.span(f"{service_name}/{name}", role=role,
                              parent=parent, trace_id=rid) as sp:
                if dl is not None and dl.expired():
                    # fail fast before the servicer queues any work —
                    # the gRPC twin of the HTTP fronts' 504
                    _udeadline.note_exceeded("grpc.ingress")
                    context.abort(
                        grpc.StatusCode.DEADLINE_EXCEEDED,
                        "deadline exceeded before dispatch")
                try:
                    return fn(request, context)
                except BaseException as e:
                    sp.set_error(e)
                    raise
        return unary

    def streaming(request, context):
        rid, parent = _adopt_trace(context)
        dl = _adopt_deadline(context)
        sp = tracing.start_span(f"{service_name}/{name}", role=role,
                                parent=parent, trace_id=rid)
        try:
            if dl is not None and dl.expired():
                _udeadline.note_exceeded("grpc.ingress")
                context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                              "deadline exceeded before dispatch")
            yield from fn(request, context)
        except BaseException as e:
            sp.set_error(e)
            raise
        finally:
            sp.finish()
    return streaming


def make_service_handler(service_name: str, methods: dict,
                         servicer, role: str = "") -> grpc.GenericRpcHandler:
    """methods: {method_name: (kind, req_cls, resp_cls)}; servicer must
    have a callable per method name.  `role` labels the server spans
    the wrapper opens around every method (tracing.py)."""
    table = {}
    for name, (kind, req_cls, resp_cls) in methods.items():
        table[name] = _KIND_TO_HANDLER[kind](
            _traced_method(service_name, name, kind,
                           getattr(servicer, name), role),
            request_deserializer=req_cls.FromString,
            response_serializer=resp_cls.SerializeToString)
    return grpc.method_handlers_generic_handler(service_name, table)


def serve(handlers, host: str = "127.0.0.1", port: int = 0,
          max_workers: int = 16) -> "tuple[grpc.Server, int]":
    """Start an insecure gRPC server with the given generic handlers on
    an ephemeral (or fixed) port; returns (server, bound_port)."""
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[("grpc.max_receive_message_length", 64 << 20),
                 ("grpc.max_send_message_length", 64 << 20)])
    for h in handlers:
        server.add_generic_rpc_handlers((h,))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.start()
    return server, bound


class StubFaultInjected(_faults.FaultInjected, grpc.RpcError):
    """An armed `rpc.stub.call` fault.  Subclasses BOTH the
    robustness plane's OSError (transport-failure handlers: retry,
    failover, unwind) and grpc.RpcError — every gRPC call site's
    `except grpc.RpcError` keeps working when the failure is injected
    instead of coming off the wire."""


class StubBreakerOpen(_uretry.BreakerOpen, grpc.RpcError):
    """Fail-fast breaker refusal on the stub plane; same dual typing
    as StubFaultInjected, and still a BreakerOpen so re-planning
    callers catch it specifically."""


class StubDeadlineExceeded(_udeadline.DeadlineExceeded, grpc.RpcError):
    """The request budget is spent before the call could even be
    dialed.  Dual-typed like its siblings: `except grpc.RpcError`
    sites keep working, and the deadline plane's handlers (retry's
    no-re-issue rule) see the DeadlineExceeded they expect."""


def _with_trace_metadata(multicallable, peer: str = ""):
    """Attach the active request id + trace parent as invocation
    metadata on every call (the gRPC twin of _pooled_request's header
    forwarding) — explicit caller metadata still wins.  When the stub
    was built with a `peer` address, every call also consults that
    peer's circuit breaker (util/retry) and feeds transport verdicts
    back: UNAVAILABLE / DEADLINE_EXCEEDED count as peer failures,
    anything else (including application-level aborts) proves the
    peer alive.  Response-streaming calls record only call setup —
    mid-stream deaths surface on iteration, outside this wrapper."""
    def call(request, **kwargs):
        from .. import tracing
        from ..util.request_id import get_request_id
        # deadline plane, outbound — checked FIRST (before the fault
        # hook or the breaker admits this caller as a half-open probe,
        # which a refusal here would otherwise strand): the contextvar
        # budget becomes the call's grpc-timeout (the native wire
        # encoding — the server wrapper reads it back via
        # context.time_remaining()).  An explicit caller timeout= wins
        # but is still capped by the budget; an expired budget refuses
        # the call before dialing.
        rem = _udeadline.remaining()
        if rem is not None:
            if rem <= 0.0:
                _udeadline.note_exceeded("rpc.stub")
                raise StubDeadlineExceeded("rpc.stub")
            explicit = kwargs.get("timeout")
            kwargs["timeout"] = rem if explicit is None \
                else min(float(explicit), rem)
        try:
            _faults.fire("rpc.stub.call", key=peer)
        except _faults.FaultInjected as e:
            raise StubFaultInjected(str(e)) from None
        if peer:
            try:
                _uretry.check_peer(peer)
            except _uretry.BreakerOpen as e:
                raise StubBreakerOpen(e.peer, e.retry_after) from None
        md = list(kwargs.pop("metadata", ()) or ())
        have = {k.lower() for k, _ in md}
        rid = get_request_id()
        if rid and "x-request-id" not in have:
            md.append(("x-request-id", rid))
        tp = tracing.traceparent_header()
        if tp and tracing.GRPC_METADATA_KEY not in have:
            md.append((tracing.GRPC_METADATA_KEY, tp))
        if md:
            kwargs["metadata"] = md
        try:
            result = multicallable(request, **kwargs)
        except grpc.RpcError as e:
            if peer:
                code = None
                if hasattr(e, "code"):
                    try:
                        code = e.code()
                    except Exception:  # noqa: BLE001 — peer verdict
                        # only; the RpcError itself still propagates
                        code = None
                if code in (grpc.StatusCode.UNAVAILABLE,
                            grpc.StatusCode.DEADLINE_EXCEEDED):
                    _uretry.record_failure(peer, repr(e))
                else:
                    _uretry.record_success(peer)
            raise
        except BaseException:
            # non-RpcError failure (channel closed ValueError,
            # serialization TypeError): no peer verdict, but return a
            # held half-open probe slot so the breaker can't wedge
            if peer:
                _uretry.probe_release(peer)
            raise
        if peer:
            _uretry.record_success(peer)
        return result
    return call


class Stub:
    """Client stub over one service: attribute access returns the bound
    callable for a method (multi-callable with the right serializers),
    mirroring what a generated *_pb2_grpc Stub exposes.  Every call
    carries the active request id + trace parent as metadata; pass
    `peer` (the dialed host:port) to route calls through that peer's
    circuit breaker — the gRPC plane then shares the HTTP funnel's
    health map instead of independently hammering a dead server."""

    def __init__(self, channel: grpc.Channel, service_name: str,
                 methods: dict, peer: str = ""):
        self._factories = {
            "uu": channel.unary_unary, "us": channel.unary_stream,
            "su": channel.stream_unary, "ss": channel.stream_stream}
        for name, (kind, req_cls, resp_cls) in methods.items():
            setattr(self, name, _with_trace_metadata(
                self._factories[kind](
                    f"/{service_name}/{name}",
                    request_serializer=req_cls.SerializeToString,
                    response_deserializer=resp_cls.FromString),
                peer=peer))


class LocalRequest:
    """Adapter so gRPC servicers reuse the HTTP route handlers (single
    implementation of every operation; the wire codec is the only
    difference between the planes)."""

    def __init__(self, query: dict | None = None,
                 payload: dict | None = None, path: str = "/",
                 headers: dict | None = None,
                 remote_ip: str = "127.0.0.1"):
        self.method = "LOCAL"
        self.path = path
        self.remote_ip = remote_ip
        self.query = {k: str(v) for k, v in (query or {}).items()}
        self.headers: dict = headers or {}
        self._payload = payload if payload is not None else {}

    def json(self) -> dict:
        return self._payload

    @property
    def body(self) -> bytes:
        return json.dumps(self._payload).encode()

    def stream_body(self, chunk_size: int = 4 << 20):
        yield self.body

    def drain(self, max_drain: int = 0) -> None:
        pass


_STATUS_TO_GRPC = {
    400: grpc.StatusCode.INVALID_ARGUMENT,
    401: grpc.StatusCode.UNAUTHENTICATED,
    403: grpc.StatusCode.PERMISSION_DENIED,
    404: grpc.StatusCode.NOT_FOUND,
    409: grpc.StatusCode.FAILED_PRECONDITION,
}


def peer_ip(context) -> str:
    """Client IP from the grpc peer string ("ipv4:1.2.3.4:567",
    "ipv6:[::1]:567")."""
    peer = context.peer() or ""
    if peer.startswith("ipv4:"):
        return peer[5:].rsplit(":", 1)[0]
    if peer.startswith("ipv6:"):
        return peer[5:].rsplit(":", 1)[0].strip("[]")
    return "127.0.0.1"


def guarded(context, server, path: str, query: dict | None = None,
            payload: dict | None = None) -> LocalRequest:
    """Build a LocalRequest carrying the LOGICAL http path + the
    caller's credentials (authorization metadata) and run the server's
    HTTP guard over it, so the gRPC plane enforces exactly the same
    admin-JWT and leader-lease rules as the HTTP plane
    (grpc_client_server.go applies the security config to every dial;
    an unguarded gRPC port would let anyone delete volumes or depose
    topology that HTTP protects).  Aborts the RPC on denial."""
    headers = {}
    for k, v in context.invocation_metadata() or ():
        if k.lower() == "authorization":
            headers["Authorization"] = v
    req = LocalRequest(query=query, payload=payload, path=path,
                       headers=headers, remote_ip=peer_ip(context))
    guard = getattr(server, "_guard", None)
    denied = guard(req) if guard is not None else None
    if denied is not None:
        check_status(context, denied[0], denied[1])
    return req


def check_status(context, status: int, payload) -> dict:
    """Map an HTTP-route (status, payload) result onto gRPC semantics:
    2xx passes the payload dict through, anything else aborts with the
    closest status code and the route's error message."""
    if 200 <= status < 300:
        return payload if isinstance(payload, dict) else {}
    msg = payload.get("error", str(payload)) \
        if isinstance(payload, dict) else str(payload)
    context.abort(_STATUS_TO_GRPC.get(status, grpc.StatusCode.INTERNAL),
                  msg)
