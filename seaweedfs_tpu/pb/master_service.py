"""gRPC Seaweed (master) service — wire-compatible with
/root/reference/weed/pb/master.proto (see protos/master.proto).

Every RPC bridges to the same code the JSON-HTTP routes run
(rpc.LocalRequest), so the two planes can never drift; the gRPC layer
only translates protobuf <-> the route dicts.
"""

from __future__ import annotations

import time

from . import master_pb2 as pb
from .rpc import (Stub, check_status, guarded, make_service_handler,
                  serve)

SERVICE = "master_pb.Seaweed"

METHODS = {
    "SendHeartbeat": ("ss", pb.Heartbeat, pb.HeartbeatResponse),
    "KeepConnected": ("ss", pb.KeepConnectedRequest,
                      pb.KeepConnectedResponse),
    "LookupVolume": ("uu", pb.LookupVolumeRequest,
                     pb.LookupVolumeResponse),
    "LookupEcVolume": ("uu", pb.LookupEcVolumeRequest,
                       pb.LookupEcVolumeResponse),
    "Assign": ("uu", pb.AssignRequest, pb.AssignResponse),
    "Statistics": ("uu", pb.StatisticsRequest, pb.StatisticsResponse),
    "CollectionList": ("uu", pb.CollectionListRequest,
                       pb.CollectionListResponse),
    "VolumeGrow": ("uu", pb.VolumeGrowRequest, pb.VolumeGrowResponse),
    "VolumeList": ("uu", pb.VolumeListRequest, pb.VolumeListResponse),
    "Ping": ("uu", pb.PingRequest, pb.PingResponse),
}


class MasterServicer:
    def __init__(self, master):
        self.master = master

    # -- streams -------------------------------------------------------

    def SendHeartbeat(self, request_iterator, context):
        """master_grpc_server.go SendHeartbeat: each Heartbeat message
        re-registers the node's full volume state; replies carry the
        size limit + leader hint.  Runs the same admin-JWT + leader
        guard as POST /heartbeat — an unauthenticated gRPC heartbeat
        would let an outsider inject topology."""
        for hb in request_iterator:
            payload = {
                "ip": hb.ip, "port": hb.port,
                "publicUrl": hb.public_url or f"{hb.ip}:{hb.port}",
                "dataCenter": hb.data_center, "rack": hb.rack,
                # reference Heartbeat carries per-disk-type slot counts
                # (map field 4); our topology tracks one total.
                "maxVolumeCount": sum(hb.max_volume_counts.values()),
                "maxFileKey": hb.max_file_key,
                "volumes": [{
                    "id": v.id, "collection": v.collection,
                    "size": v.size, "fileCount": v.file_count,
                    "deleteCount": v.delete_count,
                    "deletedByteCount": v.deleted_byte_count,
                    "readOnly": v.read_only,
                    "replicaPlacement": v.replica_placement,
                    "ttl": v.ttl, "version": v.version,
                } for v in hb.volumes],
                "ecShards": [{
                    "id": e.id, "collection": e.collection,
                    "ecIndexBits": e.ec_index_bits,
                } for e in hb.ec_shards],
            }
            req = guarded(context, self.master, "/heartbeat",
                          payload=payload)
            status, resp = self.master._heartbeat(req)
            out = check_status(context, status, resp)
            yield pb.HeartbeatResponse(
                volume_size_limit=out.get("volumeSizeLimit", 0),
                leader=out.get("leader") or "")

    def KeepConnected(self, request_iterator, context):
        """masterclient.go:417: after the greeting, push leadership and
        volume-location deltas until the client hangs up.  The first
        responses replay a full topology snapshot (a reconnecting
        client rebuilds its vid map from it).  The hub cursor is read
        BEFORE the snapshot, so deltas published while the snapshot
        streams are delivered right after it — duplicates are harmless
        (vid-map adds are idempotent), gaps are not."""
        try:
            next(iter(request_iterator))  # the client greeting
        except StopIteration:
            return
        m = self.master
        guarded(context, m, "/cluster/watch")
        cursor = m.hub.cursor
        yield pb.KeepConnectedResponse(volume_location=pb.VolumeLocation(
            leader=m.raft.leader or m.url))
        for node in m.topology.alive_nodes():
            vids, ec_vids = m._node_vid_sets(node.url)
            yield pb.KeepConnectedResponse(
                volume_location=pb.VolumeLocation(
                    url=node.url, public_url=node.public_url,
                    new_vids=sorted(vids),
                    new_ec_vids=sorted(ec_vids)))
        while context.is_active():
            events, cursor, lagged = m.hub.events_since(cursor,
                                                        timeout=0.5)
            if lagged:
                return  # force the client to reconnect + resnapshot
            for ev in events:
                if "leader" in ev:
                    yield pb.KeepConnectedResponse(
                        volume_location=pb.VolumeLocation(
                            leader=ev["leader"]))
                    continue
                yield pb.KeepConnectedResponse(
                    volume_location=pb.VolumeLocation(
                        url=ev["url"], public_url=ev["publicUrl"],
                        new_vids=ev["newVids"],
                        deleted_vids=ev["deletedVids"],
                        new_ec_vids=ev["newEcVids"],
                        deleted_ec_vids=ev["deletedEcVids"]))

    # -- unary ---------------------------------------------------------

    def Assign(self, request, context):
        req = guarded(context, self.master, "/dir/assign", query={
            "count": request.count or 1,
            "collection": request.collection,
            "replication": request.replication or
            self.master.default_replication,
            "ttl": request.ttl,
        })
        status, resp = self.master._assign(req)
        out = check_status(context, status, resp)
        return pb.AssignResponse(
            fid=out["fid"], count=out.get("count", 1),
            auth=out.get("auth", ""),
            location=pb.Location(url=out["url"],
                                 public_url=out["publicUrl"]),
            replicas=[pb.Location(url=r["url"],
                                  public_url=r["publicUrl"])
                      for r in out.get("replicas", [])])

    def LookupVolume(self, request, context):
        out = pb.LookupVolumeResponse()
        for vf in request.volume_or_file_ids:
            status, resp = self.master._lookup(
                guarded(context, self.master, "/dir/lookup",
                        query={"volumeId": vf}))
            loc = out.volume_id_locations.add(volume_or_file_id=vf)
            if status != 200:
                loc.error = resp.get("error", f"HTTP {status}") \
                    if isinstance(resp, dict) else str(resp)
                continue
            for entry in resp["locations"]:
                loc.locations.add(url=entry["url"],
                                  public_url=entry["publicUrl"])
        return out

    def LookupEcVolume(self, request, context):
        status, resp = self.master._ec_lookup(
            guarded(context, self.master, "/dir/ec_lookup",
                    query={"volumeId": request.volume_id}))
        out = check_status(context, status, resp)
        r = pb.LookupEcVolumeResponse(volume_id=request.volume_id)
        by_shard: dict[int, list[str]] = {}
        for entry in out.get("shardIdLocations", []):
            for sid in entry["shardIds"]:
                by_shard.setdefault(sid, []).append(entry["url"])
        for sid in sorted(by_shard):
            loc = r.shard_id_locations.add(shard_id=sid)
            for url in by_shard[sid]:
                loc.locations.add(url=url, public_url=url)
        return r

    def Statistics(self, request, context):
        guarded(context, self.master, "/dir/status")
        t = self.master.topology
        total = used = files = 0
        with t.lock:
            for node in t.nodes.values():
                for v in node.volumes.values():
                    if request.collection and \
                            v.collection != request.collection:
                        continue
                    used += v.size
                    files += v.file_count
            total = t.volume_size_limit * max(
                sum(n.max_volume_count for n in t.nodes.values()), 1)
        return pb.StatisticsResponse(total_size=total, used_size=used,
                                     file_count=files)

    def CollectionList(self, request, context):
        guarded(context, self.master, "/vol/list")
        t = self.master.topology
        names = set()
        # no flags set = list normal volumes (the common default)
        want_normal = request.include_normal_volumes or \
            not request.include_ec_volumes
        with t.lock:
            for node in t.nodes.values():
                if want_normal:
                    names.update(v.collection
                                 for v in node.volumes.values())
                if request.include_ec_volumes:
                    names.update(e.collection
                                 for e in node.ec_shards.values())
        return pb.CollectionListResponse(
            collections=[pb.Collection(name=n) for n in sorted(names)])

    def VolumeGrow(self, request, context):
        req = guarded(context, self.master, "/vol/grow", payload={
            "collection": request.collection,
            "replication": request.replication or
            self.master.default_replication,
            "ttl": request.ttl,
            "count": request.writable_volume_count or 1,
        })
        status, resp = self.master._vol_grow(req)
        check_status(context, status, resp)
        return pb.VolumeGrowResponse()

    def VolumeList(self, request, context):
        """master_grpc_server_volume.go VolumeList: the dc -> rack ->
        node topology tree with per-disk volume/EC inventories — the
        RPC `weed shell` opens every session with.  Our nodes are
        single-disk, so each one's whole inventory lands under the ""
        (hdd) disk type, exactly how the reference reports an untyped
        disk."""
        guarded(context, self.master, "/vol/list")
        t = self.master.topology
        topo = pb.TopologyInfo(id=self.master.raft.topology_id or "")
        dcs: "dict[str, pb.DataCenterInfo]" = {}
        racks: "dict[tuple[str, str], pb.RackInfo]" = {}
        with t.lock:
            limit = t.volume_size_limit
            for node in sorted(t.nodes.values(), key=lambda n: n.url):
                dc = dcs.get(node.data_center)
                if dc is None:
                    dc = topo.data_center_infos.add(id=node.data_center)
                    dcs[node.data_center] = dc
                rk = racks.get((node.data_center, node.rack))
                if rk is None:
                    rk = dc.rack_infos.add(id=node.rack)
                    racks[(node.data_center, node.rack)] = rk
                dn = rk.data_node_infos.add(id=node.url)
                di = dn.diskInfos[""]
                di.volume_count = len(node.volumes)
                di.max_volume_count = node.max_volume_count
                di.free_volume_count = node.free_space
                for v in sorted(node.volumes.values(),
                                key=lambda v: v.id):
                    if not v.read_only and v.size < limit:
                        di.active_volume_count += 1
                    di.volume_infos.add(
                        id=v.id, size=v.size, collection=v.collection,
                        file_count=v.file_count,
                        delete_count=v.delete_count,
                        deleted_byte_count=v.deleted_byte_count,
                        read_only=v.read_only,
                        replica_placement=v.replica_placement,
                        version=v.version, ttl=v.ttl)
                for e in sorted(node.ec_shards.values(),
                                key=lambda e: e.volume_id):
                    di.ec_shard_infos.add(
                        id=e.volume_id, collection=e.collection,
                        ec_index_bits=e.shard_bits)
        return pb.VolumeListResponse(
            topology_info=topo,
            volume_size_limit_mb=t.volume_size_limit // (1024 * 1024))

    def Ping(self, request, context):
        now = time.time_ns()
        return pb.PingResponse(start_time_ns=now, remote_time_ns=now,
                               stop_time_ns=time.time_ns())


def start_master_grpc(master, host: str = "127.0.0.1", port: int = 0):
    handler = make_service_handler(SERVICE, METHODS,
                                   MasterServicer(master),
                                   role="master")
    return serve([handler], host, port)


def master_stub(channel, peer: str = "") -> Stub:
    """`peer` (the dialed host:port) opts every call into that
    peer's circuit breaker (util/retry)."""
    return Stub(channel, SERVICE, METHODS, peer=peer)
