"""gRPC MQ services — wire-compatible with the reference broker API
(/root/reference/weed/pb/mq_broker.proto SeaweedMessaging) and agent
API (mq_agent.proto SeaweedMessagingAgent).

Every RPC drives the same BrokerServer/AgentServer route handlers the
JSON-HTTP plane uses (single implementation; the wire codec is the
only difference).  Offset semantics: our engine's offsets ARE tsNs
stamps (mq/logstore.py — strictly monotonic per partition), so
ts_ns, start_offset, next_offset and ack fields all carry the same
monotonic nanosecond value; resuming with `start_offset = last ts`
never skips or repeats (reads are strict `> since`).
"""

from __future__ import annotations

import base64
import itertools
import json
import threading
import time

import grpc

from . import mq_agent_pb2 as apb
from . import mq_broker_pb2 as bpb
from . import mq_schema_pb2 as spb
from .rpc import LocalRequest, make_service_handler, serve

BROKER_SERVICE = "messaging_pb.SeaweedMessaging"
BROKER_METHODS = {
    "FindBrokerLeader": ("uu", bpb.FindBrokerLeaderRequest,
                         bpb.FindBrokerLeaderResponse),
    "BalanceTopics": ("uu", bpb.BalanceTopicsRequest,
                      bpb.BalanceTopicsResponse),
    "ListTopics": ("uu", bpb.ListTopicsRequest,
                   bpb.ListTopicsResponse),
    "TopicExists": ("uu", bpb.TopicExistsRequest,
                    bpb.TopicExistsResponse),
    "ConfigureTopic": ("uu", bpb.ConfigureTopicRequest,
                       bpb.ConfigureTopicResponse),
    "LookupTopicBrokers": ("uu", bpb.LookupTopicBrokersRequest,
                           bpb.LookupTopicBrokersResponse),
    "GetTopicConfiguration": ("uu", bpb.GetTopicConfigurationRequest,
                              bpb.GetTopicConfigurationResponse),
    "ClosePublishers": ("uu", bpb.ClosePublishersRequest,
                        bpb.ClosePublishersResponse),
    "CloseSubscribers": ("uu", bpb.CloseSubscribersRequest,
                         bpb.CloseSubscribersResponse),
    "PublishMessage": ("ss", bpb.PublishMessageRequest,
                       bpb.PublishMessageResponse),
    "SubscribeMessage": ("ss", bpb.SubscribeMessageRequest,
                         bpb.SubscribeMessageResponse),
    "FetchMessage": ("uu", bpb.FetchMessageRequest,
                     bpb.FetchMessageResponse),
    "GetPartitionRangeInfo": ("uu", bpb.GetPartitionRangeInfoRequest,
                              bpb.GetPartitionRangeInfoResponse),
}

AGENT_SERVICE = "messaging_pb.SeaweedMessagingAgent"
AGENT_METHODS = {
    "StartPublishSession": ("uu", apb.StartPublishSessionRequest,
                            apb.StartPublishSessionResponse),
    "ClosePublishSession": ("uu", apb.ClosePublishSessionRequest,
                            apb.ClosePublishSessionResponse),
    "PublishRecord": ("ss", apb.PublishRecordRequest,
                      apb.PublishRecordResponse),
    "SubscribeRecord": ("ss", apb.SubscribeRecordRequest,
                        apb.SubscribeRecordResponse),
}


# -- schema_pb codecs -----------------------------------------------------

_SCALAR_TO_STR = {spb.BOOL: "bool", spb.INT32: "int32",
                  spb.INT64: "int64", spb.FLOAT: "float",
                  spb.DOUBLE: "double", spb.BYTES: "bytes",
                  spb.STRING: "string"}
_STR_TO_SCALAR = {v: k for k, v in _SCALAR_TO_STR.items()}


def record_type_from_pb(rt: spb.RecordType) -> dict:
    """schema_pb.RecordType -> our registry JSON (mq/schema.py)."""
    def conv_type(t: spb.Type):
        kind = t.WhichOneof("kind")
        if kind == "scalar_type":
            return _SCALAR_TO_STR.get(t.scalar_type, "string")
        if kind == "list_type":
            return {"list": conv_type(t.list_type.element_type)}
        if kind == "record_type":
            return {"record": record_type_from_pb(t.record_type)}
        return "string"
    return {"fields": [{"name": f.name, "type": conv_type(f.type)}
                       for f in rt.fields]}


def record_type_to_pb(rt: dict) -> spb.RecordType:
    def fill_type(t, out: spb.Type):
        if isinstance(t, str):
            out.scalar_type = _STR_TO_SCALAR.get(t, spb.STRING)
        elif isinstance(t, dict) and "list" in t:
            fill_type(t["list"], out.list_type.element_type)
        elif isinstance(t, dict) and "record" in t:
            out.record_type.CopyFrom(record_type_to_pb(t["record"]))
    out = spb.RecordType()
    for i, f in enumerate(rt.get("fields", [])):
        fld = out.fields.add(name=f.get("name", ""), field_index=i)
        fill_type(f.get("type"), fld.type)
    return out


def record_value_to_json(rv: spb.RecordValue) -> dict:
    """RecordValue -> the JSON form our broker schema-validates
    (bytes values become base64 text, mq/schema.py _PY_OK)."""
    def conv(v: spb.Value):
        kind = v.WhichOneof("kind")
        if kind is None:
            return None
        if kind == "bytes_value":
            return base64.b64encode(v.bytes_value).decode()
        if kind == "list_value":
            return [conv(x) for x in v.list_value.values]
        if kind == "record_value":
            return record_value_to_json(v.record_value)
        return getattr(v, kind)
    return {k: conv(v) for k, v in rv.fields.items()}


def json_to_record_value(d: dict) -> spb.RecordValue:
    def fill(v, out: spb.Value):
        if isinstance(v, bool):
            out.bool_value = v
        elif isinstance(v, int):
            out.int64_value = v
        elif isinstance(v, float):
            out.double_value = v
        elif isinstance(v, str):
            out.string_value = v
        elif isinstance(v, bytes):
            out.bytes_value = v
        elif isinstance(v, list):
            for x in v:
                fill(x, out.list_value.values.add())
        elif isinstance(v, dict):
            out.record_value.CopyFrom(json_to_record_value(v))
    out = spb.RecordValue()
    for k, v in d.items():
        fill(v, out.fields[k])
    return out


def partition_to_pb(p_json: dict) -> spb.Partition:
    return spb.Partition(ring_size=int(p_json.get("ringSize", 4096)),
                         range_start=int(p_json["rangeStart"]),
                         range_stop=int(p_json["rangeStop"]))


def _b64(b: bytes) -> str:
    return base64.b64encode(b).decode()


class BrokerServicer:
    """messaging_pb.SeaweedMessaging over a BrokerServer."""

    def __init__(self, broker):
        self.broker = broker

    # -- plumbing ---------------------------------------------------------

    def _call(self, handler, context, query=None, payload=None,
              ok_statuses=(200,)):
        status, body = handler(LocalRequest(query=query,
                                            payload=payload))
        if status not in ok_statuses:
            from .rpc import check_status
            check_status(context, status, body)
        return body

    def _layout(self, context, namespace: str, topic: str):
        """(assignments body) via the lookup route; aborts on error."""
        return self._call(self.broker._lookup, context,
                          query={"namespace": namespace,
                                 "topic": topic})

    @staticmethod
    def _partition_index(assignments: list, part: spb.Partition) -> int:
        """Locate the wire Partition in the topic layout by its slot
        range (partition identity in the reference, partition.go)."""
        for i, a in enumerate(assignments):
            pj = a["partition"]
            if int(pj["rangeStart"]) == part.range_start and \
                    int(pj["rangeStop"]) == part.range_stop:
                return i
        return -1

    # -- control plane ----------------------------------------------------

    def FindBrokerLeader(self, request, context):
        try:
            brokers = self.broker._registered_brokers()
        except RuntimeError:
            brokers = []
        # the registry's first entry plays the balancer-leader role;
        # a lone broker answers with itself
        return bpb.FindBrokerLeaderResponse(
            broker=brokers[0] if brokers else self.broker.url)

    def BalanceTopics(self, request, context):
        self._call(self.broker._balance, context, payload={})
        return bpb.BalanceTopicsResponse()

    def ListTopics(self, request, context):
        """All topics across namespaces (the reference request carries
        no namespace filter)."""
        out = bpb.ListTopicsResponse()
        try:
            namespaces = self.broker._namespaces()
        except RuntimeError:
            return out
        for ns in namespaces:
            status, b = self.broker._list_topics(
                LocalRequest(query={"namespace": ns}))
            if status != 200:
                continue
            for name in b.get("topics", []):
                out.topics.add(namespace=ns, name=name)
        return out

    def TopicExists(self, request, context):
        status, _b = self.broker._lookup(LocalRequest(query={
            "namespace": request.topic.namespace,
            "topic": request.topic.name}))
        return bpb.TopicExistsResponse(exists=status == 200)

    def ConfigureTopic(self, request, context):
        t = request.topic
        self._call(self.broker._configure, context, payload={
            "namespace": t.namespace, "topic": t.name,
            "partitionCount": request.partition_count or 4})
        if request.HasField("message_record_type") and \
                request.message_record_type.fields:
            self._call(self.broker._schema_register, context, payload={
                "namespace": t.namespace, "topic": t.name,
                "recordType":
                    record_type_from_pb(request.message_record_type)})
        body = self._layout(context, t.namespace, t.name)
        out = bpb.ConfigureTopicResponse()
        for a in body.get("assignments", []):
            out.broker_partition_assignments.add(
                partition=partition_to_pb(a["partition"]),
                leader_broker=a["broker"])
        if request.HasField("message_record_type"):
            out.message_record_type.CopyFrom(
                request.message_record_type)
        return out

    def LookupTopicBrokers(self, request, context):
        t = request.topic
        body = self._layout(context, t.namespace, t.name)
        out = bpb.LookupTopicBrokersResponse()
        out.topic.CopyFrom(request.topic)
        for a in body.get("assignments", []):
            out.broker_partition_assignments.add(
                partition=partition_to_pb(a["partition"]),
                leader_broker=a["broker"])
        return out

    def GetTopicConfiguration(self, request, context):
        t = request.topic
        body = self._layout(context, t.namespace, t.name)
        out = bpb.GetTopicConfigurationResponse()
        out.topic.CopyFrom(request.topic)
        out.partition_count = len(body.get("assignments", []))
        for a in body.get("assignments", []):
            out.broker_partition_assignments.add(
                partition=partition_to_pb(a["partition"]),
                leader_broker=a["broker"])
        status, sb = self.broker._schema_get(LocalRequest(query={
            "namespace": t.namespace, "topic": t.name}))
        if status == 200 and sb.get("recordType"):
            out.message_record_type.CopyFrom(
                record_type_to_pb(sb["recordType"]))
        return out

    def ClosePublishers(self, request, context):
        # our publish path is connectionless per-request (no broker-
        # side publisher registry): nothing to sever, ack the intent
        return bpb.ClosePublishersResponse()

    def CloseSubscribers(self, request, context):
        return bpb.CloseSubscribersResponse()

    # -- data plane -------------------------------------------------------

    def PublishMessage(self, request_iterator, context):
        """Streaming publish (broker.proto:55): init names the topic +
        partition, each DataMessage appends through the same fenced
        guarded path as HTTP publishes, each append is acked with its
        assigned offset."""
        init = None
        idx = -1
        for req in request_iterator:
            which = req.WhichOneof("message")
            if which == "init":
                init = req.init
                body = self._layout(context, init.topic.namespace,
                                    init.topic.name)
                idx = self._partition_index(
                    body.get("assignments", []), init.partition)
                if idx < 0:
                    yield bpb.PublishMessageResponse(
                        error=f"partition "
                              f"{init.partition.range_start}-"
                              f"{init.partition.range_stop} not in "
                              f"topic layout", should_close=True)
                    return
                continue
            if which != "data" or init is None:
                yield bpb.PublishMessageResponse(
                    error="init message required first",
                    should_close=True)
                return
            if req.data.ctrl.is_close:
                return
            status, body = self.broker._publish(LocalRequest(payload={
                "namespace": init.topic.namespace,
                "topic": init.topic.name, "partition": idx,
                "key": _b64(req.data.key),
                "value": _b64(req.data.value),
                "tsNs": req.data.ts_ns}))
            if status != 200:
                yield bpb.PublishMessageResponse(
                    error=body.get("error", f"status {status}"),
                    should_close=status in (404, 503))
                if status in (404, 503):
                    return
                continue
            ts = int(body.get("tsNs", 0))
            yield bpb.PublishMessageResponse(ack_ts_ns=ts,
                                             assigned_offset=ts)

    def SubscribeMessage(self, request_iterator, context):
        """Streaming subscribe: init positions the cursor
        (PartitionOffset/OffsetType), DataMessages flow until the
        client disconnects; Seek repositions, Acks are absorbed (our
        cursor is client-driven, like the reference's stateless
        FetchMessage recommendation)."""
        try:
            first = next(request_iterator)
        except StopIteration:
            return
        if first.WhichOneof("message") != "init":
            yield self._sub_ctrl("init message required first",
                                 end=True)
            return
        init = first.init
        ns, name = init.topic.namespace, init.topic.name
        body = self._layout(context, ns, name)
        idx = self._partition_index(body.get("assignments", []),
                                    init.partition_offset.partition)
        if idx < 0:
            yield self._sub_ctrl("partition not in topic layout",
                                 end=True)
            return

        state = {"since": self._initial_since(init, ns, name, idx),
                 "seek": False}

        def reader():
            try:
                for req in request_iterator:
                    which = req.WhichOneof("message")
                    if which == "seek":
                        # inclusive: the record AT the seek offset is
                        # redelivered (reads are strict `>`)
                        state["since"] = int(req.seek.offset) - 1
                        state["seek"] = True
                    # acks carry no broker state here: cursors are
                    # client-owned (reference FetchMessage model)
            except grpc.RpcError:
                pass    # client cancelled the stream

        threading.Thread(target=reader, daemon=True).start()

        while context.is_active():
            status, body = self.broker._subscribe(LocalRequest(query={
                "namespace": ns, "topic": name, "partition": idx,
                "sinceNs": state["since"], "limit": 500}))
            if status != 200:
                yield self._sub_ctrl(
                    body.get("error", f"status {status}"),
                    end=status in (404, 503))
                if status in (404, 503):
                    return
                time.sleep(0.2)
                continue
            msgs = body.get("messages", [])
            for m in msgs:
                if state["seek"]:
                    break  # re-read from the seek point
                out = bpb.SubscribeMessageResponse()
                out.data.key = base64.b64decode(m.get("key", ""))
                out.data.value = base64.b64decode(m.get("value", ""))
                out.data.ts_ns = int(m["tsNs"])
                state["since"] = int(m["tsNs"])
                yield out
            if state["seek"]:
                state["seek"] = False
                continue
            if not msgs:
                time.sleep(0.1)

    @staticmethod
    def _sub_ctrl(error: str, end: bool = False):
        out = bpb.SubscribeMessageResponse()
        out.ctrl.error = error
        out.ctrl.is_end_of_stream = end
        return out

    def _initial_since(self, init, ns: str, name: str,
                       idx: int) -> int:
        ot = init.offset_type
        if ot in (spb.RESET_TO_LATEST, spb.RESUME_OR_LATEST):
            # position at the partition's high water mark, NOT the
            # wall clock: a publisher-supplied event-time ts_ns may
            # trail time.time_ns() and would be silently skipped
            status, b = self.broker._subscribe(LocalRequest(query={
                "namespace": ns, "topic": name, "partition": idx,
                "sinceNs": 1 << 62, "limit": 1}))
            return int(b.get("highWaterMarkNs", 0)) \
                if status == 200 else 0
        if ot in (spb.EXACT_TS_NS, spb.EXACT_OFFSET,
                  spb.RESET_TO_OFFSET):
            # inclusive positioning (the reference delivers the record
            # at exactly the requested offset; reads are strict `>`)
            return int(init.partition_offset.start_offset or
                       init.partition_offset.start_ts_ns) - 1
        return int(init.partition_offset.start_ts_ns)  # earliest: 0

    def FetchMessage(self, request, context):
        """Stateless Kafka-style fetch (broker.proto:68): one
        request/response, client owns the cursor.  start_offset is a
        tsNs stamp; next_offset is the last returned stamp (reads are
        strict `>`)."""
        body = self._layout(context, request.topic.namespace,
                            request.topic.name)
        idx = self._partition_index(body.get("assignments", []),
                                    request.partition)
        out = bpb.FetchMessageResponse()
        if idx < 0:
            out.error = "partition not in topic layout"
            return out
        limit = request.max_messages or 500
        deadline = time.time() + min(request.max_wait_ms, 30_000) / 1e3
        while True:
            status, b = self.broker._subscribe(LocalRequest(query={
                "namespace": request.topic.namespace,
                "topic": request.topic.name, "partition": idx,
                "sinceNs": request.start_offset, "limit": limit}))
            if status != 200:
                out.error = b.get("error", f"status {status}")
                return out
            msgs = b.get("messages", [])
            total = 0
            for m in msgs:
                dm = out.messages.add()
                dm.key = base64.b64decode(m.get("key", ""))
                dm.value = base64.b64decode(m.get("value", ""))
                dm.ts_ns = int(m["tsNs"])
                total += len(dm.key) + len(dm.value)
                if request.max_bytes and total >= request.max_bytes:
                    break
            out.high_water_mark = int(b.get("highWaterMarkNs", 0))
            if out.messages:
                out.next_offset = out.messages[-1].ts_ns
            else:
                out.next_offset = request.start_offset
            out.end_of_partition = \
                out.next_offset >= out.high_water_mark
            if out.messages or time.time() >= deadline:
                return out
            time.sleep(0.1)

    def GetPartitionRangeInfo(self, request, context):
        body = self._layout(context, request.topic.namespace,
                            request.topic.name)
        idx = self._partition_index(body.get("assignments", []),
                                    request.partition)
        out = bpb.GetPartitionRangeInfoResponse()
        if idx < 0:
            out.error = "partition not in topic layout"
            return out
        status, b = self.broker._subscribe(LocalRequest(query={
            "namespace": request.topic.namespace,
            "topic": request.topic.name, "partition": idx,
            "sinceNs": 0, "limit": 1}))
        if status != 200:
            out.error = b.get("error", f"status {status}")
            return out
        hwm = int(b.get("highWaterMarkNs", 0))
        msgs = b.get("messages", [])
        earliest = int(msgs[0]["tsNs"]) if msgs else 0
        out.offset_range.earliest_offset = earliest
        out.offset_range.latest_offset = hwm
        out.offset_range.high_water_mark = hwm
        out.timestamp_range.earliest_timestamp_ns = earliest
        out.timestamp_range.latest_timestamp_ns = hwm
        return out


class AgentServicer:
    """messaging_pb.SeaweedMessagingAgent over an AgentServer.
    Session ids are int64 on the wire (mq_agent.proto); the agent's
    hex session ids are interned per connection."""

    def __init__(self, agent):
        self.agent = agent
        self._ids = itertools.count(1)
        self._sid: dict[int, str] = {}
        self._lock = threading.Lock()

    def _intern(self, hex_sid: str) -> int:
        n = next(self._ids)
        with self._lock:
            self._sid[n] = hex_sid
        return n

    def _hex(self, n: int) -> "str | None":
        with self._lock:
            return self._sid.get(n)

    def StartPublishSession(self, request, context):
        status, body = self.agent._start_publish(LocalRequest(payload={
            "namespace": request.topic.namespace,
            "topic": request.topic.name,
            "partitionCount": request.partition_count or 4}))
        if status != 200:
            return apb.StartPublishSessionResponse(
                error=body.get("error", f"status {status}"))
        return apb.StartPublishSessionResponse(
            session_id=self._intern(body["sessionId"]))

    def ClosePublishSession(self, request, context):
        hex_sid = self._hex(request.session_id)
        if hex_sid is not None:
            self.agent._close(LocalRequest(
                payload={"sessionId": hex_sid}))
            with self._lock:
                self._sid.pop(request.session_id, None)
        return apb.ClosePublishSessionResponse()

    def PublishRecord(self, request_iterator, context):
        """mq_agent.proto:20 — session_id rides the first record."""
        sid = None
        seq = 0
        for req in request_iterator:
            if sid is None:
                sid = self._hex(req.session_id)
                if sid is None:
                    yield apb.PublishRecordResponse(
                        error=f"unknown session {req.session_id}")
                    return
            value_json = json.dumps(
                record_value_to_json(req.value)).encode()
            status, body = self.agent._publish(LocalRequest(payload={
                "sessionId": sid, "key": _b64(req.key),
                "value": _b64(value_json)}))
            if status != 200:
                yield apb.PublishRecordResponse(
                    error=body.get("error", f"status {status}"))
                continue
            seq = int(body.get("tsNs", seq))
            yield apb.PublishRecordResponse(ack_sequence=seq)

    def SubscribeRecord(self, request_iterator, context):
        """mq_agent.proto:24 — typed records with at-least-once acks:
        the agent's partition leases redeliver un-acked records; acks
        resolve through a per-stream ts->partition map."""
        try:
            first = next(request_iterator)
        except StopIteration:
            return
        if not first.HasField("init"):
            yield apb.SubscribeRecordResponse(
                error="init required first", is_end_of_stream=True)
            return
        init = first.init
        status, body = self.agent._start_subscribe(LocalRequest(
            payload={"namespace": init.topic.namespace,
                     "topic": init.topic.name}))
        if status != 200:
            yield apb.SubscribeRecordResponse(
                error=body.get("error", f"status {status}"),
                is_end_of_stream=True)
            return
        sid = body["sessionId"]
        part_of: dict[int, int] = {}
        lock = threading.Lock()

        def reader():
            try:
                for req in request_iterator:
                    if req.ack_sequence:
                        with lock:
                            p = part_of.pop(req.ack_sequence, None)
                        if p is not None:
                            self.agent._ack(LocalRequest(payload={
                                "sessionId": sid, "partition": p,
                                "tsNs": req.ack_sequence}))
            except grpc.RpcError:
                pass    # client cancelled the stream

        threading.Thread(target=reader, daemon=True).start()
        try:
            while context.is_active():
                status, b = self.agent._subscribe(LocalRequest(query={
                    "sessionId": sid, "maxRecords": 100,
                    "waitSec": 1.0}))
                if status != 200:
                    yield apb.SubscribeRecordResponse(
                        error=b.get("error", f"status {status}"),
                        is_end_of_stream=True)
                    return
                for r in b.get("records", []):
                    out = apb.SubscribeRecordResponse()
                    out.key = base64.b64decode(r.get("key", ""))
                    raw = base64.b64decode(r.get("value", ""))
                    try:
                        decoded = json.loads(raw)
                        if not isinstance(decoded, dict):
                            raise TypeError("not a record")
                        out.value.CopyFrom(
                            json_to_record_value(decoded))
                    except (ValueError, TypeError):
                        # schemaless / non-object values ride a
                        # single-field record
                        out.value.fields["_raw"].bytes_value = raw
                    out.ts_ns = int(r["tsNs"])
                    with lock:
                        part_of[out.ts_ns] = int(r["partition"])
                    yield out
        finally:
            self.agent._close(LocalRequest(payload={"sessionId": sid}))


def start_broker_grpc(broker, host: str = "127.0.0.1", port: int = 0):
    # each SubscribeMessage stream (and a long-poll FetchMessage)
    # parks a pool worker; a deep pool keeps idle subscribers from
    # starving the unary control plane (the reference's goroutine
    # model has no such cap)
    return serve([make_service_handler(BROKER_SERVICE, BROKER_METHODS,
                                       BrokerServicer(broker),
                                       role="broker")],
                 host=host, port=port, max_workers=64)


def start_agent_grpc(agent, host: str = "127.0.0.1", port: int = 0):
    return serve([make_service_handler(AGENT_SERVICE, AGENT_METHODS,
                                       AgentServicer(agent),
                                       role="agent")],
                 host=host, port=port, max_workers=64)
