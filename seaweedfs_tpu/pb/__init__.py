r"""Protobuf/gRPC wire plane.

The reference's compatibility surface is its protos
(/root/reference/weed/pb/*.proto, SURVEY §7); this package carries a
wire-compatible subset: `protos/*.proto` (same package/service/method
names and field numbers), the protoc-generated `*_pb2.py` modules, and
hand-rolled grpc service/stub wiring (grpc_tools isn't in the image, so
method handlers and client stubs are built directly from the generated
message classes — functionally identical to *_pb2_grpc.py output).

Regenerate after editing protos:
    cd seaweedfs_tpu/pb && protoc --python_out=. -I protos \
        protos/*.proto
    # protoc emits absolute imports for proto-to-proto deps; make
    # them package-relative:
    sed -i 's/^import \(mq_schema\|filer\)_pb2 as/from . import \1_pb2 as/' *_pb2.py

Everything degrades gracefully: servers expose gRPC when `grpc` is
importable, JSON-HTTP remains the human-debuggable surface either way.
"""

from __future__ import annotations


def grpc_available() -> bool:
    try:
        import grpc  # noqa: F401
        return True
    except Exception:  # pragma: no cover
        return False
