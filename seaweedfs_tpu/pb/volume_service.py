"""gRPC VolumeServer service — wire-compatible with
/root/reference/weed/pb/volume_server.proto (see
protos/volume_server.proto): the EC family plus the streamed bulk-file
plane.  Bridges to the JSON-HTTP route handlers (one implementation per
operation); CopyFile/ReceiveFile stream chunk messages so bulk volume
data moves with bounded memory, like the reference's
volume_grpc_copy_incremental.go / ec shard distribution."""

from __future__ import annotations

import os
import time
import uuid

import grpc

from . import volume_server_pb2 as pb
from .rpc import Stub, check_status, guarded, make_service_handler, \
    serve

SERVICE = "volume_server_pb.VolumeServer"
STREAM_CHUNK = 1 << 20  # 1MB per CopyFile/ReceiveFile message

METHODS = {
    "VolumeMount": ("uu", pb.VolumeMountRequest, pb.VolumeMountResponse),
    "VolumeUnmount": ("uu", pb.VolumeUnmountRequest,
                      pb.VolumeUnmountResponse),
    "VolumeDelete": ("uu", pb.VolumeDeleteRequest,
                     pb.VolumeDeleteResponse),
    "VolumeMarkReadonly": ("uu", pb.VolumeMarkReadonlyRequest,
                           pb.VolumeMarkReadonlyResponse),
    "VolumeMarkWritable": ("uu", pb.VolumeMarkWritableRequest,
                           pb.VolumeMarkWritableResponse),
    "CopyFile": ("us", pb.CopyFileRequest, pb.CopyFileResponse),
    "ReceiveFile": ("su", pb.ReceiveFileRequest, pb.ReceiveFileResponse),
    "VolumeEcShardsGenerate": ("uu", pb.VolumeEcShardsGenerateRequest,
                               pb.VolumeEcShardsGenerateResponse),
    "VolumeEcShardsRebuild": ("uu", pb.VolumeEcShardsRebuildRequest,
                              pb.VolumeEcShardsRebuildResponse),
    "VolumeEcShardsCopy": ("uu", pb.VolumeEcShardsCopyRequest,
                           pb.VolumeEcShardsCopyResponse),
    "VolumeEcShardsDelete": ("uu", pb.VolumeEcShardsDeleteRequest,
                             pb.VolumeEcShardsDeleteResponse),
    "VolumeEcShardsMount": ("uu", pb.VolumeEcShardsMountRequest,
                            pb.VolumeEcShardsMountResponse),
    "VolumeEcShardsUnmount": ("uu", pb.VolumeEcShardsUnmountRequest,
                              pb.VolumeEcShardsUnmountResponse),
    "VolumeEcShardRead": ("us", pb.VolumeEcShardReadRequest,
                          pb.VolumeEcShardReadResponse),
    "VolumeEcShardsToVolume": ("uu", pb.VolumeEcShardsToVolumeRequest,
                               pb.VolumeEcShardsToVolumeResponse),
    "VolumeEcShardsInfo": ("uu", pb.VolumeEcShardsInfoRequest,
                           pb.VolumeEcShardsInfoResponse),
    "Ping": ("uu", pb.PingRequest, pb.PingResponse),
}


class VolumeServicer:
    def __init__(self, vs):
        self.vs = vs

    # -- plain volume admin --------------------------------------------

    def VolumeMount(self, request, context):
        status, resp = self.vs._mount_volume(guarded(
            context, self.vs, "/admin/mount_volume",
            payload={"volumeId": request.volume_id}))
        check_status(context, status, resp)
        return pb.VolumeMountResponse()

    def VolumeUnmount(self, request, context):
        status, resp = self.vs._unmount_volume(guarded(
            context, self.vs, "/admin/unmount_volume",
            payload={"volumeId": request.volume_id}))
        check_status(context, status, resp)
        return pb.VolumeUnmountResponse()

    def VolumeDelete(self, request, context):
        status, resp = self.vs._delete_volume(guarded(
            context, self.vs, "/admin/delete_volume",
            payload={"volumeId": request.volume_id}))
        check_status(context, status, resp)
        return pb.VolumeDeleteResponse()

    def VolumeMarkReadonly(self, request, context):
        status, resp = self.vs._set_readonly(guarded(
            context, self.vs, "/admin/set_readonly", payload={
                "volumeId": request.volume_id, "readOnly": True}))
        check_status(context, status, resp)
        return pb.VolumeMarkReadonlyResponse()

    def VolumeMarkWritable(self, request, context):
        status, resp = self.vs._set_readonly(guarded(
            context, self.vs, "/admin/set_readonly", payload={
                "volumeId": request.volume_id, "readOnly": False}))
        check_status(context, status, resp)
        return pb.VolumeMarkWritableResponse()

    # -- streamed bulk-file plane --------------------------------------

    def CopyFile(self, request, context):
        """volume_server.proto:69: chunked server-stream of one
        volume/shard file."""
        vs = self.vs
        guarded(context, vs, "/admin/volume_file")
        if request.ext in (".dat", ".idx"):
            v = vs.store.find_volume(request.volume_id)
            if v is not None:
                v.sync()
        try:
            path = vs._file_path(request.volume_id, request.collection,
                                 request.ext)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if path is None:
            if request.ignore_source_file_not_found:
                return
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"no {request.ext} for volume "
                          f"{request.volume_id}")
        stop = request.stop_offset or 0
        mtime = int(os.stat(path).st_mtime_ns)
        with open(path, "rb") as f:
            sent = 0
            while True:
                n = STREAM_CHUNK
                if stop and stop - sent < n:
                    n = stop - sent
                if n <= 0:
                    break
                chunk = f.read(n)
                if not chunk:
                    break
                sent += len(chunk)
                yield pb.CopyFileResponse(file_content=chunk,
                                          modified_ts_ns=mtime)

    def ReceiveFile(self, request_iterator, context):
        """volume_server.proto:71: first message carries the file info,
        the rest carry content chunks — written straight to disk."""
        it = iter(request_iterator)
        try:
            first = next(it)
        except StopIteration:
            return pb.ReceiveFileResponse(error="empty stream")
        if first.WhichOneof("data") != "info":
            return pb.ReceiveFileResponse(
                error="first message must be ReceiveFileInfo")
        info = first.info
        guarded(context, self.vs, "/admin/receive_file")
        try:
            # same path-field validation as the HTTP twin: ext must be
            # a plain ".xxx", no separators (volume_server.py
            # _receive_file -> _check_path_fields) — without it a
            # crafted ext is a remote arbitrary-file-write
            from ..server.volume_server import _check_path_fields
            _check_path_fields(info.collection, info.ext)
            base = self.vs._base_path(info.volume_id, info.collection)
        except ValueError as e:
            return pb.ReceiveFileResponse(error=str(e))
        n = 0
        # per-stream unique temp name: concurrent pushes of the same
        # volume/ext (worker retry racing the original) must not
        # interleave into one file
        tmp = f"{base}{info.ext}.recv.{uuid.uuid4().hex}"
        try:
            with open(tmp, "wb") as f:
                for msg in it:
                    if msg.WhichOneof("data") != "file_content":
                        return pb.ReceiveFileResponse(
                            error="unexpected info message mid-stream")
                    f.write(msg.file_content)
                    n += len(msg.file_content)
            os.replace(tmp, base + info.ext)
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass
        return pb.ReceiveFileResponse(bytes_written=n)

    # -- erasure coding -------------------------------------------------

    def VolumeEcShardsGenerate(self, request, context):
        status, resp = self.vs._ec_generate(guarded(
            context, self.vs, "/admin/ec/generate", payload={
                "volumeId": request.volume_id,
                "collection": request.collection}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsGenerateResponse()

    def VolumeEcShardsRebuild(self, request, context):
        status, resp = self.vs._ec_rebuild(guarded(
            context, self.vs, "/admin/ec/rebuild", payload={
                "volumeId": request.volume_id,
                "collection": request.collection}))
        out = check_status(context, status, resp)
        return pb.VolumeEcShardsRebuildResponse(
            rebuilt_shard_ids=out.get("rebuiltShardIds", []))

    def VolumeEcShardsCopy(self, request, context):
        status, resp = self.vs._ec_copy(guarded(
            context, self.vs, "/admin/ec/copy", payload={
            "volumeId": request.volume_id,
            "collection": request.collection,
            "shardIds": list(request.shard_ids),
            "copyEcxFile": request.copy_ecx_file,
            "copyEcjFile": request.copy_ecj_file,
            "copyVifFile": request.copy_vif_file,
            "sourceDataNode": request.source_data_node}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsCopyResponse()

    def VolumeEcShardsDelete(self, request, context):
        status, resp = self.vs._ec_delete_shards(guarded(
            context, self.vs, "/admin/ec/delete_shards", payload={
            "volumeId": request.volume_id,
            "collection": request.collection,
            "shardIds": list(request.shard_ids)}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsDeleteResponse()

    def VolumeEcShardsMount(self, request, context):
        status, resp = self.vs._ec_mount(guarded(
            context, self.vs, "/admin/ec/mount", payload={
            "volumeId": request.volume_id,
            "collection": request.collection,
            "shardIds": list(request.shard_ids)}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsMountResponse()

    def VolumeEcShardsUnmount(self, request, context):
        status, resp = self.vs._ec_unmount(guarded(
            context, self.vs, "/admin/ec/unmount", payload={
                "volumeId": request.volume_id,
                "shardIds": list(request.shard_ids)}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsUnmountResponse()

    def VolumeEcShardRead(self, request, context):
        vs = self.vs
        status, resp = vs._ec_shard_read(guarded(
            context, vs, "/admin/ec/shard_read", query={
                "volumeId": request.volume_id,
                "shardId": request.shard_id,
                "offset": request.offset, "size": request.size}))
        if status != 200:
            check_status(context, status, resp)
        if isinstance(resp, tuple):
            # (FileSlice, headers): re-chunk the handler's zero-copy
            # range stream into response messages without buffering
            # the whole slice
            body, _hdrs = resp
            try:
                while True:
                    chunk = body.read(STREAM_CHUNK)
                    if not chunk:
                        return
                    yield pb.VolumeEcShardReadResponse(data=chunk)
            finally:
                body.close()
            return
        data = resp if isinstance(resp, (bytes, bytearray)) \
            else bytes(resp)
        if data:
            yield pb.VolumeEcShardReadResponse(data=data)

    def VolumeEcShardsToVolume(self, request, context):
        status, resp = self.vs._ec_to_volume(guarded(
            context, self.vs, "/admin/ec/to_volume", payload={
                "volumeId": request.volume_id,
                "collection": request.collection}))
        check_status(context, status, resp)
        return pb.VolumeEcShardsToVolumeResponse()

    def VolumeEcShardsInfo(self, request, context):
        status, resp = self.vs._ec_info(guarded(
            context, self.vs, "/admin/ec/info", query={
                "volumeId": request.volume_id}))
        out = check_status(context, status, resp)
        r = pb.VolumeEcShardsInfoResponse()
        for sid in out.get("shardIds", []):
            r.ec_shard_infos.add(
                shard_id=sid, size=out.get("shardSize", 0),
                volume_id=request.volume_id)
        return r

    def Ping(self, request, context):
        now = time.time_ns()
        return pb.PingResponse(start_time_ns=now, remote_time_ns=now,
                               stop_time_ns=time.time_ns())


def start_volume_grpc(vs, host: str = "127.0.0.1", port: int = 0):
    handler = make_service_handler(SERVICE, METHODS, VolumeServicer(vs),
                                   role="volume")
    return serve([handler], host, port)


def volume_stub(channel, peer: str = "") -> Stub:
    """`peer` (the dialed host:port) opts every call into that
    peer's circuit breaker (util/retry)."""
    return Stub(channel, SERVICE, METHODS, peer=peer)


def send_file(stub: Stub, path: str, volume_id: int, ext: str,
              collection: str = "", shard_id: int = 0) -> int:
    """Client-side ReceiveFile push: stream `path` in chunks."""
    def gen():
        yield pb.ReceiveFileRequest(info=pb.ReceiveFileInfo(
            volume_id=volume_id, ext=ext, collection=collection,
            shard_id=shard_id, file_size=os.path.getsize(path)))
        with open(path, "rb") as f:
            while True:
                chunk = f.read(STREAM_CHUNK)
                if not chunk:
                    break
                yield pb.ReceiveFileRequest(file_content=chunk)
    resp = stub.ReceiveFile(gen())
    if resp.error:
        raise RuntimeError(f"ReceiveFile {ext}: {resp.error}")
    return resp.bytes_written


def fetch_file(stub: Stub, dest_path: str, volume_id: int, ext: str,
               collection: str = "") -> int:
    """Client-side CopyFile pull: stream into dest_path."""
    n = 0
    tmp = f"{dest_path}.pull.{uuid.uuid4().hex}"
    try:
        with open(tmp, "wb") as f:
            for msg in stub.CopyFile(pb.CopyFileRequest(
                    volume_id=volume_id, ext=ext,
                    collection=collection)):
                f.write(msg.file_content)
                n += len(msg.file_content)
        os.replace(tmp, dest_path)
    finally:
        try:
            os.remove(tmp)
        except OSError:
            pass
    return n
