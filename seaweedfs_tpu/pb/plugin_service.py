"""gRPC maintenance-plane services — wire-compatible with the
reference plugin control stream (/root/reference/weed/pb/plugin.proto:12
PluginControlService.WorkerStream) and the older maintenance worker
stream (/root/reference/weed/pb/worker.proto:8, served by the admin:
admin/dash/worker_grpc_server.go:176).

Both are worker-initiated bidi streams held against the AdminServer.
Every inbound message drives the same registry/dispatch handlers the
HTTP long-poll plane uses (plugin/admin.py), so the two transports
cannot drift: the stream is just a different codec for the same
conversation (register -> poll -> detect/execute -> report).
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
import uuid

import grpc

from . import plugin_pb2 as ppb
from . import worker_pb2 as wpb
from .rpc import LocalRequest, Stub, make_service_handler, serve

PLUGIN_SERVICE = "plugin.PluginControlService"
PLUGIN_METHODS = {
    "WorkerStream": ("ss", ppb.WorkerToAdminMessage,
                     ppb.AdminToWorkerMessage),
}

WORKER_SERVICE = "worker_pb.WorkerService"
WORKER_METHODS = {
    "WorkerStream": ("ss", wpb.WorkerMessage, wpb.AdminMessage),
}


# -- ConfigValue codec ----------------------------------------------------

def to_config_value(v) -> ppb.ConfigValue:
    """Python scalar -> plugin.ConfigValue (plugin.proto:185)."""
    cv = ppb.ConfigValue()
    if isinstance(v, bool):
        cv.bool_value = v
    elif isinstance(v, int):
        cv.int64_value = v
    elif isinstance(v, float):
        cv.double_value = v
    elif isinstance(v, bytes):
        cv.bytes_value = v
    elif isinstance(v, (list, tuple)):
        cv.string_list.values.extend(str(x) for x in v)
    else:
        cv.string_value = str(v)
    return cv


def from_config_value(cv: ppb.ConfigValue):
    kind = cv.WhichOneof("kind")
    if kind is None:
        return None
    if kind == "string_list":
        return list(cv.string_list.values)
    return getattr(cv, kind)


def params_to_map(params: dict, target) -> None:
    for k, v in (params or {}).items():
        target[k].CopyFrom(to_config_value(v))


def map_to_params(m) -> dict:
    return {k: from_config_value(v) for k, v in m.items()}


# our schema field types (admin.py _FIELD_TYPES) <-> ConfigFieldType
_FT_TO_PB = {"bool": ppb.CONFIG_FIELD_TYPE_BOOL,
             "int": ppb.CONFIG_FIELD_TYPE_INT64,
             "float": ppb.CONFIG_FIELD_TYPE_DOUBLE,
             "string": ppb.CONFIG_FIELD_TYPE_STRING}
_FT_FROM_PB = {v: k for k, v in _FT_TO_PB.items()}


def descriptor_to_pb(desc: dict) -> ppb.JobTypeDescriptor:
    """Worker-side dict Descriptor -> JobTypeDescriptor with the
    fields in one worker_config_form section (plugin.proto:116)."""
    out = ppb.JobTypeDescriptor(job_type=desc.get("jobType", ""),
                                descriptor_version=1)
    section = out.worker_config_form.sections.add(section_id="main")
    for f in desc.get("fields", []):
        section.fields.add(
            name=f.get("name", ""), label=f.get("label", ""),
            description=f.get("description", ""),
            field_type=_FT_TO_PB.get(f.get("type", "string"),
                                     ppb.CONFIG_FIELD_TYPE_STRING))
    return out


def descriptor_from_pb(d: ppb.JobTypeDescriptor) -> dict:
    fields = []
    for section in d.worker_config_form.sections:
        for f in section.fields:
            fields.append({
                "name": f.name, "label": f.label,
                "description": f.description,
                "type": _FT_FROM_PB.get(f.field_type, "string")})
    return {"jobType": d.job_type, "fields": fields}


# -- admin-side servicers -------------------------------------------------

class _StreamSession:
    """Shared mechanics of one worker's stream against the admin:
    a reader thread drives inbound messages into the admin's handlers
    while the response generator polls the admin's dispatch queue."""

    def __init__(self, admin):
        self.admin = admin
        self.worker_id = ""
        self.done = threading.Event()

    def register(self, worker_id: str, capabilities: list,
                 max_concurrent: int, descriptors: list) -> str:
        status, body = self.admin._register(LocalRequest(payload={
            "workerId": worker_id,
            "capabilities": capabilities,
            "descriptors": descriptors,
            "maxConcurrent": max_concurrent}))
        self.worker_id = body["workerId"]
        return self.worker_id

    def poll(self, wait: float) -> dict:
        """One admin->worker dispatch message, {"type": "none"} after
        `wait` idle seconds, or {"error": ...} if unregistered."""
        status, body = self.admin._poll(LocalRequest(payload={
            "workerId": self.worker_id, "waitSeconds": wait}))
        return body if status == 200 else {"error": body.get("error")}

    def proposals(self, props: list) -> None:
        self.admin._detection_result(LocalRequest(payload={
            "workerId": self.worker_id, "proposals": props}))

    def progress(self, job_id: str, fraction: float,
                 message: str) -> None:
        self.admin._progress(LocalRequest(payload={
            "workerId": self.worker_id, "jobId": job_id,
            "progress": fraction, "message": message}))

    def complete(self, job_id: str, success: bool,
                 message: str) -> None:
        self.admin._complete(LocalRequest(payload={
            "workerId": self.worker_id, "jobId": job_id,
            "success": success, "message": message}))

    def heartbeat(self) -> None:
        with self.admin.lock:
            self.admin._touch(self.worker_id)

    def learn_schema(self, desc: dict) -> None:
        if not desc.get("jobType"):
            return
        with self.admin.lock:
            self.admin.schemas[desc["jobType"]] = desc.get("fields", [])
            self.admin._persist_workers()


class PluginControlServicer:
    """plugin.PluginControlService bound to an AdminServer."""

    HEARTBEAT_SECONDS = 10

    def __init__(self, admin):
        self.admin = admin

    def WorkerStream(self, request_iterator, context):
        sess = _StreamSession(self.admin)
        try:
            first = next(request_iterator)
        except StopIteration:
            return
        if first.WhichOneof("body") != "hello":
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "first message must be hello (plugin.proto:48)")
        hello = first.hello
        caps = [{"jobType": c.job_type, "canDetect": c.can_detect,
                 "canExecute": c.can_execute, "weight": c.weight or 50}
                for c in hello.capabilities]
        max_conc = max((c.max_execution_concurrency
                        for c in hello.capabilities), default=1) or 1
        wid = sess.register(hello.worker_id or first.worker_id,
                            caps, max_conc, [])
        out = ppb.AdminToWorkerMessage(request_id=uuid.uuid4().hex[:12])
        out.hello.accepted = True
        out.hello.message = f"registered as {wid}"
        out.hello.heartbeat_interval_seconds = self.HEARTBEAT_SECONDS
        out.hello.reconnect_delay_seconds = 1
        yield out
        # SchemaCoordinator pull: ask for each job type's config form
        for c in caps:
            req = ppb.AdminToWorkerMessage(
                request_id=uuid.uuid4().hex[:12])
            req.request_config_schema.job_type = c["jobType"]
            yield req

        reader = threading.Thread(
            target=self._drain_inbound,
            args=(sess, request_iterator), daemon=True)
        reader.start()

        detection_seq = 0
        while not sess.done.is_set() and context.is_active() \
                and not self.admin._stop.is_set():
            msg = sess.poll(wait=1.0)
            mtype = msg.get("type")
            if msg.get("error"):
                break
            if mtype == "runDetection":
                detection_seq += 1
                config = msg.get("config") or {}
                for c in caps:
                    if not c.get("canDetect"):
                        continue
                    jt = c["jobType"]
                    req = ppb.AdminToWorkerMessage(
                        request_id=uuid.uuid4().hex[:12])
                    rd = req.run_detection_request
                    rd.job_type = jt
                    rd.detection_sequence = detection_seq
                    params_to_map(config.get(jt, {}),
                                  rd.worker_config_values)
                    rd.cluster_context.master_grpc_addresses.append(
                        self.admin.master)
                    yield req
            elif mtype == "executeJob":
                req = ppb.AdminToWorkerMessage(
                    request_id=uuid.uuid4().hex[:12])
                ej = req.execute_job_request
                ej.job.job_id = msg["jobId"]
                ej.job.job_type = msg["jobType"]
                params_to_map(msg.get("params", {}),
                              ej.job.parameters)
                ej.cluster_context.master_grpc_addresses.append(
                    self.admin.master)
                yield req
        if self.admin._stop.is_set() and context.is_active():
            bye = ppb.AdminToWorkerMessage()
            bye.shutdown.reason = "admin stopping"
            yield bye
        sess.done.set()

    def _drain_inbound(self, sess: _StreamSession,
                       request_iterator) -> None:
        try:
            for msg in request_iterator:
                body = msg.WhichOneof("body")
                if body == "heartbeat":
                    sess.heartbeat()
                elif body == "detection_proposals":
                    dp = msg.detection_proposals
                    sess.proposals([{
                        "jobType": p.job_type or dp.job_type,
                        "params": map_to_params(p.parameters),
                        "dedupeKey": p.dedupe_key,
                        "reason": p.summary,
                    } for p in dp.proposals])
                elif body == "job_progress_update":
                    up = msg.job_progress_update
                    sess.progress(up.job_id,
                                  up.progress_percent / 100.0,
                                  up.message)
                elif body == "job_completed":
                    jc = msg.job_completed
                    sess.complete(jc.job_id, jc.success,
                                  jc.error_message or
                                  jc.result.summary)
                elif body == "config_schema_response":
                    rsp = msg.config_schema_response
                    if rsp.success:
                        sess.learn_schema(descriptor_from_pb(
                            rsp.job_type_descriptor))
        except Exception as e:  # noqa: BLE001 — stream broke:
            from ..util import wlog     # worker gone; session reaped
            wlog.info("maintenance stream closed: %s", e,
                      component="plugin")
        finally:
            sess.done.set()


class WorkerServicer:
    """worker_pb.WorkerService bound to an AdminServer — the older
    maintenance stream (admin/dash/worker_grpc_server.go).  Task
    params ride the typed TaskParams variants; our job params dicts
    round-trip through the fields both sides understand."""

    def __init__(self, admin):
        self.admin = admin

    @staticmethod
    def _params_to_assignment(job_type: str, params: dict,
                              ta: wpb.TaskAssignment) -> None:
        # malformed operator params must never kill the stream (the
        # job is already marked assigned by _poll) — an uncastable
        # value just stays out of its typed slot and rides metadata
        def num(key, cast, default):
            """(value, key-present-AND-castable)."""
            if key not in params:
                return default, False
            try:
                return cast(params[key]), True
            except (TypeError, ValueError):
                return default, False

        tp = ta.params
        typed = {"collection"}  # keys carried outside metadata
        vid, ok = num("volumeId", int, 0)
        if not ok:
            vid, ok = num("volume_id", int, 0)
        if ok:
            typed |= {"volumeId", "volume_id"}
        tp.volume_id = vid
        tp.collection = str(params.get("collection", ""))
        if job_type == "vacuum":
            gt, ok = num("garbageThreshold", float, 0.3)
            tp.vacuum_params.garbage_threshold = gt
            if ok:
                typed.add("garbageThreshold")
            tp.vacuum_params.force_vacuum = bool(params.get("force"))
            typed.add("force")
        elif job_type in ("erasure_coding", "ec", "tpu_ec"):
            ds, ok1 = num("dataShards", int, 10)
            ps, ok2 = num("parityShards", int, 4)
            tp.erasure_coding_params.data_shards = ds
            tp.erasure_coding_params.parity_shards = ps
            tp.erasure_coding_params.cleanup_source = True
            if ok1:
                typed.add("dataShards")
            if ok2:
                typed.add("parityShards")
        elif job_type == "balance":
            for mv in params.get("moves", []) or []:
                try:
                    mvid = int(mv.get("volumeId", 0))
                except (TypeError, ValueError, AttributeError):
                    continue
                tp.balance_params.moves.add(
                    volume_id=mvid,
                    source_node=str(mv.get("source", "")),
                    target_node=str(mv.get("target", "")),
                    collection=str(mv.get("collection", "")))
            typed.add("moves")
        # only keys WITHOUT a typed home ride the metadata map (a
        # stringified duplicate would shadow the typed value — and its
        # type — on decode)
        for k, v in params.items():
            if k not in typed:
                ta.metadata[k] = str(v)

    @staticmethod
    def _assignment_to_params(ta: wpb.TaskAssignment) -> dict:
        params = dict(ta.metadata)
        tp = ta.params
        if tp.volume_id:
            params["volumeId"] = tp.volume_id
        if tp.collection:
            params["collection"] = tp.collection
        which = tp.WhichOneof("task_params")
        if which == "vacuum_params":
            params["garbageThreshold"] = \
                tp.vacuum_params.garbage_threshold
            params["force"] = tp.vacuum_params.force_vacuum
        elif which == "erasure_coding_params":
            params["dataShards"] = \
                tp.erasure_coding_params.data_shards
            params["parityShards"] = \
                tp.erasure_coding_params.parity_shards
        elif which == "balance_params":
            params["moves"] = [{
                "volumeId": m.volume_id, "source": m.source_node,
                "target": m.target_node, "collection": m.collection,
            } for m in tp.balance_params.moves]
        return params

    def WorkerStream(self, request_iterator, context):
        sess = _StreamSession(self.admin)
        admin_id = "admin"
        try:
            first = next(request_iterator)
        except StopIteration:
            return
        if first.WhichOneof("message") != "registration":
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          "first message must be registration "
                          "(worker.proto:45)")
        reg = first.registration
        caps = [{"jobType": c, "canDetect": False, "canExecute": True,
                 "weight": 50} for c in reg.capabilities]
        wid = sess.register(reg.worker_id or first.worker_id, caps,
                            reg.max_concurrent or 1, [])
        out = wpb.AdminMessage(admin_id=admin_id,
                               timestamp=int(time.time()))
        out.registration_response.success = True
        out.registration_response.assigned_worker_id = wid
        yield out

        reader = threading.Thread(target=self._drain_inbound,
                                  args=(sess, request_iterator),
                                  daemon=True)
        reader.start()

        while not sess.done.is_set() and context.is_active() \
                and not self.admin._stop.is_set():
            msg = sess.poll(wait=1.0)
            if msg.get("error"):
                break
            if msg.get("type") == "executeJob":
                out = wpb.AdminMessage(admin_id=admin_id,
                                       timestamp=int(time.time()))
                ta = out.task_assignment
                ta.task_id = msg["jobId"]
                ta.task_type = msg["jobType"]
                ta.created_time = int(time.time())
                self._params_to_assignment(
                    msg["jobType"], msg.get("params", {}), ta)
                yield out
            # runDetection has no wire analog here: worker.proto
            # detection lives admin-side (maintenance scan); ignore.
        if self.admin._stop.is_set() and context.is_active():
            out = wpb.AdminMessage(admin_id=admin_id,
                                   timestamp=int(time.time()))
            out.admin_shutdown.reason = "admin stopping"
            yield out
        sess.done.set()

    def _drain_inbound(self, sess: _StreamSession,
                       request_iterator) -> None:
        try:
            for msg in request_iterator:
                which = msg.WhichOneof("message")
                if which == "heartbeat":
                    sess.heartbeat()
                elif which == "task_update":
                    up = msg.task_update
                    sess.progress(up.task_id, up.progress, up.message)
                elif which == "task_complete":
                    tc = msg.task_complete
                    sess.complete(tc.task_id, tc.success,
                                  tc.error_message)
                elif which == "shutdown":
                    break
        except Exception as e:  # noqa: BLE001 — stream broke:
            from ..util import wlog     # worker gone; session reaped
            wlog.info("worker stream closed: %s", e,
                      component="plugin")
        finally:
            sess.done.set()


def start_admin_grpc(admin, host: str = "127.0.0.1", port: int = 0):
    """Serve both maintenance streams for an AdminServer; returns
    (grpc_server, bound_port)."""
    handlers = [
        make_service_handler(PLUGIN_SERVICE, PLUGIN_METHODS,
                             PluginControlServicer(admin),
                             role="admin"),
        make_service_handler(WORKER_SERVICE, WORKER_METHODS,
                             WorkerServicer(admin),
                             role="admin"),
    ]
    return serve(handlers, host=host, port=port)


# -- worker-side gRPC client ---------------------------------------------

class GrpcPluginWorker:
    """A PluginWorker that holds the plugin.proto WorkerStream instead
    of HTTP long-polls: same JobHandlers, same report semantics
    (plugin/worker.go's connection loop).  `admin` is host:port of the
    admin's gRPC listener."""

    def __init__(self, admin: str, master: str, work_dir: str,
                 handlers: list, max_concurrent: int = 1):
        self.admin = admin
        self.master = master
        self.work_dir = work_dir
        self.handlers = {h.job_type: h for h in handlers}
        for h in handlers:
            for alias in getattr(h, "aliases", []):
                self.handlers.setdefault(alias, h)
        self.max_concurrent = max_concurrent
        self.worker_id = ""
        self.executed: list[str] = []
        self._outq: "queue.Queue[ppb.WorkerToAdminMessage]" = \
            queue.Queue()
        self._stop = threading.Event()
        self._channel = None
        self._thread: threading.Thread | None = None

    # the request iterator: hello first, then whatever the worker
    # enqueues (reports, proposals, heartbeats)
    def _outbound(self):
        hello = ppb.WorkerToAdminMessage(worker_id=self.worker_id)
        hello.hello.worker_id = self.worker_id
        hello.hello.protocol_version = "1"
        for jt, h in self.handlers.items():
            cap = h.capability()
            hello.hello.capabilities.add(
                job_type=jt, can_detect=bool(cap.get("canDetect")),
                can_execute=bool(cap.get("canExecute", True)),
                max_execution_concurrency=self.max_concurrent,
                weight=int(cap.get("weight", 50)))
        yield hello
        while not self._stop.is_set():
            try:
                yield self._outq.get(timeout=0.2)
            except queue.Empty:
                continue

    def start(self):
        self.worker_id = uuid.uuid4().hex[:12]
        self._channel = grpc.insecure_channel(self.admin)
        stub = Stub(self._channel, PLUGIN_SERVICE, PLUGIN_METHODS)
        self._stream = stub.WorkerStream(self._outbound())
        self._thread = threading.Thread(target=self._inbound,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._channel is not None:
            self._channel.close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _send(self, msg: ppb.WorkerToAdminMessage) -> None:
        msg.worker_id = self.worker_id
        self._outq.put(msg)

    def _inbound(self) -> None:
        try:
            for msg in self._stream:
                body = msg.WhichOneof("body")
                if body == "hello":
                    # the admin registered the id we sent in our own
                    # hello (admin._register keeps it); nothing to do
                    pass
                elif body == "request_config_schema":
                    self._answer_schema(msg)
                elif body == "run_detection_request":
                    self._run_detection(msg.run_detection_request)
                elif body == "execute_job_request":
                    self._execute(msg.execute_job_request)
                elif body == "shutdown":
                    break
        except grpc.RpcError:
            pass

    def _answer_schema(self, msg: ppb.AdminToWorkerMessage) -> None:
        jt = msg.request_config_schema.job_type
        h = self.handlers.get(jt)
        out = ppb.WorkerToAdminMessage()
        rsp = out.config_schema_response
        rsp.request_id = msg.request_id
        rsp.job_type = jt
        if h is None:
            rsp.success = False
            rsp.error_message = f"no handler for {jt!r}"
        else:
            rsp.success = True
            rsp.job_type_descriptor.CopyFrom(
                descriptor_to_pb(h.descriptor()))
        self._send(out)

    def _run_detection(self, rd: ppb.RunDetectionRequest) -> None:
        h = self.handlers.get(rd.job_type)
        if h is None:
            return
        from ..plugin.worker import apply_config_values
        apply_config_values(h, {
            name: from_config_value(cv)
            for name, cv in rd.worker_config_values.items()})
        out = ppb.WorkerToAdminMessage()
        dp = out.detection_proposals
        dp.request_id = rd.request_id
        dp.job_type = rd.job_type
        try:
            proposals = h.detect(self)
        except Exception:  # noqa: BLE001 — detection must not kill stream
            traceback.print_exc()
            proposals = []
        for p in proposals:
            prop = dp.proposals.add()
            prop.job_type = p.get("jobType", rd.job_type)
            prop.dedupe_key = p.get("dedupeKey", "")
            prop.summary = p.get("reason", "")
            params_to_map(p.get("params", {}), prop.parameters)
        self._send(out)
        done = ppb.WorkerToAdminMessage()
        done.detection_complete.request_id = rd.request_id
        done.detection_complete.job_type = rd.job_type
        done.detection_complete.success = True
        done.detection_complete.total_proposals = len(dp.proposals)
        self._send(done)

    def _execute(self, ej: ppb.ExecuteJobRequest) -> None:
        def run():
            job_id = ej.job.job_id
            h = self.handlers.get(ej.job.job_type)
            # traceability for stream-dispatched jobs (tracing.py):
            # the proto carries no trace context, so the execution
            # roots its own trace under `job-<id>` — the same id the
            # HTTP worker falls back to
            from .. import tracing
            from ..util.request_id import set_request_id
            set_request_id(f"job-{job_id}")
            try:
                if h is None:
                    raise ValueError(
                        f"no handler for {ej.job.job_type!r}")
                with tracing.span(f"job:{ej.job.job_type}",
                                  role="worker") as sp:
                    sp.set("jobId", job_id)
                    message = h.execute(self, job_id,
                                        map_to_params(
                                            ej.job.parameters))
                success = True
            except Exception as e:  # noqa: BLE001 — report, don't die
                traceback.print_exc()
                message, success = f"{type(e).__name__}: {e}", False
            self.executed.append(job_id)
            out = ppb.WorkerToAdminMessage()
            jc = out.job_completed
            jc.request_id = ej.request_id
            jc.job_id = job_id
            jc.job_type = ej.job.job_type
            jc.success = success
            if success:
                jc.result.summary = message or ""
            else:
                jc.error_message = message
            self._send(out)
        threading.Thread(target=run, daemon=True).start()

    def report_progress(self, job_id: str, progress: float,
                        message: str = "") -> None:
        out = ppb.WorkerToAdminMessage()
        up = out.job_progress_update
        up.job_id = job_id
        up.progress_percent = progress * 100.0
        up.message = message
        self._send(out)
