"""gRPC SeaweedFiler service — wire-compatible with
/root/reference/weed/pb/filer.proto (see protos/filer.proto; field
numbers machine-checked by tests/test_proto_wire_compat.py).

The reference's most-trafficked proto (filer.proto:13-87): entries
CRUD, atomic rename, streaming list, SubscribeMetadata (fed by the
filer's meta log, filer_notify.go), KV, and the distributed-lock RPCs
(lock ring, distributed_lock_manager.go).  Every RPC drives the same
Filer/LockManager objects the JSON-HTTP routes use, so the planes
cannot drift.
"""

from __future__ import annotations

import base64
import time
from collections import deque

import grpc

from . import filer_pb2 as pb
from .rpc import Stub, make_service_handler, serve

SERVICE = "filer_pb.SeaweedFiler"

METHODS = {
    "LookupDirectoryEntry": ("uu", pb.LookupDirectoryEntryRequest,
                             pb.LookupDirectoryEntryResponse),
    "ListEntries": ("us", pb.ListEntriesRequest,
                    pb.ListEntriesResponse),
    "CreateEntry": ("uu", pb.CreateEntryRequest,
                    pb.CreateEntryResponse),
    "UpdateEntry": ("uu", pb.UpdateEntryRequest,
                    pb.UpdateEntryResponse),
    "AppendToEntry": ("uu", pb.AppendToEntryRequest,
                      pb.AppendToEntryResponse),
    "DeleteEntry": ("uu", pb.DeleteEntryRequest,
                    pb.DeleteEntryResponse),
    "AtomicRenameEntry": ("uu", pb.AtomicRenameEntryRequest,
                          pb.AtomicRenameEntryResponse),
    "LookupVolume": ("uu", pb.LookupVolumeRequest,
                     pb.LookupVolumeResponse),
    "CollectionList": ("uu", pb.CollectionListRequest,
                       pb.CollectionListResponse),
    "Statistics": ("uu", pb.StatisticsRequest, pb.StatisticsResponse),
    "Ping": ("uu", pb.PingRequest, pb.PingResponse),
    "GetFilerConfiguration": ("uu", pb.GetFilerConfigurationRequest,
                              pb.GetFilerConfigurationResponse),
    "TraverseBfsMetadata": ("us", pb.TraverseBfsMetadataRequest,
                            pb.TraverseBfsMetadataResponse),
    "SubscribeMetadata": ("us", pb.SubscribeMetadataRequest,
                          pb.SubscribeMetadataResponse),
    "SubscribeLocalMetadata": ("us", pb.SubscribeMetadataRequest,
                               pb.SubscribeMetadataResponse),
    "KvGet": ("uu", pb.KvGetRequest, pb.KvGetResponse),
    "KvPut": ("uu", pb.KvPutRequest, pb.KvPutResponse),
    "DistributedLock": ("uu", pb.LockRequest, pb.LockResponse),
    "DistributedUnlock": ("uu", pb.UnlockRequest, pb.UnlockResponse),
    "FindLockOwner": ("uu", pb.FindLockOwnerRequest,
                      pb.FindLockOwnerResponse),
}

# reserved namespace for KvGet/KvPut pairs (the reference routes them
# into the filer store's KV tables; our stores are path-keyed, so KV
# lives under a dot-directory HTTP listings naturally skip)
KV_DIR = "/.kv"

# inline Entry.content (filer.proto Entry.content=9) round-trips via
# extended[] — our Entry model is chunk-based; content-carrying
# entries are small metadata records (mount hardlinks etc.)
CONTENT_XATTR = "__grpc_content__"


def _join(directory: str, name: str) -> str:
    return (directory.rstrip("/") or "") + "/" + name


def entry_to_pb(e) -> pb.Entry:
    """Entry (filer/entry.py) -> filer_pb.Entry."""
    out = pb.Entry(name=e.name, is_directory=e.is_directory)
    a = e.attributes
    out.attributes.file_size = e.total_size()
    out.attributes.mtime = int(a.mtime)
    out.attributes.file_mode = a.mode | (
        0o20000000000 if e.is_directory else 0)  # os.ModeDir bit
    out.attributes.uid = a.uid
    out.attributes.gid = a.gid
    out.attributes.crtime = int(a.crtime)
    out.attributes.mime = a.mime
    out.attributes.ttl_sec = a.ttl_sec
    out.attributes.symlink_target = a.symlink_target
    for c in e.chunks:
        pc = out.chunks.add(file_id=c.file_id, offset=c.offset,
                            size=c.size, e_tag=c.e_tag,
                            modified_ts_ns=c.mtime_ns)
        try:
            vid, rest = c.file_id.split(",", 1)
            key_cookie = bytes.fromhex(rest)
            pc.fid.volume_id = int(vid)
            pc.fid.file_key = int.from_bytes(key_cookie[:-4], "big")
            pc.fid.cookie = int.from_bytes(key_cookie[-4:], "big")
        except (ValueError, IndexError):
            pass  # non-canonical fid string: file_id=1 still names it
    for k, v in (e.extended or {}).items():
        if k == CONTENT_XATTR:
            out.content = base64.b64decode(v)
        else:
            out.extended[k] = v.encode() if isinstance(v, str) \
                else bytes(v)
    return out


def pb_to_entry(directory: str, pe: pb.Entry):
    """filer_pb.Entry -> Entry at directory/name."""
    from ..filer.entry import Attributes, Entry, FileChunk
    a = pe.attributes
    entry = Entry(
        full_path=_join(directory, pe.name),
        is_directory=pe.is_directory,
        attributes=Attributes(
            mtime=a.mtime or time.time(),
            crtime=a.crtime or time.time(),
            mode=(a.file_mode & 0o7777) or 0o660,
            uid=a.uid, gid=a.gid, mime=a.mime,
            ttl_sec=a.ttl_sec, symlink_target=a.symlink_target),
        chunks=[FileChunk(c.file_id, c.offset, c.size, c.e_tag,
                          c.modified_ts_ns)
                for c in pe.chunks],
        extended={k: v.decode("utf-8", "replace")
                  for k, v in pe.extended.items()})
    if pe.content:
        entry.extended[CONTENT_XATTR] = \
            base64.b64encode(pe.content).decode()
    return entry


def _event_to_pb(ev: dict) -> pb.SubscribeMetadataResponse:
    """Meta-log event dict (filer.py _notify) -> wire event.  Ops map
    onto the reference's old/new-entry convention
    (filer_pb.EventNotification): create = new only, delete = old
    only, update/rename = both."""
    from ..filer.entry import Entry
    resp = pb.SubscribeMetadataResponse(ts_ns=int(ev.get("tsNs", 0)))
    new_e = ev.get("newEntry")
    old_e = ev.get("oldEntry")
    path = (new_e or old_e or {}).get("fullPath", "/")
    resp.directory = path.rsplit("/", 1)[0] or "/"
    if new_e:
        resp.event_notification.new_entry.CopyFrom(
            entry_to_pb(Entry.from_json(new_e)))
        if ev.get("op") == "rename":
            resp.event_notification.new_parent_path = resp.directory
    if old_e:
        resp.event_notification.old_entry.CopyFrom(
            entry_to_pb(Entry.from_json(old_e)))
        resp.event_notification.delete_chunks = \
            ev.get("op") == "delete"
    return resp


class FilerServicer:
    def __init__(self, filer_server):
        self.fs = filer_server

    @property
    def filer(self):
        return self.fs.filer

    # -- entries CRUD --------------------------------------------------

    def LookupDirectoryEntry(self, request, context):
        e = self.filer.find_entry(_join(request.directory,
                                        request.name))
        if e is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{request.name} not found under "
                          f"{request.directory}")
        return pb.LookupDirectoryEntryResponse(entry=entry_to_pb(e))

    def ListEntries(self, request, context):
        """Streaming list with resumable pagination, the reference's
        ListEntries contract (filer_grpc_server.go ListEntries):
        limit=0 means everything."""
        remaining = request.limit or (1 << 62)
        start = request.startFromFileName
        include = request.inclusiveStartFrom
        while remaining > 0:
            page = self.filer.list_directory(
                request.directory, start_file=start,
                include_start=include,
                limit=min(remaining, 1024),
                prefix=request.prefix)
            for e in page:
                yield pb.ListEntriesResponse(entry=entry_to_pb(e))
            if len(page) < min(remaining, 1024):
                return
            remaining -= len(page)
            start, include = page[-1].name, False

    def CreateEntry(self, request, context):
        entry = pb_to_entry(request.directory, request.entry)
        if request.o_excl and \
                self.filer.find_entry(entry.full_path) is not None:
            return pb.CreateEntryResponse(
                error=f"EEXIST: {entry.full_path} already exists")
        self.filer.create_entry(entry)
        return pb.CreateEntryResponse()

    def UpdateEntry(self, request, context):
        entry = pb_to_entry(request.directory, request.entry)
        if self.filer.find_entry(entry.full_path) is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"{entry.full_path} not found")
        self.filer.create_entry(entry, create_parents=False)
        return pb.UpdateEntryResponse()

    def AppendToEntry(self, request, context):
        from ..filer.entry import Entry, FileChunk
        path = _join(request.directory, request.entry_name)
        with self.filer._chunk_lock(path):
            e = self.filer.find_entry(path)
            if e is None:
                e = Entry(full_path=path)
            # reference semantics: chunks land AT the current size,
            # whatever offset the client stamped
            # (filer_grpc_server.go AppendToEntry)
            offset = e.total_size()
            for c in request.chunks:
                e.chunks.append(FileChunk(
                    c.file_id, offset, c.size, c.e_tag,
                    c.modified_ts_ns))
                offset += c.size
            self.filer.create_entry(e)
        return pb.AppendToEntryResponse()

    def DeleteEntry(self, request, context):
        path = _join(request.directory, request.name)
        try:
            self.filer.delete_entry(
                path, recursive=request.is_recursive,
                delete_chunks=request.is_delete_data)
        except IsADirectoryError as e:
            if not request.ignore_recursive_error:
                return pb.DeleteEntryResponse(error=str(e))
        return pb.DeleteEntryResponse()

    def AtomicRenameEntry(self, request, context):
        try:
            self.filer.rename(
                _join(request.old_directory, request.old_name),
                _join(request.new_directory, request.new_name))
        except FileNotFoundError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except FileExistsError as e:
            context.abort(grpc.StatusCode.ALREADY_EXISTS, str(e))
        return pb.AtomicRenameEntryResponse()

    # -- cluster views -------------------------------------------------

    def LookupVolume(self, request, context):
        from .. import operation
        resp = pb.LookupVolumeResponse()
        for vid_s in request.volume_ids:
            try:
                locs = operation.lookup(self.filer.master,
                                        int(vid_s.split(",")[0]))
            except (OSError, LookupError, RuntimeError, ValueError):
                locs = []
            bucket = resp.locations_map[vid_s]
            for loc in locs:
                bucket.locations.add(
                    url=loc.get("url", ""),
                    public_url=loc.get("publicUrl", loc.get("url", "")))
        return resp

    def CollectionList(self, request, context):
        from ..server.httpd import http_json
        resp = pb.CollectionListResponse()
        try:
            vl = http_json("GET",
                           f"{self.filer.master}/dir/status")
        except OSError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        names = set()
        for dc in vl.get("dataCenters", {}).values():
            for rack in dc.get("racks", {}).values():
                for node in rack.get("nodes", []):
                    for v in node.get("volumes", []):
                        names.add(v.get("collection", ""))
                    for e in node.get("ecShards", []):
                        names.add(e.get("collection", ""))
        for n in sorted(n for n in names if n):
            resp.collections.add(name=n)
        return resp

    def Statistics(self, request, context):
        from ..server.filer_server import cluster_statistics
        try:
            body = cluster_statistics(self.filer.master,
                                      request.collection)
        except OSError as e:
            context.abort(grpc.StatusCode.UNAVAILABLE, str(e))
        return pb.StatisticsResponse(
            total_size=body.get("totalSize", 0),
            used_size=body.get("usedSize", 0),
            file_count=body.get("fileCount", 0))

    def Ping(self, request, context):
        now = time.time_ns()
        return pb.PingResponse(start_time_ns=now, remote_time_ns=now,
                               stop_time_ns=time.time_ns())

    def GetFilerConfiguration(self, request, context):
        from .. import __version__
        return pb.GetFilerConfigurationResponse(
            masters=[self.filer.master],
            replication=self.filer.replication,
            collection=self.filer.collection,
            max_mb=4,
            version=__version__)

    # -- metadata streams ----------------------------------------------

    def TraverseBfsMetadata(self, request, context):
        """BFS over the namespace (filer_grpc_server_traverse_meta.go):
        parents stream before children."""
        excluded = tuple(request.excluded_prefixes)
        q = deque([request.directory or "/"])
        while q and context.is_active():
            d = q.popleft()
            start = ""
            while True:
                page = self.filer.list_directory(d, start_file=start,
                                                 limit=1024)
                for e in page:
                    if excluded and \
                            e.full_path.startswith(excluded):
                        continue
                    yield pb.TraverseBfsMetadataResponse(
                        directory=d, entry=entry_to_pb(e))
                    if e.is_directory:
                        q.append(e.full_path)
                if len(page) < 1024:
                    break
                start = page[-1].name

    def _subscribe_impl(self, request, context):
        """Replay from since_ns out of the meta log, then follow live
        appends (filer_grpc_server_sub_meta.go; the meta log stamps
        strictly-monotonic tsNs, so `> last` resume never skips)."""
        last = request.since_ns
        prefix = request.path_prefix or "/"
        while context.is_active():
            events = self.filer.events_since(last, limit=1000)
            for ev in events:
                last = max(last, int(ev.get("tsNs", 0)))
                path = ((ev.get("newEntry") or ev.get("oldEntry") or
                         {}).get("fullPath", "/"))
                if not path.startswith(prefix):
                    continue
                if request.until_ns and \
                        ev.get("tsNs", 0) > request.until_ns:
                    return
                yield _event_to_pb(ev)
            if request.until_ns and last >= request.until_ns:
                return
            if not events:
                time.sleep(0.1)

    def SubscribeMetadata(self, request, context):
        yield from self._subscribe_impl(request, context)

    def SubscribeLocalMetadata(self, request, context):
        # single-filer deployment: local == aggregated
        yield from self._subscribe_impl(request, context)

    # -- KV ------------------------------------------------------------

    def _kv_path(self, key: bytes) -> str:
        return f"{KV_DIR}/{base64.urlsafe_b64encode(key).decode()}"

    def KvGet(self, request, context):
        e = self.filer.store.find_entry(self._kv_path(request.key))
        if e is None:
            return pb.KvGetResponse()  # empty value = not found
        return pb.KvGetResponse(value=base64.b64decode(
            e.extended.get(CONTENT_XATTR, "")))

    def KvPut(self, request, context):
        from ..filer.entry import Entry
        path = self._kv_path(request.key)
        if not request.value:
            self.filer.store.delete_entry(path)  # empty = delete
            self._kv_invalidate(path)
            return pb.KvPutResponse()
        e = Entry(full_path=path, extended={
            CONTENT_XATTR: base64.b64encode(request.value).decode()})
        self.filer.store.insert_entry(e)
        self._kv_invalidate(path)
        return pb.KvPutResponse()

    def _kv_invalidate(self, path: str) -> None:
        """KV mutations go straight to the store (no metadata event —
        reference KV semantics); the filer metadata cache could have
        cached the entry (or its absence) via an HTTP find/list over
        the KV dir, so invalidate it explicitly."""
        mc = self.filer.meta_cache
        if mc is not None:
            mc.invalidate(path)

    # -- distributed locks (lock ring) ---------------------------------

    def DistributedLock(self, request, context):
        lm = self.fs.lock_manager
        target = lm.target_server(request.name)
        if target and target != self.fs._ring_self:
            return pb.LockResponse(lock_host_moved_to=target)
        r = lm.acquire(request.name, request.owner,
                       float(request.seconds_to_lock or 10),
                       request.renew_token)
        if isinstance(r, str):
            return pb.LockResponse(lock_owner=r,
                                   error=f"locked by {r}")
        token, _expires = r
        return pb.LockResponse(renew_token=token)

    def DistributedUnlock(self, request, context):
        lm = self.fs.lock_manager
        target = lm.target_server(request.name)
        if target and target != self.fs._ring_self:
            return pb.UnlockResponse(moved_to=target)
        if not lm.release(request.name, request.renew_token):
            return pb.UnlockResponse(error="renew token mismatch")
        return pb.UnlockResponse()

    def FindLockOwner(self, request, context):
        owner = self.fs.lock_manager.find_owner(request.name)
        if owner is None:
            context.abort(grpc.StatusCode.NOT_FOUND,
                          f"lock {request.name} not held")
        return pb.FindLockOwnerResponse(owner=owner)


def start_filer_grpc(filer_server, host: str = "127.0.0.1",
                     port: int = 0):
    handler = make_service_handler(SERVICE, METHODS,
                                   FilerServicer(filer_server),
                                   role="filer")
    return serve([handler], host, port)


def filer_stub(channel, peer: str = "") -> Stub:
    """`peer` (the dialed host:port) opts every call into that
    peer's circuit breaker (util/retry)."""
    return Stub(channel, SERVICE, METHODS, peer=peer)
