"""EC encode/rebuild file pipeline
(weed/storage/erasure_coding/ec_encoder.go).

`.dat` -> `.ec00..ecNN`: the volume stream is striped into rows of
data_shards blocks (1GB rows first, then 1MB rows for the tail, zero-
padded past EOF), parity blocks are computed per row, and each block is
appended to its shard file.  The file geometry is identical to the
reference for ANY batch size that divides the block size — the Go path
encodes in 256KB batches (ec_encoder.go:61), the TPU path uses 64MB
batches to amortize device dispatch; outputs are byte-identical.

Rebuild regenerates missing shards from >= data_shards survivors in
1MB steps (ec_encoder.go:323 rebuildEcFiles).
"""

from __future__ import annotations

import os

import numpy as np

from .. import idx as idxmod
from .. import types
from ..volume_info import (EcShardConfig, VolumeInfo,
                           maybe_load_volume_info, save_volume_info)
from .ec_context import (ECContext, LARGE_BLOCK_SIZE, SMALL_BLOCK_SIZE,
                         to_ext)  # noqa: F401  (re-exported)


# --- .ecx generation ----------------------------------------------------

def write_sorted_file_from_idx(base_file_name: str, ext: str = ".ecx"
                               ) -> None:
    """Generate the sorted needle index (ec_encoder.go:31
    WriteSortedFileFromIdx): replay .idx with memdb semantics — a delete
    REMOVES the key entirely (readNeedleMap ec_encoder.go:387-393 routes
    tombstones through MemDb.Delete), so pre-encode deletes never appear
    in .ecx — then write live entries ascending by key."""
    live: dict[int, tuple[int, int]] = {}
    with open(base_file_name + ".idx", "rb") as f:
        for key, off, size in idxmod.walk_index(f.read()):
            if off != 0 and not types.size_is_deleted(size):
                live[key] = (off, size)
            else:
                live.pop(key, None)
    entries = sorted(live.items())
    with open(base_file_name + ext, "wb") as out:
        if entries:
            keys = [k for k, _ in entries]
            offs = [o for _, (o, _) in entries]
            sizes = [s for _, (_, s) in entries]
            out.write(idxmod.pack_index(keys, offs, sizes))


# --- encode -------------------------------------------------------------

def write_ec_files(base_file_name: str, ctx: ECContext | None = None
                   ) -> None:
    """ec_encoder.go:61 WriteEcFiles / :67 WriteEcFilesWithContext."""
    ctx = ctx or ECContext()
    _generate_ec_files(base_file_name, ctx)


def _generate_ec_files(base_file_name: str, ctx: ECContext) -> None:
    dat_path = base_file_name + ".dat"
    dat_size = os.path.getsize(dat_path)
    codec = ctx.create_codec()
    outputs = [open(base_file_name + ctx.to_ext(i), "wb")
               for i in range(ctx.total)]
    try:
        with open(dat_path, "rb") as dat:
            _encode_dat_file(dat, dat_size, codec, outputs, ctx)
    finally:
        for f in outputs:
            f.close()


def _encode_dat_file(dat, dat_size: int, codec, outputs, ctx: ECContext
                     ) -> None:
    """ec_encoder.go:280 encodeDatFile: large rows then small rows."""
    large_row = LARGE_BLOCK_SIZE * ctx.data_shards
    small_row = SMALL_BLOCK_SIZE * ctx.data_shards
    remaining = dat_size
    processed = 0
    while remaining >= large_row:
        _encode_rows(dat, processed, LARGE_BLOCK_SIZE, codec, outputs, ctx)
        remaining -= large_row
        processed += large_row
    while remaining > 0:
        _encode_rows(dat, processed, SMALL_BLOCK_SIZE, codec, outputs, ctx)
        remaining -= small_row
        processed += small_row


def _encode_rows(dat, row_start: int, block_size: int, codec, outputs,
                 ctx: ECContext) -> None:
    """Encode one row (data_shards x block_size) in batches
    (ec_encoder.go:202 encodeData / :248 encodeDataOneBatch).  Reads past
    EOF zero-pad (ec_encoder.go:258-262)."""
    batch = ctx.batch_size(block_size)
    d = ctx.data_shards
    buf = np.zeros((ctx.total, batch), dtype=np.uint8)
    for b0 in range(0, block_size, batch):
        buf[:] = 0
        for i in range(d):
            dat.seek(row_start + i * block_size + b0)
            chunk = dat.read(batch)
            if chunk:
                buf[i, :len(chunk)] = np.frombuffer(chunk, dtype=np.uint8)
        parity = codec.parity(buf[:d])
        buf[d:] = np.asarray(parity)
        for i in range(ctx.total):
            outputs[i].write(buf[i].tobytes())


# --- rebuild ------------------------------------------------------------

def rebuild_ec_files(base_file_name: str, ctx: ECContext | None = None,
                     additional_dirs: list[str] | None = None
                     ) -> list[int]:
    """ec_encoder.go:74 RebuildEcFiles: recover the scheme from .vif,
    then regenerate missing shard files from survivors.  Returns the
    generated shard ids."""
    if ctx is None:
        vi = maybe_load_volume_info(base_file_name + ".vif")
        if vi is not None and vi.ec_shard_config is not None and \
                vi.ec_shard_config.data_shards:
            ctx = ECContext(vi.ec_shard_config.data_shards,
                            vi.ec_shard_config.parity_shards)
        else:
            ctx = ECContext()
    return _generate_missing_ec_files(
        base_file_name, ctx, additional_dirs or [])


def _find_shard_file(base_file_name: str, ext: str,
                     additional_dirs: list[str]) -> str | None:
    """ec_encoder.go:131 findShardFile: primary path, then extra dirs."""
    primary = base_file_name + ext
    if os.path.exists(primary):
        return primary
    base = os.path.basename(base_file_name)
    for d in additional_dirs:
        cand = os.path.join(d, base + ext)
        if os.path.exists(cand):
            return cand
    return None


def _generate_missing_ec_files(base_file_name: str, ctx: ECContext,
                               additional_dirs: list[str]) -> list[int]:
    """Two-pass discover-then-create (ec_encoder.go:146)."""
    present_paths: dict[int, str] = {}
    missing: list[int] = []
    for sid in range(ctx.total):
        p = _find_shard_file(base_file_name, ctx.to_ext(sid),
                             additional_dirs)
        if p is not None:
            present_paths[sid] = p
        else:
            missing.append(sid)
    if len(present_paths) < ctx.data_shards:
        raise ValueError(
            f"not enough shards to rebuild {base_file_name}: found "
            f"{len(present_paths)}, need {ctx.data_shards}, "
            f"missing {missing}")
    if not missing:
        return []
    codec = ctx.create_codec()
    shard_size = max(os.path.getsize(p) for p in present_paths.values())
    inputs = {sid: open(p, "rb") for sid, p in present_paths.items()}
    outputs = {sid: open(base_file_name + ctx.to_ext(sid), "wb")
               for sid in missing}
    present_mask = [sid in present_paths for sid in range(ctx.total)]
    try:
        step = ctx.batch_size(LARGE_BLOCK_SIZE)
        pos = 0
        while pos < shard_size:
            n = min(step, shard_size - pos)
            shards = np.zeros((ctx.total, n), dtype=np.uint8)
            for sid, f in inputs.items():
                f.seek(pos)
                chunk = f.read(n)
                if chunk:
                    shards[sid, :len(chunk)] = np.frombuffer(
                        chunk, dtype=np.uint8)
            rec = codec.reconstruct(shards, present_mask)
            for sid in missing:
                outputs[sid].write(np.asarray(rec[sid]).tobytes())
            pos += n
    finally:
        for f in inputs.values():
            f.close()
        for f in outputs.values():
            f.close()
    return missing


def save_ec_volume_info(base_file_name: str, ctx: ECContext,
                        dat_file_size: int, version: int) -> None:
    """Persist the EC scheme to .vif so rebuild/decode can recover it
    (server/volume_grpc_erasure_coding.go:132)."""
    vi = maybe_load_volume_info(base_file_name + ".vif") or VolumeInfo()
    vi.version = version
    vi.dat_file_size = dat_file_size
    vi.ec_shard_config = EcShardConfig(ctx.data_shards, ctx.parity_shards)
    save_volume_info(base_file_name + ".vif", vi)
